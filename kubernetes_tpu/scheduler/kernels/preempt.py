"""Batched victim-pricing preemption on device.

The serial port (scheduler/preemption.py, ref generic_scheduler.go
selectVictimsOnNode + pickOneNodeForPreemption) walks one candidate node
at a time, cloning NodeInfos and re-running the full predicate oracle per
reprieve step. This module re-prices the same decision as a tensor
program over ALL candidate nodes at once:

  - each candidate node's would-be victims are tensorized into
    priority-band-sorted ``[N, V]`` unit tables (cheapest band first,
    PDB-violating units masked to a LAST-RESORT band after every clean
    unit, ties broken youngest-first then by key — the eviction order);
  - "does the preemptor fit after evicting the <=k cheapest units" is a
    masked prefix-sum scan over the sorted band axis (cumsum of freed
    resources + freed pod slots vs the preemptor's request);
  - a whole PodGroup is priced as a SINGLE unit: evicting any member
    charges the entire group (top/sum priority, cluster-wide member
    count) while freeing only the group's on-node resources — evicting
    1 of 4 workers buys nothing and the cost table says so;
  - the winner node is the reference's pickOneNodeForPreemption
    tie-break order (fewest PDB violations, lowest top-victim priority,
    lowest priority sum, fewest victims, latest start among the
    top-priority victims, first remaining) expressed as one
    lexicographic argmax over per-node cost vectors.

``price_nodes_reference`` / ``price_domains_reference`` are numpy
mirrors with the same op order and f32 arithmetic — the parity oracles
(tests/test_preempt.py randomized fixtures), in the same role
gang_schedule_reference plays for the gang kernel.

Two deliberate modeling divergences from the serial path, which
``KTPU_PREEMPT_KERNEL=0`` keeps available as the measured control:

  - victim sets are PREFIXES of the band order; the serial reprieve
    loop may carve non-contiguous sets when re-adding a cheap victim
    happens not to break the fit. Prefix pricing is what makes the scan
    O(N·V) tensor work instead of per-node python.
  - the fit check is resources + pod-count (after the same
    pod-independent candidate screen the serial path applies); the
    reprieve loop's full-predicate fit also sees inter-pod affinity.
    A preemptor that still cannot place after its victims terminate
    simply stays pending — the eviction was wasted, not wrong.

``price_domains`` is the whole-gang variant: candidate rows are ICI
topology DOMAINS, the fit threshold is "minMember member-slots across
the domain's nodes", and each unit's value is the member-slot delta its
eviction unlocks on its node (per-node slot curves are concave-free by
construction: freed resources only grow, so the per-node sorted unit
stream has well-defined non-negative increments and a cross-node merge
in band order keeps them additive).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...api import helpers
from ...api.core import Pod
from ...api.scheduling import pod_group_key
from ..nodeinfo import NodeInfo, pod_resource
from ..preemption import filter_pods_with_pdb_violation, _more_important

INT32_MAX = np.int32(2**31 - 1)
INT32_MIN = np.int32(-(2**31))


# ----------------------------------------------------------- host tables

@dataclass
class _Unit:
    """One evictable pricing unit on one node: a singleton pod, or a
    whole PodGroup's on-node members (charged cluster-wide)."""

    key: str                      # deterministic final tie-break
    evict: List[Pod]              # every pod this eviction takes down
    freed: np.ndarray             # [R] resources freed ON THIS NODE
    fcnt: int                     # pod slots freed on this node
    pdb: bool                     # last-resort band (budget exhausted)
    top: int                      # highest victim priority in the unit
    psum: float                   # sum of victim priorities (whole group)
    gcnt: int                     # victims charged (whole group)
    start: str                    # latest start among top-priority victims
    startr: int = 0               # global rank of `start` (filled late)
    is_group: bool = False        # whole-PodGroup unit (never cached)
    #: quantized DRF over-share rank of the unit's tenant (0 at/below
    #: fair share, or when DRF is off) — over-share tenants' units sort
    #: into a cheaper eviction band
    oshare: int = 0


@dataclass
class VictimTables:
    """Everything price_nodes consumes plus the host-side unit metadata
    needed to expand the winner's chosen prefix back into pods."""

    names: List[str]
    units: List[List[_Unit]]
    res_names: List[str]
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)

    def expand(self, row: int, chosen: np.ndarray) -> List[Pod]:
        """Winner row + chosen unit mask -> ordered victim pods (band
        order, whole groups expanded in sorted-key order)."""
        out: List[Pod] = []
        for v, unit in enumerate(self.units[row]):
            if v < len(chosen) and chosen[v]:
                out.extend(sorted(unit.evict,
                                  key=lambda p: p.metadata.key()))
        return out


def _res_columns(need) -> List[str]:
    """cpu/memory always, plus the preemptor's extended scalars — the
    only columns that can gate ITS fit."""
    return ["cpu", "memory"] + sorted(need.scalar_resources)


def _res_row(res, names: Sequence[str]) -> np.ndarray:
    row = np.zeros((len(names),), np.float32)
    for i, n in enumerate(names):
        if n == "cpu":
            row[i] = res.milli_cpu
        elif n == "memory":
            row[i] = res.memory
        else:
            row[i] = res.scalar_resources.get(n, 0)
    return row


def bound_group_index(infos: Dict[str, NodeInfo]) -> Dict[str, List[Pod]]:
    """gkey -> every BOUND member across the cluster: the expansion (and
    cost) of evicting any one of them."""
    out: Dict[str, List[Pod]] = {}
    for ni in infos.values():
        for p in ni.pods:
            gk = pod_group_key(p)
            if gk is not None:
                out.setdefault(gk, []).append(p)
    return out


def _unit_oshare(pods: Sequence[Pod], overshare) -> int:
    """The unit's DRF pricing term: the MAX over-share rank among its
    victims' tenants (a group mixing tenants prices at its most
    over-share member). 0 whenever DRF is off."""
    if not overshare:
        return 0
    from ...tenancy.drf import tenant_of
    return max((overshare.get(tenant_of(p), 0) for p in pods), default=0)


def _node_units(prio: int, ni: NodeInfo, pdbs,
                group_bound: Dict[str, List[Pod]],
                res_names: Sequence[str],
                overshare=None) -> Tuple[List[_Unit], bool]:
    """The node's evictable units in band (eviction) order, plus
    whether the list is CACHEABLE: any gang member among the node's
    potential victims makes it not — both surviving group units (their
    cluster-wide expansion) and groups filtered as off-limits (a remote
    member's priority) depend on state other nodes' generations track."""
    potential = [p for p in ni.pods if helpers.pod_priority(p) < prio]
    if not potential:
        return [], True
    singles: List[Pod] = []
    groups: Dict[str, List[Pod]] = {}
    for p in potential:
        gk = pod_group_key(p)
        if gk is None:
            singles.append(p)
        else:
            groups.setdefault(gk, []).append(p)
    # a group with any member at/above the preemptor's priority is
    # off-limits entirely: its eviction would take down a pod preemption
    # may never touch
    for gk in list(groups):
        members = group_bound.get(gk, groups[gk])
        if any(helpers.pod_priority(m) >= prio for m in members):
            del groups[gk]
    # PDB accounting in the reference's order (most important first,
    # cumulative disruptionsAllowed) over this node's surviving victims
    ordered = sorted(singles + [p for ps in groups.values() for p in ps],
                     key=_more_important)
    violating, _ok = filter_pods_with_pdb_violation(ordered, pdbs)
    viol = {p.metadata.key() for p in violating}
    units: List[_Unit] = []
    for p in singles:
        pr = helpers.pod_priority(p)
        units.append(_Unit(
            key=p.metadata.key(), evict=[p],
            freed=_res_row(pod_resource(p), res_names), fcnt=1,
            pdb=p.metadata.key() in viol, top=pr, psum=float(pr), gcnt=1,
            start=p.status.start_time or "",
            oshare=_unit_oshare([p], overshare)))
    for gk, here in sorted(groups.items()):
        members = group_bound.get(gk, here)
        prios = [helpers.pod_priority(m) for m in members]
        top = max(prios)
        freed = np.zeros((len(res_names),), np.float32)
        for m in here:
            freed += _res_row(pod_resource(m), res_names)
        units.append(_Unit(
            key=f"group:{gk}", evict=list(members), freed=freed,
            fcnt=len(here), pdb=any(m.metadata.key() in viol for m in here),
            top=top, psum=float(sum(prios)), gcnt=len(members),
            start=max((m.status.start_time or "") for m, pr in
                      zip(members, prios) if pr == top),
            is_group=True, oshare=_unit_oshare(members, overshare)))
    return units, not any(pod_group_key(p) is not None for p in potential)


def _rank_and_sort(per_row: List[List[_Unit]]) -> None:
    """Assign global start-time ranks, then sort each row into the
    eviction band order: clean before PDB, most over-share tenant first
    (the DRF pricing term — 0 for every unit when DRF is off, so the
    legacy order is unchanged), cheapest priority first, youngest
    (latest start) first within a band, key as the final deterministic
    tie. This is HOST code consumed by both price_nodes and its numpy
    reference, so kernel-vs-oracle parity holds by construction."""
    starts = sorted({u.start for row in per_row for u in row})
    rank = {s: i for i, s in enumerate(starts)}
    for row in per_row:
        for u in row:
            u.startr = rank[u.start]
        row.sort(key=lambda u: (u.pdb, -u.oshare, u.top, -u.startr, u.key))


def _bucket_pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def build_victim_tables(pod: Pod,
                        candidates: Sequence[Tuple[str, NodeInfo]],
                        infos: Dict[str, NodeInfo], pdbs,
                        unit_cache: Optional[dict] = None,
                        overshare: Optional[Dict[str, int]] = None
                        ) -> Optional[VictimTables]:
    """Single-preemptor tables: one row per candidate node.

    `unit_cache` amortizes the host tensorize across a preemption storm:
    per-node unit lists are keyed by (node, NodeInfo.generation,
    preemptor priority) — generations bump on every pod add/remove, so
    an eviction invalidates exactly its node. Nodes carrying GROUP units
    are never cached (a sibling eviction on another node changes their
    cluster-wide expansion without touching this node's generation).
    Callers must serialize access (the shell holds _algo_lock)."""
    need = pod_resource(pod)
    res_names = _res_columns(need)
    prio = helpers.pod_priority(pod)
    group_bound = bound_group_index(infos)
    names: List[str] = []
    rows: List[List[_Unit]] = []
    free0_rows: List[np.ndarray] = []
    cfree0: List[float] = []
    res_key = tuple(res_names)
    # PDB budgets are not captured by node generations: fingerprint them
    # into the key so a DisruptionController update invalidates wholesale
    pdb_key = tuple(sorted(
        (p.metadata.key(), p.status.disruptions_allowed) for p in pdbs))
    # cached unit lists bake the DRF pricing term in: fingerprint the
    # over-share ranks so a share shift invalidates rather than reuses
    os_key = tuple(sorted(overshare.items())) if overshare else ()
    for name, ni in candidates:
        key = (name, ni.generation, prio, res_key, pdb_key, os_key)
        units = unit_cache.get(key) if unit_cache is not None else None
        if units is None:
            units, cacheable = _node_units(prio, ni, pdbs, group_bound,
                                           res_names, overshare=overshare)
            # gang members key CLUSTER-WIDE state: a sibling binding (or
            # a remote member's priority putting its group off-limits)
            # changes this node's units without touching this node's
            # generation — any gang member among the potential victims
            # makes the list uncacheable, even when no group unit
            # survived the off-limits filter
            if unit_cache is not None and cacheable:
                if len(unit_cache) > 8192:
                    unit_cache.clear()
                unit_cache[key] = units
        if not units:
            continue
        names.append(name)
        rows.append(units)
        free0_rows.append(_res_row(ni.allocatable, res_names)
                          - _res_row(ni.requested, res_names))
        cfree0.append(float(ni.allocatable.allowed_pod_number
                            - len(ni.pods)))
    if not names:
        return None
    _rank_and_sort(rows)
    N = _bucket_pow2(len(names))
    V = _bucket_pow2(max(len(r) for r in rows))
    R = len(res_names)
    t = VictimTables(names=names, units=rows, res_names=res_names)
    a = t.arrays
    a["free0"] = np.zeros((N, R), np.float32)
    a["cfree0"] = np.zeros((N,), np.float32)
    a["need"] = _res_row(need, res_names)
    a["need_cnt"] = np.float32(1.0)
    a["freed"] = np.zeros((N, V, R), np.float32)
    a["fcnt"] = np.zeros((N, V), np.float32)
    a["valid"] = np.zeros((N, V), bool)
    a["pdb"] = np.zeros((N, V), bool)
    a["top"] = np.full((N, V), INT32_MIN, np.int32)
    a["psum"] = np.zeros((N, V), np.float32)
    a["gcnt"] = np.zeros((N, V), np.int32)
    a["startr"] = np.full((N, V), -1, np.int32)
    a["row_valid"] = np.zeros((N,), bool)
    for i, units in enumerate(rows):
        a["free0"][i] = free0_rows[i]
        a["cfree0"][i] = cfree0[i]
        a["row_valid"][i] = True
        for v, u in enumerate(units):
            a["freed"][i, v] = u.freed
            a["fcnt"][i, v] = u.fcnt
            a["valid"][i, v] = True
            a["pdb"][i, v] = u.pdb
            a["top"][i, v] = u.top
            a["psum"][i, v] = u.psum
            a["gcnt"][i, v] = u.gcnt
            a["startr"][i, v] = u.startr
    return t


# ---------------------------------------------------------------- kernels

def _lexi_winner(feasible, crits):
    """Lexicographic argmin: narrow the feasible mask criterion by
    criterion (each `crits` entry is minimized; negate to maximize),
    then take the FIRST remaining row — exactly
    pickOneNodeForPreemption's narrowing loop as masked reductions."""
    m = feasible
    for vals in crits:
        if vals.dtype == jnp.float32:
            big = jnp.float32(np.inf)
        else:
            big = jnp.asarray(INT32_MAX, vals.dtype)
        best = jnp.min(jnp.where(m, vals, big))
        m = m & (vals == best)
    return jnp.where(m.any(), jnp.argmax(m), -1).astype(jnp.int32)


def _prefix_costs(chosen, pdb, top, psum, gcnt, startr):
    """Per-row cost vector of the chosen victim prefix."""
    nviol = (chosen & pdb).sum(axis=1).astype(jnp.int32)
    topv = jnp.max(jnp.where(chosen, top, INT32_MIN), axis=1)
    psumv = jnp.sum(jnp.where(chosen, psum, 0.0), axis=1)
    cntv = jnp.sum(jnp.where(chosen, gcnt, 0), axis=1).astype(jnp.int32)
    startv = jnp.max(jnp.where(chosen & (top == topv[:, None]), startr, -1),
                     axis=1).astype(jnp.int32)
    return nviol, topv, psumv, cntv, startv


@jax.jit
def price_nodes(free0, cfree0, need, need_cnt, freed, fcnt, valid, pdb,
                top, psum, gcnt, startr, row_valid):
    """[N, V] single-preemptor pricing. Returns (winner row or -1,
    chosen [N, V], k [N] victims-unit count, nviol [N])."""
    V = valid.shape[1]
    cumfreed = jnp.cumsum(freed, axis=1)
    cumcnt = jnp.cumsum(fcnt, axis=1)
    fit0 = (free0 >= need).all(axis=1) & (cfree0 >= need_cnt)
    fitk = ((free0[:, None, :] + cumfreed) >= need).all(axis=2) \
        & ((cfree0[:, None] + cumcnt) >= need_cnt)
    elig = fitk & valid
    # first fitting prefix; a node the preemptor ALREADY fits is not a
    # preemption candidate (scheduling should have placed it — the
    # serial path's everything-reprieved None)
    kidx = jnp.argmax(elig, axis=1)
    feasible = elig.any(axis=1) & ~fit0 & row_valid
    chosen = valid & (jnp.arange(V)[None, :] <= kidx[:, None]) \
        & feasible[:, None]
    nviol, topv, psumv, cntv, startv = _prefix_costs(
        chosen, pdb, top, psum, gcnt, startr)
    winner = _lexi_winner(feasible, (nviol, topv, psumv, cntv, -startv))
    return winner, chosen, (kidx + 1).astype(jnp.int32), nviol


def price_nodes_reference(a: Dict[str, np.ndarray]):
    """Numpy mirror of price_nodes — same op order, f32 throughout."""
    free0, cfree0 = a["free0"], a["cfree0"]
    need, need_cnt = a["need"], a["need_cnt"]
    freed, fcnt, valid = a["freed"], a["fcnt"], a["valid"]
    pdb, top, psum = a["pdb"], a["top"], a["psum"]
    gcnt, startr, row_valid = a["gcnt"], a["startr"], a["row_valid"]
    N, V = valid.shape
    cumfreed = np.cumsum(freed, axis=1, dtype=np.float32)
    cumcnt = np.cumsum(fcnt, axis=1, dtype=np.float32)
    fit0 = (free0 >= need).all(axis=1) & (cfree0 >= need_cnt)
    fitk = ((free0[:, None, :] + cumfreed) >= need).all(axis=2) \
        & ((cfree0[:, None] + cumcnt) >= need_cnt)
    elig = fitk & valid
    kidx = np.argmax(elig, axis=1)
    feasible = elig.any(axis=1) & ~fit0 & row_valid
    chosen = valid & (np.arange(V)[None, :] <= kidx[:, None]) \
        & feasible[:, None]
    nviol = (chosen & pdb).sum(axis=1).astype(np.int32)
    topv = np.max(np.where(chosen, top, INT32_MIN), axis=1)
    psumv = np.sum(np.where(chosen, psum, np.float32(0.0)), axis=1,
                   dtype=np.float32)
    cntv = np.sum(np.where(chosen, gcnt, 0), axis=1).astype(np.int32)
    startv = np.max(np.where(chosen & (top == topv[:, None]), startr, -1),
                    axis=1).astype(np.int32)
    m = feasible.copy()
    for vals in (nviol, topv, psumv, cntv, -startv):
        big = np.float32(np.inf) if vals.dtype == np.float32 \
            else np.array(INT32_MAX, vals.dtype)
        if not m.any():
            break
        best = np.min(np.where(m, vals, big))
        m = m & (vals == best)
    winner = np.int32(np.argmax(m)) if m.any() else np.int32(-1)
    return winner, chosen, (kidx + 1).astype(np.int32), nviol


# ------------------------------------------------- whole-gang (domains)

@dataclass
class DomainTables:
    """price_domains input + metadata: one row per ICI domain, units
    merged across the domain's nodes in band order; per-node slot
    curves for the post-winner member spread."""

    domains: List[str]
    #: domain -> [(node name, slot curve [len(units)+1])]
    nodes: Dict[str, List[Tuple[str, np.ndarray]]]
    #: per-domain merged unit stream [(unit, node name, per-node j)]
    units: List[List[Tuple[_Unit, str, int]]]
    res_names: List[str]
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)

    def expand(self, row: int, chosen: np.ndarray) -> List[Pod]:
        out: List[Pod] = []
        for v, (unit, _n, _j) in enumerate(self.units[row]):
            if v < len(chosen) and chosen[v]:
                out.extend(sorted(unit.evict,
                                  key=lambda p: p.metadata.key()))
        return out

    def node_slots(self, row: int, chosen: np.ndarray
                   ) -> List[Tuple[str, int]]:
        """Member slots per node of the winner domain AFTER the chosen
        evictions, in sorted node order — the nomination spread."""
        evicted: Dict[str, int] = {}
        for v, (_u, node, j) in enumerate(self.units[row]):
            if v < len(chosen) and chosen[v]:
                evicted[node] = max(evicted.get(node, 0), j + 1)
        out = []
        for node, curve in self.nodes[self.domains[row]]:
            out.append((node, int(curve[evicted.get(node, 0)])))
        return out


def _slot_curve(free0: np.ndarray, cfree0: float, units: List[_Unit],
                q: np.ndarray, qmask: np.ndarray) -> np.ndarray:
    """[len(units)+1] member-slots on one node after evicting the first
    j units: min over requested resources of floor(free / q), capped by
    freed pod-count slots; monotone non-decreasing in j."""
    curves = np.zeros((len(units) + 1,), np.int64)
    free = free0.astype(np.float32).copy()
    cfree = np.float32(cfree0)
    for j in range(len(units) + 1):
        if j > 0:
            free = free + units[j - 1].freed
            cfree = cfree + np.float32(units[j - 1].fcnt)
        per_res = np.where(qmask, np.floor(free / np.maximum(q, 1e-9)),
                           np.float32(np.inf))
        slots = min(float(per_res.min()), float(np.floor(cfree)))
        curves[j] = max(0, int(slots))
    # eviction only frees capacity; enforce monotonicity against any
    # f32 floor jitter so merged per-domain deltas stay non-negative
    np.maximum.accumulate(curves, out=curves)
    return curves


def build_domain_tables(members: Sequence[Pod],
                        candidates: Sequence[Tuple[str, NodeInfo, str]],
                        infos: Dict[str, NodeInfo], pdbs,
                        min_member: int,
                        overshare: Optional[Dict[str, int]] = None
                        ) -> Optional[DomainTables]:
    """Whole-gang tables: `candidates` are (node, info, domain value)
    triples of screen-passing nodes carrying the gang's topology label.
    The member request is the elementwise MAX over members (a slot that
    holds the largest member holds any member), the fit threshold
    `min_member` slots inside ONE domain."""
    if not members or not candidates:
        return None
    need = pod_resource(members[0]).clone()
    for m in members[1:]:
        r = pod_resource(m)
        need.milli_cpu = max(need.milli_cpu, r.milli_cpu)
        need.memory = max(need.memory, r.memory)
        for k, v in r.scalar_resources.items():
            need.scalar_resources[k] = max(need.scalar_resources.get(k, 0),
                                           v)
    res_names = _res_columns(need)
    q = _res_row(need, res_names)
    qmask = q > 0
    # victims must sit strictly below EVERY member's priority
    prio = min(helpers.pod_priority(m) for m in members)
    group_bound = bound_group_index(infos)
    gkey = pod_group_key(members[0])
    per_dom: Dict[str, List[Tuple[str, NodeInfo]]] = {}
    for name, ni, dom in candidates:
        per_dom.setdefault(dom, []).append((name, ni))
    domains = sorted(per_dom)
    all_rows: List[List[_Unit]] = []
    node_units: Dict[str, List[_Unit]] = {}
    for dom in domains:
        for name, ni in sorted(per_dom[dom]):
            units, _cacheable = _node_units(prio, ni, pdbs, group_bound,
                                            res_names, overshare=overshare)
            # the preemptor gang itself may already hold bound members
            # (a partially-recovered slice): never price them as victims
            if gkey is not None:
                units = [u for u in units if u.key != f"group:{gkey}"]
            node_units[name] = units
            all_rows.append(units)
    _rank_and_sort(all_rows)
    t = DomainTables(domains=domains, nodes={}, units=[],
                     res_names=res_names)
    base: List[float] = []
    merged_rows: List[List[Tuple[_Unit, str, int]]] = []
    for dom in domains:
        slots0 = 0.0
        merged: List[Tuple[_Unit, str, int]] = []
        t.nodes[dom] = []
        for name, ni in sorted(per_dom[dom]):
            units = node_units[name]
            curve = _slot_curve(
                _res_row(ni.allocatable, res_names)
                - _res_row(ni.requested, res_names),
                float(ni.allocatable.allowed_pod_number - len(ni.pods)),
                units, q, qmask)
            t.nodes[dom].append((name, curve))
            slots0 += float(curve[0])
            for j, u in enumerate(units):
                merged.append((u, name, j))
        # cross-node merge in the shared band order; per-node unit order
        # is preserved (same sort key), so slot deltas stay additive
        merged.sort(key=lambda e: (e[0].pdb, -e[0].oshare, e[0].top,
                                   -e[0].startr, e[0].key, e[1]))
        merged_rows.append(merged)
        base.append(slots0)
    D = _bucket_pow2(len(domains))
    U = _bucket_pow2(max((len(m) for m in merged_rows), default=1))
    t.units = merged_rows
    a = t.arrays
    a["base"] = np.zeros((D,), np.float32)
    a["need"] = np.float32(min_member)
    a["dslots"] = np.zeros((D, U), np.float32)
    a["valid"] = np.zeros((D, U), bool)
    a["pdb"] = np.zeros((D, U), bool)
    a["top"] = np.full((D, U), INT32_MIN, np.int32)
    a["psum"] = np.zeros((D, U), np.float32)
    a["gcnt"] = np.zeros((D, U), np.int32)
    a["startr"] = np.full((D, U), -1, np.int32)
    a["row_valid"] = np.zeros((D,), bool)
    for i, dom in enumerate(domains):
        a["base"][i] = base[i]
        a["row_valid"][i] = True
        curves = dict(t.nodes[dom])
        for v, (u, name, j) in enumerate(merged_rows[i]):
            curve = curves[name]
            a["dslots"][i, v] = float(curve[j + 1] - curve[j])
            a["valid"][i, v] = True
            a["pdb"][i, v] = u.pdb
            a["top"][i, v] = u.top
            a["psum"][i, v] = u.psum
            a["gcnt"][i, v] = u.gcnt
            a["startr"][i, v] = u.startr
    return t


@jax.jit
def price_domains(base, need, dslots, valid, pdb, top, psum, gcnt,
                  startr, row_valid):
    """[D, U] whole-gang pricing: fit = minMember member-slots in one
    domain. k=0 (no eviction) is allowed — a domain already holding the
    slots wins for free. Returns (winner row or -1, chosen [D, U],
    nviol [D])."""
    U = valid.shape[1]
    cums = base[:, None] + jnp.cumsum(jnp.where(valid, dslots, 0.0),
                                      axis=1)
    fit0 = base >= need
    fitk = (cums >= need) & valid
    kidx = jnp.argmax(fitk, axis=1)
    feasible = (fitk.any(axis=1) | fit0) & row_valid
    chosen = valid & (jnp.arange(U)[None, :] <= kidx[:, None]) \
        & (~fit0)[:, None] & feasible[:, None]
    nviol, topv, psumv, cntv, startv = _prefix_costs(
        chosen, pdb, top, psum, gcnt, startr)
    winner = _lexi_winner(feasible, (nviol, topv, psumv, cntv, -startv))
    return winner, chosen, nviol


def price_domains_reference(a: Dict[str, np.ndarray]):
    """Numpy mirror of price_domains."""
    base, need = a["base"], a["need"]
    dslots, valid = a["dslots"], a["valid"]
    pdb, top, psum = a["pdb"], a["top"], a["psum"]
    gcnt, startr, row_valid = a["gcnt"], a["startr"], a["row_valid"]
    D, U = valid.shape
    cums = base[:, None] + np.cumsum(
        np.where(valid, dslots, np.float32(0.0)), axis=1, dtype=np.float32)
    fit0 = base >= need
    fitk = (cums >= need) & valid
    kidx = np.argmax(fitk, axis=1)
    feasible = (fitk.any(axis=1) | fit0) & row_valid
    chosen = valid & (np.arange(U)[None, :] <= kidx[:, None]) \
        & (~fit0)[:, None] & feasible[:, None]
    nviol = (chosen & pdb).sum(axis=1).astype(np.int32)
    topv = np.max(np.where(chosen, top, INT32_MIN), axis=1)
    psumv = np.sum(np.where(chosen, psum, np.float32(0.0)), axis=1,
                   dtype=np.float32)
    cntv = np.sum(np.where(chosen, gcnt, 0), axis=1).astype(np.int32)
    startv = np.max(np.where(chosen & (top == topv[:, None]), startr, -1),
                    axis=1).astype(np.int32)
    m = feasible.copy()
    for vals in (nviol, topv, psumv, cntv, -startv):
        big = np.float32(np.inf) if vals.dtype == np.float32 \
            else np.array(INT32_MAX, vals.dtype)
        if not m.any():
            break
        best = np.min(np.where(m, vals, big))
        m = m & (vals == best)
    winner = np.int32(np.argmax(m)) if m.any() else np.int32(-1)
    return winner, chosen, nviol
