"""All-or-nothing gang assignment on device.

Extends the batched Filter+Score+Assign kernel (batch.py schedule_batch)
with the gang-scheduling contract: a PodGroup's members either ALL place —
each against the running usage, all inside one ICI topology domain — or
NONE do. Placing 3 of 4 workers of a v4-32 slice wedges the slice and
deadlocks against other partial gangs, so partial placement is strictly
worse than no placement.

Layout: the batch's placement units (gangs, and every singleton as a gang
of one) are FLATTENED into one member-entry stream, so the scan length is
O(total members) regardless of gang sizes — a 512-member slice costs the
same HLO as 512 singletons, where a per-gang scan with the max gang size
unrolled in its step would blow up compilation:

    pod_idx [T] int32   pod-axis index of the entry (-1 = padding)
    start   [T] bool    first entry of its gang (opens a trial window)
    end     [T] bool    last entry of its gang (commit-or-rollback point)
    gang_id [T] int32   unit id, for the post-scan all-or-nothing mask
    dom_idx [T] int32   row into dom_tab (-1 = no topology constraint)
    pin_dom [T] int32   pre-pinned domain id (-1 = free): a gang whose
                        EARLIER batches already reserved in a domain seeds
                        the carry with it, so stragglers can only join
                        that slice
    dom_tab [K, N] int32  node row -> topology-domain id (-1 = label absent)

The scan carry holds TWO usage states: `committed` (last gang boundary)
and `trial` (running placements of the open gang). A gang start copies
committed into trial; each member places greedily against trial exactly
like schedule_batch's step (same feasibility, same resource scores, same
(row, seq) tie-break hash — a singleton-only batch is bit-identical to
schedule_batch modulo the spread/topology in-scan extras, which gang
batches do not carry); the gang's end either folds trial into committed or
drops it. The first placed member of a topology-constrained gang pins the
gang's domain; every later member's mask is restricted to that domain.

Members that individually placed inside a gang that later failed are
masked to -1 AFTER the scan via the per-gang ok vector — the usage they
touched only ever lived in the discarded trial, so no rollback scatter is
needed.

The same isolation is what makes gang batches CHAINABLE in the pipelined
drain (core.schedule_launch): the returned usage holds exactly the
committed gangs' placements — every one of which the commit path assumes
into the cache (bind or permit-gate reservation) — so a successor batch
may take it as its usage input before the host commit lands, with losses
surfacing through the ordinary phantom/epoch machinery.

`gang_schedule_reference` is the host numpy mirror (same op order, f32
throughout) — the parity oracle for tests/test_gang.py's randomized
instances, in the same role predicates.py/priorities.py play for the
plain batch kernel.
"""

from __future__ import annotations

import os as _os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .batch import (COL_CPU, COL_MEM, NEG, _pod_feasible, _pod_score,
                    _soft_raw, _soft_score, _soft_tables, _soft_write,
                    _split_batch, _tie_penalized)

#: entries per scan step (unrolled inside, same op sequence — see
#: batch.py's step grouping); must divide the bucketed T (a power of two)
_STEP_GROUP_GANG = int(_os.environ.get("KTPU_SCAN_GROUP_GANG", "8"))


@jax.jit
def gang_feasible(fits: jnp.ndarray, members: jnp.ndarray) -> jnp.ndarray:
    """[G] bool per-gang static-feasibility reduction over the pods x nodes
    mask (filter_score output): False when some member fits NOWHERE even
    on the empty batch-start snapshot — such a gang can never place, so a
    caller may reject it without paying the assignment scan. A reduction,
    not a placement: True only means "not provably impossible". NOT yet
    routed by core.schedule_launch (the scan subsumes it); kept as the
    building block for a cheap pre-reject / gang-aware autoscaling signal
    (ROADMAP), exercised by tests/test_gang.py.

    members: [G, M] int32 pod rows, -1 padded."""
    ok_pod = fits.any(axis=1)                       # [P]
    valid = members >= 0                            # [G, M]
    ok_m = ok_pod[jnp.maximum(members, 0)]          # [G, M]
    return (ok_m | ~valid).all(axis=1)


@jax.jit
def gang_schedule_batch(node_cfg: dict, usage: dict, pod_batch: dict,
                        gang_tab: dict, nom: dict = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Serial-semantics greedy assignment with per-gang atomicity.

    Same signature/returns as batch.schedule_batch — (assign [P] int32,
    chosen_score [P] f32, new_usage) — so core.BatchScheduler's
    launch/finish plumbing (pack_results, usage adoption) is shared.
    new_usage reflects only COMMITTED gangs. Gang batches never carry the
    in-scan spread/topology tables (the core refuses those combinations
    before routing here), but soft inter-pod credit tables DO ride: the
    per-(term, domain) accumulators live in the trial/committed usage
    dicts, so a rejected gang's credit writes vanish with its trial —
    which is what let core drop the gang SOFT_SCORE_CHUNK sub-batching.
    `nom` is the same phantom nominated-reservation overlay
    schedule_batch takes — a mixed batch's singletons must not steal a
    preemptor's freed space just because a gang member rode along.
    """
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    N = node_cfg["alloc"].shape[0]
    P = per_pod["seq"].shape[0]
    dom_tab = gang_tab["dom_tab"]
    rows = jnp.arange(N, dtype=jnp.int32)
    # capacity-aware per-domain feasibility (gang_tab need/greq present):
    # at each gang boundary, a domain is ELIGIBLE only when its nodes'
    # member-slots against committed usage cover the whole gang — the
    # first placed member can no longer pin the gang into a domain that
    # cannot hold everyone. Absent keys keep the greedy-pin behavior
    # (hand-built fixtures, older callers).
    has_cap = "need" in gang_tab
    soft = _soft_tables(pod_batch)
    has_soft = soft is not None
    if has_soft:
        soft_dom, soft_cnt0, soft_base, soft_w = soft
    if nom is None:
        nom = {"used": jnp.zeros_like(usage["used"]),
               "count": jnp.zeros_like(usage["pod_count"])}

    def one_entry(carry, e):
        committed, trial, gang_dom, gang_ok, gang_elig = carry
        # gang boundary: open a fresh trial window over committed state
        fresh = e["start"]
        trial = {k: jnp.where(fresh, committed[k], trial[k])
                 for k in trial}
        gang_dom = jnp.where(fresh, e["pin_dom"], gang_dom)
        gang_ok = jnp.where(fresh, True, gang_ok)

        valid = e["pod_idx"] >= 0
        i = jnp.maximum(e["pod_idx"], 0)
        pod = {k: v[i] for k, v in per_pod.items()}
        mask = unique_masks[pod["mask_idx"]]
        static = unique_scores[pod["score_idx"]]
        # ICI-domain restriction: members of a constrained gang must land
        # where the topology label EXISTS, and — once the first member
        # pinned a domain — inside that domain
        constrained = e["dom_idx"] >= 0
        dom_row = dom_tab[jnp.maximum(e["dom_idx"], 0)]
        if has_cap:
            # per-node member-slots against COMMITTED usage (f32 floors,
            # mirrored by the oracle), summed per domain; eligibility =
            # the domain holds the whole gang. Applied at the boundary of
            # constrained, un-pinned gangs; when NO domain passes, fall
            # back to the greedy pin so feasibility never regresses.
            greq = e["greq"]
            qmask = greq > 0
            # the nominated phantom overlay counts here exactly like the
            # per-member fit (eff_used below): a domain whose free space
            # is shielded by preemptors' reservations cannot hold this
            # gang. (A gang holding its OWN nominations may see its
            # reserved domain as full — the any-eligible fallback, or an
            # honestly eligible other domain, still places it, and its
            # per-member self-credit applies at fit time.)
            free = node_cfg["alloc"] - (committed["used"] + nom["used"])
            per = jnp.where(
                qmask[None, :],
                jnp.floor(free / jnp.maximum(greq, jnp.float32(1e-9))
                          [None, :]),
                jnp.float32(jnp.inf))
            slots = jnp.minimum(
                per.min(axis=1),
                jnp.floor(node_cfg["max_pods"]
                          - (committed["pod_count"] + nom["count"])))
            slots = jnp.maximum(slots, jnp.float32(0.0))
            ok_node = node_cfg["node_ok"] & node_cfg["valid"] \
                & (dom_row >= 0)
            slots = jnp.where(ok_node, slots, jnp.float32(0.0))
            domcap = jnp.zeros((N,), jnp.float32).at[
                jnp.where(dom_row >= 0, dom_row, N)].add(
                    slots, mode="drop")
            elig = (domcap[jnp.maximum(dom_row, 0)] >= e["need"]) \
                & (dom_row >= 0)
            apply_f = constrained & (e["pin_dom"] < 0) \
                & (e["need"] > 0) & elig.any()
            gang_elig = jnp.where(fresh,
                                  jnp.where(apply_f, elig, True),
                                  gang_elig)
        dmask = jnp.where(constrained,
                          (dom_row >= 0) & ((gang_dom < 0)
                                            | (dom_row == gang_dom))
                          & gang_elig,
                          True)
        # phantom nominated usage shields preemption's freed space, minus
        # the pod's own reservation at its nominated row (batch.py's
        # schedule_batch semantics)
        self_oh = rows == pod.get("nom_row", jnp.int32(-1))
        eff_used = trial["used"] + nom["used"] - \
            jnp.where(self_oh[:, None], pod["req"][None, :], 0.0)
        eff_count = trial["pod_count"] + nom["count"] \
            - self_oh.astype(jnp.float32)
        fits = _pod_feasible(node_cfg, eff_used, eff_count,
                             pod, mask & dmask)
        score = _pod_score(node_cfg, trial["nonzero_used"], pod, static, rw)
        if has_soft:
            # credits read from the TRIAL accumulators: an open gang's
            # earlier members are visible, a rejected gang's never were
            raw = _soft_raw(soft_dom, trial["soft_cnt"], soft_base, pod)
            score = score + jnp.where(
                pod["soft_base_idx"] >= 0,
                _soft_score(raw, fits, soft_w), 0.0)
        masked = jnp.where(fits, score, NEG)
        # identical tie-break to schedule_batch (selectHost rotation)
        best = jnp.argmax(_tie_penalized(masked, rows, pod["seq"])) \
            .astype(jnp.int32)
        ok = fits[best] & pod["active"] & valid
        oh_f = ((rows == best) & ok).astype(jnp.float32)
        new_trial = {
            "used": trial["used"] + oh_f[:, None] * pod["req"][None, :],
            "nonzero_used": trial["nonzero_used"]
            + oh_f[:, None] * pod["nonzero_req"][None, :],
            "pod_count": trial["pod_count"] + oh_f,
        }
        if has_soft:
            new_trial["soft_cnt"] = _soft_write(
                soft_dom, trial["soft_cnt"], pod, best, ok)
        trial = new_trial
        gang_dom = jnp.where(valid & ok & constrained & (gang_dom < 0),
                             dom_row[best], gang_dom)
        # a padding entry never vetoes its (padding) gang
        gang_ok = gang_ok & (ok | ~valid)
        # gang end: fold the trial into committed state, or drop it whole
        closing = e["end"]
        commit = closing & gang_ok
        committed = {k: jnp.where(commit, trial[k], committed[k])
                     for k in committed}
        assign = jnp.where(ok, best, jnp.int32(-1))
        return ((committed, trial, gang_dom, gang_ok, gang_elig),
                (assign, masked[best], gang_ok))

    usage0 = {"used": usage["used"], "nonzero_used": usage["nonzero_used"],
              "pod_count": usage["pod_count"]}
    if has_soft:
        # chained launches seed from the predecessor's committed finals
        sc0 = usage.get("soft_cnt")
        usage0["soft_cnt"] = sc0 if sc0 is not None else soft_cnt0
    carry0 = (usage0, usage0, jnp.int32(-1), jnp.bool_(True),
              jnp.ones((N,), bool))
    entries = {"pod_idx": gang_tab["pod_idx"], "start": gang_tab["start"],
               "end": gang_tab["end"], "dom_idx": gang_tab["entry_dom_idx"],
               "pin_dom": gang_tab["pin_dom"]}
    if has_cap:
        entries["need"] = gang_tab["need"]
        entries["greq"] = gang_tab["greq"]
    T = entries["pod_idx"].shape[0]
    G = min(1 << (max(1, _STEP_GROUP_GANG).bit_length() - 1), T)

    def step(carry, eg):
        outs = []
        for g in range(G):
            e = {k: v[g] for k, v in eg.items()}
            carry, out = one_entry(carry, e)
            outs.append(out)
        return carry, tuple(jnp.stack([o[j] for o in outs])
                            for j in range(3))

    entries_g = {k: v.reshape((T // G, G) + v.shape[1:])
                 for k, v in entries.items()}
    (committed, _, _, _, _), (assign_e, score_e, ok_e) = lax.scan(
        step, carry0, entries_g)
    assign_e = assign_e.reshape(T)
    score_e = score_e.reshape(T)
    ok_e = ok_e.reshape(T)

    # all-or-nothing mask: each gang's verdict is the carry's gang_ok AT
    # ITS END ENTRY; scatter it over the gang's ids, gather per entry
    # (unit ids are entry-stream positions, so T bounds them statically)
    gang_id = gang_tab["gang_id"]
    n_units = T
    end = gang_tab["end"]
    ok_units = jnp.zeros((n_units,), bool).at[
        jnp.where(end, gang_id, n_units)].set(ok_e, mode="drop")
    entry_ok = ok_units[jnp.minimum(gang_id, n_units - 1)]
    assign_e = jnp.where(entry_ok, assign_e, jnp.int32(-1))

    # entry axis -> pod axis
    pod_idx = gang_tab["pod_idx"]
    tgt = jnp.where(pod_idx >= 0, pod_idx, P)
    assign = jnp.full((P,), -1, jnp.int32).at[tgt].set(
        assign_e, mode="drop")
    scores = jnp.full((P,), NEG, jnp.float32).at[tgt].set(
        score_e, mode="drop")
    return assign, scores, committed


# ----------------------------------------------------------------- oracle

def gang_schedule_reference(node_cfg: Dict[str, np.ndarray],
                            usage: Dict[str, np.ndarray],
                            pod_batch: Dict[str, np.ndarray],
                            gang_tab: Dict[str, np.ndarray],
                            nom: Dict[str, np.ndarray] = None
                            ) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Host numpy mirror of gang_schedule_batch — same greedy order, same
    f32 arithmetic, same tie-break — the parity oracle. Deliberately
    written as the obvious nested loop over gangs and members."""
    alloc = np.asarray(node_cfg["alloc"], np.float32)
    max_pods = np.asarray(node_cfg["max_pods"], np.float32)
    node_ok = np.asarray(node_cfg["node_ok"], bool)
    node_valid = np.asarray(node_cfg["valid"], bool)
    mem_pressure = np.asarray(node_cfg["mem_pressure"], bool)
    N = alloc.shape[0]
    P = np.asarray(pod_batch["req"]).shape[0]
    used = np.asarray(usage["used"], np.float32).copy()
    nz = np.asarray(usage["nonzero_used"], np.float32).copy()
    cnt = np.asarray(usage["pod_count"], np.float32).copy()
    reqs = np.asarray(pod_batch["req"], np.float32)
    nzreqs = np.asarray(pod_batch["nonzero_req"], np.float32)
    blocked = np.asarray(pod_batch["mem_pressure_blocked"], bool)
    active = np.asarray(pod_batch["active"], bool)
    seq = np.asarray(pod_batch["seq"], np.int64)
    mask_idx = np.asarray(pod_batch["mask_idx"], np.int64)
    score_idx = np.asarray(pod_batch["score_idx"], np.int64)
    unique_masks = np.asarray(pod_batch["unique_masks"], bool)
    unique_scores = np.asarray(pod_batch["unique_scores"], np.float32)
    rw = np.asarray(pod_batch["resource_weights"], np.float32)
    dom_tab = np.asarray(gang_tab["dom_tab"], np.int32)
    cap_cpu = alloc[:, COL_CPU]
    cap_mem = alloc[:, COL_MEM]
    safe_cpu = np.maximum(cap_cpu, np.float32(1.0))
    safe_mem = np.maximum(cap_mem, np.float32(1.0))
    rows64 = np.arange(N, dtype=np.int64)
    NEG32 = np.float32(NEG)
    if nom is None:
        nom_used = np.zeros_like(used)
        nom_cnt = np.zeros_like(cnt)
    else:
        nom_used = np.asarray(nom["used"], np.float32)
        nom_cnt = np.asarray(nom["count"], np.float32)
    nom_row = np.asarray(pod_batch["nom_row"], np.int64)
    # soft inter-pod credit tables (same trial/commit life as usage)
    has_soft = pod_batch.get("soft_dom") is not None
    if has_soft:
        soft_dom = np.asarray(pod_batch["soft_dom"], np.int64)
        soft_cnt = np.asarray(pod_batch["soft_cnt0"], np.float32).copy()
        soft_base = np.asarray(pod_batch["soft_base"], np.float32)
        soft_bidx = np.asarray(pod_batch["soft_base_idx"], np.int64)
        soft_rt = np.asarray(pod_batch["soft_read_tids"], np.int64)
        soft_rw = np.asarray(pod_batch["soft_read_w"], np.float32)
        soft_wt = np.asarray(pod_batch["soft_write_tids"], np.int64)
        soft_ww = np.asarray(pod_batch["soft_write_w"], np.float32)
        soft_w = np.float32(pod_batch["soft_weight"])

    assign = np.full((P,), -1, np.int32)
    scores = np.full((P,), NEG32, np.float32)

    # regroup the flattened entry stream back into units (keeping each
    # unit's start-entry index for the capacity-feasibility inputs)
    units: list = []
    gid = np.asarray(gang_tab["gang_id"])
    pod_idx = np.asarray(gang_tab["pod_idx"])
    entry_dom = np.asarray(gang_tab["entry_dom_idx"])
    pin_dom = np.asarray(gang_tab["pin_dom"])
    for t in range(len(pod_idx)):
        if gang_tab["start"][t]:
            units.append(([], int(entry_dom[t]), int(pin_dom[t]),
                          int(gid[t]), t))
        units[-1][0].append(int(pod_idx[t]))
    has_cap = "need" in gang_tab
    if has_cap:
        cap_need = np.asarray(gang_tab["need"], np.float32)
        cap_greq = np.asarray(gang_tab["greq"], np.float32)

    for members, dom_idx, pin, _, t_start in units:
        trial_used = used.copy()
        trial_nz = nz.copy()
        trial_cnt = cnt.copy()
        trial_soft = soft_cnt.copy() if has_soft else None
        gang_dom = pin
        gang_ok = True
        placed: list = []
        dom_row = dom_tab[max(dom_idx, 0)]
        gang_elig = np.ones((N,), bool)
        if has_cap and dom_idx >= 0 and pin < 0 \
                and cap_need[t_start] > 0:
            # capacity-aware per-domain feasibility — the kernel's
            # boundary reduction, same f32 op order
            greq = cap_greq[t_start]
            qmask = greq > 0
            free = alloc - (used + nom_used)
            per = np.where(qmask[None, :],
                           np.floor(free / np.maximum(
                               greq, np.float32(1e-9))[None, :]),
                           np.float32(np.inf))
            slots = np.minimum(per.min(axis=1),
                               np.floor(max_pods - (cnt + nom_cnt)))
            slots = np.maximum(slots, np.float32(0.0))
            ok_node = node_ok & node_valid & (dom_row >= 0)
            slots = np.where(ok_node, slots, np.float32(0.0))
            domcap = np.zeros((N,), np.float32)
            np.add.at(domcap, dom_row[dom_row >= 0],
                      slots[dom_row >= 0])
            elig = (domcap[np.maximum(dom_row, 0)] >= cap_need[t_start]) \
                & (dom_row >= 0)
            if elig.any():
                gang_elig = elig
        for i in members:
            if i < 0:
                continue
            if dom_idx >= 0:
                dmask = (dom_row >= 0) & ((gang_dom < 0)
                                          | (dom_row == gang_dom)) \
                    & gang_elig
            else:
                dmask = np.ones((N,), bool)
            eff_used = trial_used + nom_used
            eff_cnt = trial_cnt + nom_cnt
            if nom_row[i] >= 0:
                eff_used = eff_used.copy()
                eff_cnt = eff_cnt.copy()
                eff_used[nom_row[i]] -= reqs[i]
                eff_cnt[nom_row[i]] -= np.float32(1.0)
            fits = unique_masks[mask_idx[i]] & dmask & node_ok & node_valid
            fits &= (reqs[i][None, :] + eff_used <= alloc).all(axis=1)
            fits &= eff_cnt + np.float32(1.0) <= max_pods
            if blocked[i]:
                fits &= ~mem_pressure
            # resource priorities, f32 like the kernel
            req_cpu = trial_nz[:, 0] + nzreqs[i, 0]
            req_mem = trial_nz[:, 1] + nzreqs[i, 1]
            lr_c = np.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                            np.floor((cap_cpu - req_cpu) * np.float32(10.0)
                                     / safe_cpu), np.float32(0.0))
            lr_m = np.where((cap_mem > 0) & (req_mem <= cap_mem),
                            np.floor((cap_mem - req_mem) * np.float32(10.0)
                                     / safe_mem), np.float32(0.0))
            lr = np.floor((lr_c + lr_m) / np.float32(2.0))
            cpu_frac = np.where(cap_cpu > 0, req_cpu / safe_cpu,
                                np.float32(1.0))
            mem_frac = np.where(cap_mem > 0, req_mem / safe_mem,
                                np.float32(1.0))
            ba = np.floor((np.float32(1.0) - np.abs(cpu_frac - mem_frac))
                          * np.float32(10.0) + np.float32(4e-6))
            ba = np.where((cpu_frac >= 1.0) | (mem_frac >= 1.0),
                          np.float32(0.0), ba)
            score = rw[0] * lr + rw[1] * ba + unique_scores[score_idx[i]]
            if has_soft and soft_bidx[i] >= 0:
                # _soft_raw / _soft_score in f32, same op order
                rt = soft_rt[i]
                t = np.maximum(rt, 0)
                drow = soft_dom[t]                          # [Ks, N]
                at = np.take_along_axis(trial_soft[t],
                                        np.maximum(drow, 0), axis=1)
                valid_r = (rt[:, None] >= 0) & (drow >= 0)
                raw = soft_base[max(int(soft_bidx[i]), 0)] + \
                    (soft_rw[i][:, None]
                     * np.where(valid_r, at, np.float32(0.0))).sum(axis=0)
                mn = np.min(np.where(fits, raw, np.float32(np.inf)))
                mx = np.max(np.where(fits, raw, np.float32(-np.inf)))
                if mx > mn and np.isfinite(mn):
                    norm = np.floor(
                        np.float32(10.0) * (raw - mn)
                        / np.maximum(mx - mn, np.float32(1e-30))
                        + np.float32(4e-6))
                    score = score + soft_w * norm
            masked = np.where(fits, score, NEG32)
            h = ((rows64 * -1640531527 + int(seq[i]) * 40503)
                 & 0xFFFF).astype(np.float32)
            best = int(np.argmax(masked - h * np.float32(0.5 / 65536.0)))
            ok = bool(fits[best]) and bool(active[i])
            scores[i] = masked[best]
            if ok:
                placed.append((i, best))
                trial_used[best] += reqs[i]
                trial_nz[best] += nzreqs[i]
                trial_cnt[best] += np.float32(1.0)
                if has_soft:
                    wt = soft_wt[i]
                    wtc = np.maximum(wt, 0)
                    wd = soft_dom[wtc, best]
                    wval = np.where((wt >= 0) & (wd >= 0), soft_ww[i],
                                    np.float32(0.0))
                    np.add.at(trial_soft, (wtc, np.maximum(wd, 0)), wval)
                if dom_idx >= 0 and gang_dom < 0:
                    gang_dom = int(dom_row[best])
            else:
                gang_ok = False
        if gang_ok:
            used, nz, cnt = trial_used, trial_nz, trial_cnt
            if has_soft:
                soft_cnt = trial_soft
            for i, best in placed:
                assign[i] = best
    new_usage = {"used": used, "nonzero_used": nz, "pod_count": cnt}
    if has_soft:
        new_usage["soft_cnt"] = soft_cnt
    return assign, scores, new_usage
