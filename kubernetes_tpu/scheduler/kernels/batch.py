"""Batched Filter+Score+Assign on device.

Replaces the reference's per-pod hot loop (pkg/scheduler/core/
generic_scheduler.go — findNodesThatFit :457 with 16 goroutines,
PrioritizeNodes :672, selectHost :286) with device kernels over a frozen
node snapshot:

  filter_score(node_cfg, usage, pod_batch) -> (fits[P,N] bool, score[P,N])
    the full pods x nodes feasibility mask and score matrix — one fused XLA
    computation, no sampling (vs numFeasibleNodesToFind's 50% shortcut,
    generic_scheduler.go:434-453).

  schedule_batch(node_cfg, usage, pod_batch) -> (assign[P], score[P], usage')
    a lax.scan over the pod axis that reproduces the reference's SERIAL
    semantics exactly — each pod sees node usage updated by every earlier
    bind (the reference achieves this with cache.AssumePod between
    iterations, scheduler.go:514) — but never leaves the device: per step it
    recomputes resource feasibility + resource scores against the running
    usage, combines the batch-invariant mask/score terms, argmaxes, and
    scatter-adds the winner's requests onto the usage tensors.

State layout (host mirror: tensorize.TensorMirror):
  node_cfg — bind-invariant per-node config: alloc [N,R], max_pods [N],
    node_ok/mem_pressure/valid [N] bool. Only informer events change it.
  usage    — bind-varying per-node accounting: used [N,R],
    nonzero_used [N,2], pod_count [N]. schedule_batch returns the
    post-batch value so consecutive batches can chain ON DEVICE without a
    host round trip (core.BatchScheduler's drain fast path).

Transfer discipline (the TPU is reached over a high-latency tunnel): the
pod batch never ships [P, N] matrices. The batch-invariant mask and score
terms are deduplicated host-side — pods sharing constraint terms (one
Deployment's pods share selectors/tolerations) share a row:
    unique_masks  [U, N] bool   +  mask_idx  [P] int32
    unique_scores [S, N] f32    +  score_idx [P] int32
U and S are typically 1-8 where P is thousands, so per-batch upload is
O(P*R + U*N), a few hundred KB instead of the dense O(P*N) hundreds of MB.

Scores follow the reference's integer arithmetic (LeastRequested
least_requested.go:53, BalancedAllocation balanced_resource_allocation.go:77)
via f32 floor; priorities.py is the parity oracle.

Tie-break: a sub-integer pseudo-random penalty keyed on (node row, pod seq)
rotates uniformly among max-score ties, mirroring selectHost's round-robin
intent (:286-296); parity fixtures compare score classes, not tie order.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

MAX_PRIORITY = 10.0
NEG = jnp.float32(-1e30)
#: pods per scan step (unrolled inside the step, exact serial semantics);
#: the scan is latency-bound so fewer, fatter steps win — see
#: schedule_batch. Power of two <= the minimum pod bucket (8).
#: Topology-carrying batches on the CLASSIC path use their own knob: the
#: in-step (anti-)affinity gathers/scatters chain through the carry, so
#: fat steps buy less there (measured r05: uniform 7.7k->9.7k at G=8;
#: anti 2.3k->2.1k). The CLASS-INDEXED path (below) made the whole step
#: cheap enough that one shared fat-step knob covers topology batches too.
import os as _os
_STEP_GROUP = int(_os.environ.get("KTPU_SCAN_GROUP", "8"))
_STEP_GROUP_TOPO = int(_os.environ.get("KTPU_SCAN_GROUP_TOPO", "1"))
#: sharded scan: pack (score, global row) into ONE int64 key so the
#: cross-shard winner election is a single pmax instead of the
#: pmax(score)+pmin(row) pair — halves the per-pod collective count on
#: the latency-bound scan. Requires jax_enable_x64 (the key is int64);
#: with x64 off the knob is inert and the two-collective path runs.
#: Bit-identical winners either way (the key order is exactly
#: lexicographic (score, -row) — see the packed branch in one_pod).
_X64_ARGMAX = _os.environ.get("KTPU_X64_ARGMAX", "0") != "0"

# column layout (keep in sync with tensorize.py)
COL_CPU = 0
COL_MEM = 1


def _least_requested(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                     cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """least_requested.go:53 — ((cap-req)*10/cap int div, avg of cpu+mem)."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu = jnp.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                    jnp.floor((cap_cpu - req_cpu) * MAX_PRIORITY / jnp.maximum(cap_cpu, 1.0)),
                    0.0)
    mem = jnp.where((cap_mem > 0) & (req_mem <= cap_mem),
                    jnp.floor((cap_mem - req_mem) * MAX_PRIORITY / jnp.maximum(cap_mem, 1.0)),
                    0.0)
    return jnp.floor((cpu + mem) / 2.0)


def _balanced_allocation(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                         cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """balanced_resource_allocation.go:77 — 10 - |cpuFrac-memFrac|*10."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu_frac = jnp.where(cap_cpu > 0, req_cpu / jnp.maximum(cap_cpu, 1.0), 1.0)
    mem_frac = jnp.where(cap_mem > 0, req_mem / jnp.maximum(cap_mem, 1.0), 1.0)
    diff = jnp.abs(cpu_frac - mem_frac)
    # epsilon-floor: when (1-diff)*10 is EXACTLY an integer in exact math
    # (e.g. cpuFrac .7875, memFrac .1875 -> 4.0), f32 rounding can land a
    # hair below it while the f64 reference truncation lands at it — a
    # one-point score flip that permutes whole assignment windows (the
    # r04/r05 pod-affinity parity gap, stuck at 0.961). The nudge is far
    # above f32 error (~1e-6 at this magnitude) and far below the spacing
    # of distinct achievable scores near a boundary.
    score = jnp.floor((1.0 - diff) * MAX_PRIORITY + 4e-6)
    return jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, score)


def _pod_feasible(node_cfg: dict, used, pod_count, pod: dict,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """One pod's [N] feasibility against running usage."""
    fits_res = jnp.all(pod["req"][None, :] + used <= node_cfg["alloc"], axis=1)
    fits_count = pod_count + 1.0 <= node_cfg["max_pods"]
    blocked = pod["mem_pressure_blocked"] & node_cfg["mem_pressure"]
    return (fits_res & fits_count & node_cfg["node_ok"] &
            node_cfg["valid"] & mask & ~blocked)


def _pod_score(node_cfg: dict, nz_used, pod: dict,
               static_score: jnp.ndarray,
               rw: jnp.ndarray) -> jnp.ndarray:
    """One pod's [N] batch-varying score (resource priorities, weighted by
    rw = [LeastRequested, BalancedAllocation] from the Policy) plus the
    host-precomputed batch-invariant terms (its unique_scores row)."""
    cap_cpu = node_cfg["alloc"][:, COL_CPU]
    cap_mem = node_cfg["alloc"][:, COL_MEM]
    score = rw[0] * _least_requested(nz_used, pod["nonzero_req"],
                                     cap_cpu, cap_mem)
    score = score + rw[1] * _balanced_allocation(nz_used, pod["nonzero_req"],
                                                 cap_cpu, cap_mem)
    return score + static_score


#: SelectorSpread zone blend weight (selector_spreading.go zoneWeighting)
ZONE_WEIGHTING = 2.0 / 3.0

_BATCH_INVARIANT = ("unique_masks", "unique_scores", "resource_weights",
                    "spread_base", "spread_zone", "spread_zinit",
                    "spread_weight", "anti_dom", "anti_cnt0",
                    "class_req", "class_nz", "class_blocked",
                    "class_mask_idx", "class_score_idx",
                    "soft_dom", "soft_cnt0", "soft_base", "soft_weight")


def _zone_onehot(zone_of: jnp.ndarray, zinit: jnp.ndarray) -> jnp.ndarray:
    """[Z, N] f32 one-hot of the zone-id vector, built ONCE per kernel
    call: the per-step zone sums become a matvec (zoh @ cf) instead of a
    scatter-add — XLA CPU serializes scatters, and the scan pays that
    cost per step. Counts are integer-valued f32, so the matvec's sum
    order cannot change the result (bit-identical to the scatter)."""
    z_idx = jnp.arange(zinit.shape[0], dtype=zone_of.dtype)
    return (zone_of[None, :] == z_idx[:, None]).astype(jnp.float32)


def _spread_score(cnt_g: jnp.ndarray, fits: jnp.ndarray,
                  zone_of: jnp.ndarray, zinit: jnp.ndarray,
                  zoh: jnp.ndarray) -> jnp.ndarray:
    """One pod's [N] SelectorSpread score from running group counts —
    the serial reduce (priorities.selector_spread_reduce /
    selector_spreading.go): invert node counts to 0-10 normalized over the
    FEASIBLE set, blend zone-level counts at weight 2/3; zone id 0 means
    'no zone label' (keeps the MaxPriority zone default, excluded from the
    zone max). int() truncation == floor for these non-negatives."""
    cf = jnp.where(fits, cnt_g, 0.0)
    maxc = jnp.max(cf)
    zs = zinit + zoh @ cf
    z_idx = jnp.arange(zs.shape[0])
    maxz = jnp.max(jnp.where(z_idx > 0, zs, 0.0))
    # f32 max, not jnp.any: a boolean reduce over the mesh-sharded node
    # axis lowers to a pred all-reduce, which the CPU collective backend
    # rejects (the pre-PR test_multichip XLA failures); the f32 form is
    # semantically identical and reduces everywhere
    have_zones = jnp.max(jnp.where(fits & (zone_of > 0), 1.0, 0.0)) > 0
    node_s = jnp.where(maxc > 0,
                       MAX_PRIORITY * (maxc - cnt_g) / jnp.maximum(maxc, 1.0),
                       MAX_PRIORITY)
    zone_s = jnp.where((zone_of > 0) & (maxz > 0),
                       MAX_PRIORITY * (maxz - zs[zone_of])
                       / jnp.maximum(maxz, 1.0),
                       MAX_PRIORITY)
    blended = jnp.where(have_zones,
                        node_s * (1.0 - ZONE_WEIGHTING)
                        + ZONE_WEIGHTING * zone_s,
                        node_s)
    return jnp.floor(blended)


def _split_batch(pod_batch: dict):
    """(per-pod scanned arrays, unique_masks, unique_scores, rw)."""
    per_pod = {k: v for k, v in pod_batch.items()
               if k not in _BATCH_INVARIANT}
    rw = pod_batch.get("resource_weights")
    if rw is None:
        rw = jnp.ones((2,), jnp.float32)
    return per_pod, pod_batch["unique_masks"], pod_batch["unique_scores"], rw


def _spread_tables(pod_batch: dict, N: int):
    """(base [G,N], zone_of [N], zinit [Z], weight scalar) with inert
    defaults for batches without spread groups."""
    base = pod_batch.get("spread_base")
    if base is None:
        return (jnp.zeros((1, N), jnp.float32),
                jnp.zeros((N,), jnp.int32),
                jnp.zeros((1,), jnp.float32),
                jnp.float32(0.0))
    return (base, pod_batch["spread_zone"], pod_batch["spread_zinit"],
            pod_batch["spread_weight"])


@jax.jit
def filter_score(node_cfg: dict, usage: dict, pod_batch: dict
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The full pods x nodes mask + score matrix against the frozen snapshot
    (no in-batch usage updates). vmap over the pod axis."""
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    N = node_cfg["alloc"].shape[0]
    spread_base, zone_of, zinit, spread_w = _spread_tables(pod_batch, N)
    zoh = _zone_onehot(zone_of, zinit)

    def one(pod):
        mask = unique_masks[pod["mask_idx"]]
        static = unique_scores[pod["score_idx"]]
        fits = _pod_feasible(node_cfg, usage["used"], usage["pod_count"],
                             pod, mask)
        score = _pod_score(node_cfg, usage["nonzero_used"], pod, static, rw)
        g = pod.get("spread_gidx", jnp.int32(-1))
        use_spread = jnp.where(g >= 0, 1.0, 0.0)
        score = score + spread_w * use_spread * _spread_score(
            spread_base[jnp.maximum(g, 0)], fits, zone_of, zinit, zoh)
        return fits, jnp.where(fits, score, NEG)
    return jax.vmap(one)(per_pod)


def _soft_tables(pod_batch: dict):
    """(soft_dom [Ts,N], soft_cnt0 [Ts,Ds], soft_base [Sb,N], weight) or
    None — the in-scan preferred inter-pod (anti-)affinity credit tables
    (core._assign_soft_terms)."""
    dom = pod_batch.get("soft_dom")
    if dom is None:
        return None
    return (dom, pod_batch["soft_cnt0"], pod_batch["soft_base"],
            pod_batch["soft_weight"])


def _soft_raw(soft_dom, scnt, soft_base, pod):
    """One pod's [N] raw inter-pod affinity score from the frozen base row
    plus the running per-(term, domain) in-batch credit accumulators —
    the serial reference's per-pod re-count (interpod_affinity.go) over
    batch winners, in the scan carry."""
    rt = pod["soft_read_tids"]                       # [Ks], -1 padded
    t = jnp.maximum(rt, 0)
    drow = soft_dom[t]                               # [Ks, N]
    at = jnp.take_along_axis(scnt[t], jnp.maximum(drow, 0), axis=1)
    valid = (rt[:, None] >= 0) & (drow >= 0)
    delta = (pod["soft_read_w"][:, None]
             * jnp.where(valid, at, 0.0)).sum(axis=0)
    return soft_base[jnp.maximum(pod["soft_base_idx"], 0)] + delta


def _soft_score(raw, fits, weight):
    """minmax_normalize over the CURRENT feasible set (the oracle's
    domain: prioritize_nodes normalizes over filtered nodes), floored with
    the same 4e-6 epsilon as _balanced_allocation (f32 vs the oracle's f64
    can land a hair under an exact-integer boundary)."""
    mn = jnp.min(jnp.where(fits, raw, jnp.inf))
    mx = jnp.max(jnp.where(fits, raw, -jnp.inf))
    span_ok = (mx > mn) & jnp.isfinite(mn)
    norm = jnp.floor(MAX_PRIORITY * (raw - mn)
                     / jnp.maximum(mx - mn, jnp.float32(1e-30)) + 4e-6)
    return jnp.where(span_ok, weight * norm, 0.0)


def _class_resource_score(cap_cpu, cap_mem, req_cpu, req_mem, rw):
    """LeastRequested + BalancedAllocation over pre-broadcast class/node
    axes — the ONE copy of the f32 arithmetic (cap guards, floors, the
    4e-6 boundary epsilon) shared by _class_col (one node row) and
    _class_ms_init (all rows). Elementwise mirror of _least_requested /
    _balanced_allocation, so class-path decisions stay bit-identical to
    the classic per-pod path."""
    lr_c = jnp.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                     jnp.floor((cap_cpu - req_cpu) * MAX_PRIORITY
                               / jnp.maximum(cap_cpu, 1.0)), 0.0)
    lr_m = jnp.where((cap_mem > 0) & (req_mem <= cap_mem),
                     jnp.floor((cap_mem - req_mem) * MAX_PRIORITY
                               / jnp.maximum(cap_mem, 1.0)), 0.0)
    lr = jnp.floor((lr_c + lr_m) / 2.0)
    cpu_frac = jnp.where(cap_cpu > 0, req_cpu / jnp.maximum(cap_cpu, 1.0),
                         1.0)
    mem_frac = jnp.where(cap_mem > 0, req_mem / jnp.maximum(cap_mem, 1.0),
                         1.0)
    ba = jnp.floor((1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_PRIORITY
                   + 4e-6)
    ba = jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, ba)
    return rw[0] * lr + rw[1] * ba


def _class_col(node_cfg: dict, cls: dict, unique_masks, unique_scores, rw,
               used_b, nz_b, cnt_b, b):
    """Recompute every template class's masked score at ONE node row `b`
    (the only row a winner's bind changes) — [C] f32, NEG where
    infeasible. Same elementwise f32 arithmetic as the classic per-pod
    path, so decisions are bit-identical."""
    alloc_b = node_cfg["alloc"][b]                                 # [R]
    fits = jnp.all(cls["class_req"] + used_b[None, :]
                   <= alloc_b[None, :], axis=1)                    # [C]
    fits &= cnt_b + 1.0 <= node_cfg["max_pods"][b]
    fits &= ~(cls["class_blocked"] & node_cfg["mem_pressure"][b])
    fits &= node_cfg["node_ok"][b] & node_cfg["valid"][b]
    fits &= unique_masks[cls["class_mask_idx"], b]
    score = _class_resource_score(
        alloc_b[COL_CPU], alloc_b[COL_MEM],
        nz_b[0] + cls["class_nz"][:, 0],
        nz_b[1] + cls["class_nz"][:, 1], rw) \
        + unique_scores[cls["class_score_idx"], b]
    return jnp.where(fits, score, NEG)


def _class_ms_init(node_cfg: dict, usage: dict, cls: dict,
                   unique_masks, unique_scores, rw):
    """[C, N] masked-score table at batch start — the same arithmetic as
    _class_col, vectorized over the node axis (computed once per batch;
    the scan then refreshes one COLUMN per winner instead of recomputing
    [N, R] feasibility + scores per pod)."""
    used = usage["used"]                                           # [N, R]
    nz = usage["nonzero_used"]                                     # [N, 2]
    cnt = usage["pod_count"]                                       # [N]
    alloc = node_cfg["alloc"]
    C = cls["class_req"].shape[0]
    R = alloc.shape[1]
    fits = jnp.ones((C, alloc.shape[0]), bool)
    for r in range(R):  # static unroll: no [C, N, R] intermediate
        fits &= cls["class_req"][:, r][:, None] + used[None, :, r] \
            <= alloc[None, :, r]
    fits &= (cnt + 1.0 <= node_cfg["max_pods"])[None, :]
    fits &= ~(cls["class_blocked"][:, None]
              & node_cfg["mem_pressure"][None, :])
    fits &= (node_cfg["node_ok"] & node_cfg["valid"])[None, :]
    fits &= unique_masks[cls["class_mask_idx"]]
    score = _class_resource_score(
        alloc[:, COL_CPU][None, :], alloc[:, COL_MEM][None, :],
        nz[:, 0][None, :] + cls["class_nz"][:, 0][:, None],
        nz[:, 1][None, :] + cls["class_nz"][:, 1][:, None], rw) \
        + unique_scores[cls["class_score_idx"]]
    return jnp.where(fits, score, NEG)


def _term_hits(anti_dom, table, tids):
    """[K,N] bool: node's domain holds an in-batch hit for term tids[k]
    in `table` (-1 = padding, never hits)."""
    t = jnp.maximum(tids, 0)                          # [K]
    drow = anti_dom[t]                                # [K,N]
    at = jnp.take_along_axis(
        table[t], jnp.maximum(drow, 0), axis=1)       # [K,N]
    return (tids[:, None] >= 0) & (drow >= 0) & (at > 0.0)


def _topo_bad(anti_dom, carry, pod, has_dir2):
    """[N] bool: nodes this pod may NOT take because of in-batch winners'
    required (anti-)affinity — direction 1 (pod CARRIES an anti term, a
    winner MATCHES it in the domain), direction 2 (pod MATCHES a term a
    winner CARRIES, when the carry table ships), and waived required
    affinity (once ANY winner matches the term, later carriers must
    co-locate into its domain). ONE copy for the classic and
    class-indexed kernels: their contract is bit-identical decisions, so
    this mask arithmetic must never diverge between them."""
    bad = _term_hits(anti_dom, carry["topo_cnt"],
                     pod["anti_tids"]).any(axis=0)
    if has_dir2:
        bad = bad | _term_hits(anti_dom, carry["topo_carry"],
                               pod["cmatch_tids"]).any(axis=0)
    atids = pod["aff_tids"]
    need = (atids >= 0) & (carry["topo_tot"][jnp.maximum(atids, 0)] > 0.0)
    bad = bad | (need[:, None] & ~_term_hits(
        anti_dom, carry["topo_cnt"], atids)).any(axis=0)
    return bad


def _topo_scatter(anti_dom, carry, pod, best, ok, has_dir2):
    """The winner's (term, domain) counter updates: one [K]-vector
    scatter-add per table instead of K chained scatters (duplicate padded
    indices add 0, .at accumulates safely). Shared by both kernels for
    the same bit-identity reason as _topo_bad."""
    mtids = pod["match_tids"]                         # [K]
    mt = jnp.maximum(mtids, 0)
    md = anti_dom[mt, best]                           # [K]
    val = ((mtids >= 0) & (md >= 0) & ok).astype(jnp.float32)
    out = {"topo_cnt": carry["topo_cnt"].at[
               mt, jnp.maximum(md, 0)].add(val),
           "topo_tot": carry["topo_tot"].at[mt].add(val)}
    if has_dir2:
        atids2 = pod["canti_tids"]
        at2 = jnp.maximum(atids2, 0)
        ad = anti_dom[at2, best]
        aval = ((atids2 >= 0) & (ad >= 0) & ok).astype(jnp.float32)
        out["topo_carry"] = carry["topo_carry"].at[
            at2, jnp.maximum(ad, 0)].add(aval)
    return out


#: "was feasible" threshold for the class path: real masked scores are
#: small-magnitude; NEG marks infeasible. Strictly between them.
_NEG_THRESHOLD = jnp.float32(-1e29)


def _tie_penalized(masked, rows, seq):
    """selectHost rotates among max-score ties across cycles (:286-296):
    sub-integer hash penalty keyed on (node row, pod seq). Base scores
    are integers spaced >= 1 and the penalty is < 0.5, so cross-class
    ranking is intact. ONE copy for the classic, class-indexed, gang,
    and sharded kernels — the hash is part of the DECISION, so it must
    never diverge between them (the sharded kernel feeds GLOBAL row ids,
    making its penalties match the single-device kernel bit for bit);
    the host replicas (core._RepairReassigner, the gang oracle, bench's
    parity oracle) mirror the same constants in int64+mask form."""
    h = jnp.bitwise_and(rows * jnp.int32(-1640531527) +
                        seq * jnp.int32(40503), 0xFFFF)
    return masked - h.astype(jnp.float32) * jnp.float32(0.5 / 65536.0)


def _soft_write(soft_dom, soft_cnt, pod, best, ok):
    """The winner's soft-credit writes: +1 per matched read channel,
    +weight per carried preferred/required-affinity channel, at the
    chosen node's domains. ONE copy for the classic, class-indexed, and
    gang kernels (bit-identity contract, like _topo_scatter)."""
    wtids = pod["soft_write_tids"]                    # [Ks]
    wt = jnp.maximum(wtids, 0)
    wd = soft_dom[wt, best]                           # [Ks]
    wval = jnp.where((wtids >= 0) & (wd >= 0) & ok,
                     pod["soft_write_w"], 0.0)
    return soft_cnt.at[wt, jnp.maximum(wd, 0)].add(wval)


def _nom_feas_usage(usage: dict, nom: dict) -> dict:
    """Usage with the phantom nominated reservations folded into the
    FEASIBILITY columns (used/pod_count) only — scores stay on real usage
    (nonzero_used), matching PrioritizeNodes ranking against the snapshot
    and the classic kernel's eff_used/eff_count arithmetic."""
    return {"used": usage["used"] + nom["used"],
            "nonzero_used": usage["nonzero_used"],
            "pod_count": usage["pod_count"] + nom["count"]}


def _class_ctx(node_cfg: dict, usage: dict, pod_batch: dict, nom: dict):
    """Shared setup for the class-indexed kernels: split the batch,
    resolve the optional term tables, build the [C, N] masked-score
    table and the initial carry. ONE copy for the serial scan below and
    the speculative cohort kernel (kernels/speculative.py) — the
    speculative kernel's serial-replay branch runs _class_pod_step
    against this exact carry layout, so its decisions cannot diverge
    from _schedule_batch_classes. Returns (ctx, carry0, per_pod)."""
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    N = node_cfg["alloc"].shape[0]
    cls = {k: pod_batch[k] for k in ("class_req", "class_nz",
                                     "class_blocked", "class_mask_idx",
                                     "class_score_idx")}
    anti_dom = pod_batch.get("anti_dom")
    has_topo = anti_dom is not None
    has_dir2 = has_topo and "cmatch_tids" in pod_batch
    has_spread = pod_batch.get("spread_base") is not None
    spread_base, zone_of, zinit, spread_w = _spread_tables(pod_batch, N)
    zoh = _zone_onehot(zone_of, zinit)
    soft = _soft_tables(pod_batch)
    has_soft = soft is not None
    has_nom = nom is not None
    ms0 = _class_ms_init(node_cfg,
                         _nom_feas_usage(usage, nom) if has_nom else usage,
                         cls, unique_masks, unique_scores, rw)
    ctx = {"node_cfg": node_cfg, "cls": cls, "unique_masks": unique_masks,
           "unique_scores": unique_scores, "rw": rw,
           "rows": jnp.arange(N, dtype=jnp.int32), "N": N,
           "anti_dom": anti_dom, "has_topo": has_topo,
           "has_dir2": has_dir2, "has_spread": has_spread,
           "spread_w": spread_w, "zone_of": zone_of, "zinit": zinit,
           "zoh": zoh, "soft": soft, "has_soft": has_soft,
           "has_nom": has_nom, "nom": nom}
    carry0 = {"used": usage["used"], "nz_used": usage["nonzero_used"],
              "pod_count": usage["pod_count"], "ms": ms0}
    if has_topo:
        carry0["topo_cnt"] = pod_batch["anti_cnt0"]
        carry0["topo_tot"] = jnp.zeros((anti_dom.shape[0],), jnp.float32)
        if has_dir2:
            carry0["topo_carry"] = jnp.zeros_like(pod_batch["anti_cnt0"])
    if has_spread:
        # chained launches seed the spread/soft carries from the
        # predecessor's finals (same contract as the classic path)
        sp0 = usage.get("spread")
        carry0["spread"] = sp0 if sp0 is not None else spread_base
    if has_soft:
        sc0 = usage.get("soft_cnt")
        carry0["soft_cnt"] = sc0 if sc0 is not None else soft[1]
    return ctx, carry0, per_pod


def _class_pod_step(ctx, carry, pod):
    """One pod's serial class-scan step: gather its class's masked-score
    row, apply the carry-dependent terms, argmax, scatter the winner's
    usage and refresh the winner's COLUMN across all classes. Shared by
    _schedule_batch_classes and the speculative kernel's repair branch
    (bit-identity contract, like _topo_bad/_topo_scatter)."""
    node_cfg = ctx["node_cfg"]
    cls = ctx["cls"]
    unique_masks, unique_scores = ctx["unique_masks"], ctx["unique_scores"]
    rw, rows, N = ctx["rw"], ctx["rows"], ctx["N"]
    nom = ctx["nom"]
    u = pod["class_idx"]
    base = carry["ms"][u]                                      # [N]
    if ctx["has_nom"]:
        # self-exemption: the pod's own nominated row is recomputed
        # with eff = (used + nom) - own req / count - 1 — the same
        # f32 op order as the classic kernel's self_oh subtraction
        r = pod.get("nom_row", jnp.int32(-1))
        rc = jnp.clip(r, 0, N - 1)
        corr = _class_col(
            node_cfg, cls, unique_masks, unique_scores, rw,
            carry["used"][rc] + nom["used"][rc] - cls["class_req"][u],
            carry["nz_used"][rc],
            carry["pod_count"][rc] + nom["count"][rc] - 1.0, rc)[u]
        base = jnp.where((r >= 0) & (rows == r), corr, base)
    fits = base > _NEG_THRESHOLD
    if ctx["has_topo"]:
        # both (anti-)affinity directions + waived co-location, from
        # the running counters (_topo_bad — shared with the classic
        # kernel so the mask arithmetic can't diverge)
        fits = fits & ~_topo_bad(ctx["anti_dom"], carry, pod,
                                 ctx["has_dir2"])
    score = base
    if ctx["has_soft"]:
        soft_dom, _, soft_base, soft_w = ctx["soft"]
        raw = _soft_raw(soft_dom, carry["soft_cnt"], soft_base, pod)
        score = score + jnp.where(pod["soft_base_idx"] >= 0,
                                  _soft_score(raw, fits, soft_w), 0.0)
    if ctx["has_spread"]:
        g = pod.get("spread_gidx", jnp.int32(-1))
        use_spread = jnp.where(g >= 0, 1.0, 0.0)
        score = score + ctx["spread_w"] * use_spread * _spread_score(
            carry["spread"][jnp.maximum(g, 0)], fits, ctx["zone_of"],
            ctx["zinit"], ctx["zoh"])
    masked = jnp.where(fits, score, NEG)
    best = jnp.argmax(_tie_penalized(masked, rows, pod["seq"])) \
        .astype(jnp.int32)
    chosen = masked[best]
    ok = (chosen > _NEG_THRESHOLD) & pod["active"]
    ok_f = jnp.where(ok, 1.0, 0.0)
    used = carry["used"].at[best].add(ok_f * cls["class_req"][u])
    nz_used = carry["nz_used"].at[best].add(ok_f * cls["class_nz"][u])
    pod_count = carry["pod_count"].at[best].add(ok_f)
    if ctx["has_nom"]:
        col = _class_col(node_cfg, cls, unique_masks, unique_scores,
                         rw, used[best] + nom["used"][best],
                         nz_used[best],
                         pod_count[best] + nom["count"][best], best)
    else:
        col = _class_col(node_cfg, cls, unique_masks, unique_scores,
                         rw, used[best], nz_used[best],
                         pod_count[best], best)
    out = {"used": used, "nz_used": nz_used, "pod_count": pod_count,
           "ms": carry["ms"].at[:, best].set(col)}
    if ctx["has_spread"]:
        sm = pod.get("spread_match")
        if sm is None:
            sm = jnp.zeros((carry["spread"].shape[0],), jnp.float32)
        out["spread"] = carry["spread"].at[:, best].add(sm * ok_f)
    if ctx["has_topo"]:
        out.update(_topo_scatter(ctx["anti_dom"], carry, pod, best, ok,
                                 ctx["has_dir2"]))
    if ctx["has_soft"]:
        soft_dom = ctx["soft"][0]
        out["soft_cnt"] = _soft_write(soft_dom, carry["soft_cnt"],
                                      pod, best, ok)
    assign = jnp.where(ok, best, jnp.int32(-1))
    return out, (assign, chosen)


def _class_usage_out(ctx, final) -> dict:
    """The post-batch usage dict from a class-scan carry final (spread/
    soft carry finals ride along for the next chained launch)."""
    new_usage = {"used": final["used"],
                 "nonzero_used": final["nz_used"],
                 "pod_count": final["pod_count"]}
    if ctx["has_spread"]:
        new_usage["spread"] = final["spread"]
    if ctx["has_soft"]:
        new_usage["soft_cnt"] = final["soft_cnt"]
    return new_usage


def _schedule_batch_classes(node_cfg: dict, usage: dict, pod_batch: dict,
                            nom: dict = None):
    """The class-indexed incremental scan: pods sharing a (template,
    score-row) class share a precomputed masked-score ROW; a scan step
    gathers its pod's row, argmaxes, and refreshes only the winner's
    COLUMN across all classes (the single node whose usage changed).
    Per-step cost drops from O(N*R) feasibility+score recompute to
    O(N + C*R) — the change that lets topology batches run fat scan
    steps instead of the r05 alignment-split workaround.

    Semantics and f32 arithmetic are bit-identical to the classic path
    (tests/test_topo_cache.py + tests/test_class_fastpath.py pin
    decisions). Every non-gang batch shape rides here now:

      - spread groups: per-group running counts in the carry, the
        winner's spread_match row bumping every matching group —
        identical to the classic kernel's in-scan spread.
      - soft inter-pod credits: the per-(term, domain) channel
        accumulators in the carry, read/written per pod.
      - nominated reservations: the phantom {used, count} overlay is
        folded into the masked-score table's FEASIBILITY at build time
        and at every winner-column refresh; a pod's own reservation at
        its nominated row is re-credited by recomputing that ONE column
        with the self-subtracted overlay (the classic kernel's self_oh
        arithmetic, so the f32 ops match bit for bit).

    A chained launch seeds the spread/soft carries from the predecessor's
    finals (usage["spread"] / usage["soft_cnt"], riding the same device
    handle as the chained usage — core.schedule_launch gates this on the
    anchor's base tables still applying).

    The per-pod step lives in _class_pod_step and the setup in
    _class_ctx, both shared with the speculative cohort kernel
    (kernels/speculative.py) so the two paths cannot drift."""
    ctx, carry0, per_pod = _class_ctx(node_cfg, usage, pod_batch, nom)
    P = per_pod["seq"].shape[0]
    want = max(1, _STEP_GROUP)
    G = min(1 << (want.bit_length() - 1), P)

    def step(carry, podg):
        outs = []
        for g in range(G):
            pod = {k: v[g] for k, v in podg.items()}
            carry, out = _class_pod_step(ctx, carry, pod)
            outs.append(out)
        return carry, (jnp.stack([o[0] for o in outs]),
                       jnp.stack([o[1] for o in outs]))

    per_pod_g = {k: v.reshape((P // G, G) + v.shape[1:])
                 for k, v in per_pod.items()}
    final, (assign_g, scores_g) = lax.scan(step, carry0, per_pod_g)
    return assign_g.reshape(P), scores_g.reshape(P), \
        _class_usage_out(ctx, final)


@jax.jit
def schedule_batch(node_cfg: dict, usage: dict, pod_batch: dict,
                   nom: dict = None):
    """Serial-semantics greedy assignment, fully on device.

    Returns (assign [P] int32 node row or -1, chosen_score [P] f32,
    new_usage dict). new_usage chains into the next batch's call during a
    queue drain (core.BatchScheduler fast path) so N batches cost N device
    dispatches and zero usage re-uploads; the cache remains the source of
    truth between drains (assume/forget -> mirror dirty rows).

    `nom` carries aggregated nominated-pod reservations (preemption's
    freed space, scheduler.go:292-380): used [N,R], nz [N,2], count [N].
    Feasibility treats them as phantom usage so no pod steals a nominated
    node's space, except the nominee itself — each pod's own contribution
    is subtracted at its `nom_row` (its nominated node's row, -1 if none).
    Deviation from the reference's two-pass nominated check
    (generic_scheduler.go:598-664): the reservation shields against ALL
    other pods, not just lower-priority ones — strictly more conservative;
    a higher-priority pod pushed off a full nominated node preempts
    instead. Scores stay on real usage (matching PrioritizeNodes, which
    ranks against the snapshot).

    Dispatch (trace-time, by pytree structure): batches carrying class
    tables (tensorize.PodBatchTensors.enable_class_scan) route to the
    incremental class-indexed scan — spread groups, soft in-scan
    credits, and nominated reservations now ride it as carried state.
    The classic per-pod recompute below remains as the one-source parity
    control (KTPU_CLASS_SCAN=0, hand-built batches in tests)."""
    if "class_req" in pod_batch:
        return _schedule_batch_classes(node_cfg, usage, pod_batch, nom)
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    N = node_cfg["alloc"].shape[0]
    spread_base, zone_of, zinit, spread_w = _spread_tables(pod_batch, N)
    zoh = _zone_onehot(zone_of, zinit)
    soft = _soft_tables(pod_batch)
    has_soft = soft is not None
    if has_soft:
        soft_dom, soft_cnt0, soft_base, soft_w = soft
    #: in-scan required (anti-)affinity: per-term node->domain rows plus
    #: running (term, domain) match counters — the BatchOverlay's
    #: serial-winner visibility, ON DEVICE, so the kernel's picks already
    #: respect earlier same-batch winners instead of being repaired after
    anti_dom = pod_batch.get("anti_dom")        # [T, N] int32, -1=no label
    has_topo = anti_dom is not None
    has_dir2 = has_topo and "cmatch_tids" in pod_batch
    rows = jnp.arange(N, dtype=jnp.int32)
    if nom is None:
        nom = {"used": jnp.zeros_like(usage["used"]),
               "count": jnp.zeros_like(usage["pod_count"])}

    def one_pod(carry, pod):
        mask = unique_masks[pod["mask_idx"]]
        static = unique_scores[pod["score_idx"]]
        self_oh = rows == pod.get("nom_row", jnp.int32(-1))
        eff_used = carry["used"] + nom["used"] - \
            jnp.where(self_oh[:, None], pod["req"][None, :], 0.0)
        eff_count = carry["pod_count"] + nom["count"] \
            - self_oh.astype(jnp.float32)
        fits = _pod_feasible(node_cfg, eff_used, eff_count, pod, mask)
        if has_topo:
            # per-pod term lists ([K] tids, -1 padded) keep this O(K*N)
            # per step instead of O(T*N): a pod carries/matches only a
            # handful of terms, while the batch's union can be hundreds.
            # The K axis is VECTORIZED — one [K,N] gather + one reduce —
            # not a Python loop: K unrolled iterations serialize K
            # dependent gathers in the scan's HLO (the r04 anti-affinity
            # regression, 2.5k -> 1.7k pods/s). _topo_bad is shared with
            # the class-indexed kernel (bit-identity contract).
            fits = fits & ~_topo_bad(anti_dom, carry, pod, has_dir2)
        score = _pod_score(node_cfg, carry["nz_used"], pod, static, rw)
        if has_soft:
            # preferred inter-pod (anti-)affinity runs IN-SCAN from running
            # per-(term, domain) credit accumulators — the serial
            # reference's per-pod re-score via assume-between-iterations,
            # which SOFT_SCORE_CHUNK sub-batching used to approximate
            raw = _soft_raw(soft_dom, carry["soft_cnt"], soft_base, pod)
            score = score + jnp.where(
                pod["soft_base_idx"] >= 0,
                _soft_score(raw, fits, soft_w), 0.0)
        # SelectorSpread runs IN-SCAN from running group counts — the
        # serial reference recounts per pod via assume-between-iterations
        # (selector_spreading.go:277); a frozen batch-start score would
        # clump one controller's pods onto the same "least loaded" nodes
        g = pod.get("spread_gidx", jnp.int32(-1))
        gi = jnp.maximum(g, 0)
        use_spread = jnp.where(g >= 0, 1.0, 0.0)
        score = score + spread_w * use_spread * _spread_score(
            carry["spread"][gi], fits, zone_of, zinit, zoh)
        masked = jnp.where(fits, score, NEG)
        best = jnp.argmax(_tie_penalized(masked, rows, pod["seq"])) \
            .astype(jnp.int32)
        ok = fits[best] & pod["active"]
        onehot = (rows == best) & ok
        oh_f = onehot.astype(jnp.float32)
        # a winner bumps EVERY spread group whose selectors match it (its
        # spread_match row), not only its own — overlapping groups see
        # each other's in-batch placements like the serial re-count does
        sm = pod.get("spread_match")
        if sm is None:
            sm = jnp.zeros((carry["spread"].shape[0],), jnp.float32)
        ok_f = jnp.where(ok, 1.0, 0.0)
        out = {
            "used": carry["used"] + oh_f[:, None] * pod["req"][None, :],
            "nz_used": carry["nz_used"]
            + oh_f[:, None] * pod["nonzero_req"][None, :],
            "pod_count": carry["pod_count"] + oh_f,
            "spread": carry["spread"].at[:, best].add(sm * ok_f),
        }
        if has_topo:
            out.update(_topo_scatter(anti_dom, carry, pod, best, ok,
                                     has_dir2))
        if has_soft:
            # the winner's credit writes: +1 per matched read channel,
            # +weight per carried preferred/required-affinity channel
            out["soft_cnt"] = _soft_write(soft_dom, carry["soft_cnt"],
                                          pod, best, ok)
        assign = jnp.where(ok, best, jnp.int32(-1))
        return out, (assign, masked[best])

    # chained launches seed the spread/soft carries from the
    # predecessor's finals (same contract as the class-indexed path)
    sp0 = usage.get("spread")
    carry0 = {"used": usage["used"], "nz_used": usage["nonzero_used"],
              "pod_count": usage["pod_count"],
              "spread": sp0 if sp0 is not None else spread_base}
    if has_topo:
        carry0["topo_cnt"] = pod_batch["anti_cnt0"]
        carry0["topo_tot"] = jnp.zeros((anti_dom.shape[0],), jnp.float32)
        if has_dir2:
            carry0["topo_carry"] = jnp.zeros_like(pod_batch["anti_cnt0"])
    if has_soft:
        sc0 = usage.get("soft_cnt")
        carry0["soft_cnt"] = sc0 if sc0 is not None else soft_cnt0
    # STEP GROUPING: the scan is latency-bound — each step's compute
    # ([N]-vector ops) is tiny next to the per-step sequencing overhead,
    # so a P-step scan costs ~P * step_latency regardless of N. Packing G
    # pods per step (unrolled inside, SAME op sequence -> bit-identical
    # results) cuts the step count G-fold. P is always a power of two
    # >= 8 (tensorize._bucket), so G=8 divides it exactly.
    P = per_pod["seq"].shape[0]
    # clamp the knob to a power of two dividing P (P is always a power of
    # two via tensorize._bucket) — an arbitrary env value must degrade,
    # not crash the reshape below
    want = max(1, _STEP_GROUP_TOPO if has_topo else _STEP_GROUP)
    G = min(1 << (want.bit_length() - 1), P)

    def step(carry, podg):
        outs = []
        for g in range(G):
            pod = {k: v[g] for k, v in podg.items()}
            carry, out = one_pod(carry, pod)
            outs.append(out)
        return carry, (jnp.stack([o[0] for o in outs]),
                       jnp.stack([o[1] for o in outs]))

    per_pod_g = {k: v.reshape((P // G, G) + v.shape[1:])
                 for k, v in per_pod.items()}
    final, (assign_g, scores_g) = lax.scan(step, carry0, per_pod_g)
    new_usage = {"used": final["used"],
                 "nonzero_used": final["nz_used"],
                 "pod_count": final["pod_count"]}
    if pod_batch.get("spread_base") is not None:
        new_usage["spread"] = final["spread"]
    if has_soft:
        new_usage["soft_cnt"] = final["soft_cnt"]
    return assign_g.reshape(P), scores_g.reshape(P), new_usage


# ------------------------------------------------------------- sharded scan
#
# The class-indexed scan under jax.experimental.shard_map over a 1-D
# "nodes" mesh axis (sharding.py owns the axis name and the name-keyed
# partition rules). Each shard holds its node slice of the mirror
# (cfg/usage rows), the mask/score tables' node columns, and the [C, N]
# masked-score carry; a scan step runs filter+score over the LOCAL slice
# and reduces to a winner with a cross-shard argmax over (penalized
# score, global node id):
#
#     per shard:   local max + first-max row of (masked - tie_penalty)
#     cross-shard: pmax(score)  -> the global max
#                  pmin(row where local max == global max) -> the winner
#
# f32 max is exact and ties resolve to the LOWEST global row — precisely
# jnp.argmax's first-max-index semantics on one device, so decisions are
# bit-identical to _schedule_batch_classes (the parity-1.0 and chaos
# determinism contracts survive sharding). The winner's column refresh
# and usage scatter stay local to the owning shard (non-owners write
# through an out-of-range index with mode="drop"); the winner's masked
# score and its (anti-)affinity domain ids are broadcast from the owner
# (re-deriving the score from the penalized max would re-round).
#
# GSPMD (plain jit over sharded inputs) remains the path for gang
# batches and for KTPU_SHARD_MAP=0 (the pjit-vs-shard_map selection
# knob). Spread groups, soft credits, and nominated reservations ride
# the shard_map kernel as carried/overlaid state:
#
#   spread — group counts replicate? No: the [G, N] count rows shard on
#     the node axis like spread_base; the per-step normalization needs
#     the GLOBAL max count and zone sums, which are one pmax + one psum
#     of integer-valued f32 (exact in any order, so bit-identical).
#   soft — the [Ts, Ds] channel accumulators replicate; the winner's
#     domain ids broadcast from the owning shard (pmax over -1 padding,
#     the _topo_scatter_sharded recipe), so every shard applies the
#     identical scatter-add. Min-max normalization is a pmin/pmax pair.
#   nominated — the phantom overlay shards with the mirror rows
#     (P("nodes")); the self-exemption column recomputes on the owning
#     shard and drops everywhere else.

_INT32_MAX = jnp.int32(2147483647)


def _spread_score_sharded(cnt_g, fits, zone_of, zinit, zoh):
    """_spread_score under shard_map: cnt_g/fits/zone_of/zoh are the
    LOCAL node slice; the max count, zone sums, and zone presence reduce
    across shards. All reduced values are integer-valued f32 (counts),
    so psum/pmax are order-insensitive and the result is bit-identical
    to the single-device reduce."""
    from ..sharding import NODE_AXIS
    cf = jnp.where(fits, cnt_g, 0.0)
    maxc = lax.pmax(jnp.max(cf), NODE_AXIS)
    zs = zinit + lax.psum(zoh @ cf, NODE_AXIS)
    z_idx = jnp.arange(zs.shape[0])
    maxz = jnp.max(jnp.where(z_idx > 0, zs, 0.0))
    have_zones = lax.pmax(
        jnp.max(jnp.where(fits & (zone_of > 0), 1.0, 0.0)), NODE_AXIS) > 0
    node_s = jnp.where(maxc > 0,
                       MAX_PRIORITY * (maxc - cnt_g) / jnp.maximum(maxc, 1.0),
                       MAX_PRIORITY)
    zone_s = jnp.where((zone_of > 0) & (maxz > 0),
                       MAX_PRIORITY * (maxz - zs[zone_of])
                       / jnp.maximum(maxz, 1.0),
                       MAX_PRIORITY)
    blended = jnp.where(have_zones,
                        node_s * (1.0 - ZONE_WEIGHTING)
                        + ZONE_WEIGHTING * zone_s,
                        node_s)
    return jnp.floor(blended)


def _soft_score_sharded(raw, fits, weight):
    """_soft_score with the min-max normalization domain reduced across
    shards (f32 min/max are exact, so bit-identical)."""
    from ..sharding import NODE_AXIS
    mn = lax.pmin(jnp.min(jnp.where(fits, raw, jnp.inf)), NODE_AXIS)
    mx = lax.pmax(jnp.max(jnp.where(fits, raw, -jnp.inf)), NODE_AXIS)
    span_ok = (mx > mn) & jnp.isfinite(mn)
    norm = jnp.floor(MAX_PRIORITY * (raw - mn)
                     / jnp.maximum(mx - mn, jnp.float32(1e-30)) + 4e-6)
    return jnp.where(span_ok, weight * norm, 0.0)


def _sharded_class_scan(node_cfg: dict, usage: dict, pod_batch: dict,
                        nom: dict = None):
    """shard_map body: every node-axis array here is the LOCAL shard."""
    from ..sharding import NODE_AXIS
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    Nl = node_cfg["alloc"].shape[0]
    offset = lax.axis_index(NODE_AXIS).astype(jnp.int32) * Nl
    rows_g = offset + jnp.arange(Nl, dtype=jnp.int32)
    cls = {k: pod_batch[k] for k in ("class_req", "class_nz",
                                     "class_blocked", "class_mask_idx",
                                     "class_score_idx")}
    anti_dom = pod_batch.get("anti_dom")
    has_topo = anti_dom is not None
    has_dir2 = has_topo and "cmatch_tids" in pod_batch
    has_spread = pod_batch.get("spread_base") is not None
    spread_base, zone_of, zinit, spread_w = _spread_tables(pod_batch, Nl)
    zoh = _zone_onehot(zone_of, zinit)
    soft = _soft_tables(pod_batch)
    has_soft = soft is not None
    if has_soft:
        soft_dom, soft_cnt0, soft_base, soft_w = soft
    has_nom = nom is not None
    ms0 = _class_ms_init(node_cfg,
                         _nom_feas_usage(usage, nom) if has_nom else usage,
                         cls, unique_masks, unique_scores, rw)

    def one_pod(carry, pod):
        u = pod["class_idx"]
        base = carry["ms"][u]                                      # [Nl]
        if has_nom:
            # self-exemption column on the owning shard only (nom_row is
            # a GLOBAL row id); other shards drop the write
            r = pod.get("nom_row", jnp.int32(-1))
            lrn = r - offset
            own_n = (r >= 0) & (lrn >= 0) & (lrn < Nl)
            lrc = jnp.clip(lrn, 0, Nl - 1)
            corr = _class_col(
                node_cfg, cls, unique_masks, unique_scores, rw,
                carry["used"][lrc] + nom["used"][lrc]
                - cls["class_req"][u],
                carry["nz_used"][lrc],
                carry["pod_count"][lrc] + nom["count"][lrc] - 1.0, lrc)[u]
            base = base.at[jnp.where(own_n, lrn, Nl)].set(corr,
                                                          mode="drop")
        fits = base > _NEG_THRESHOLD
        if has_topo:
            fits = fits & ~_topo_bad(anti_dom, carry, pod, has_dir2)
        score = base
        if has_soft:
            raw = _soft_raw(soft_dom, carry["soft_cnt"], soft_base, pod)
            score = score + jnp.where(
                pod["soft_base_idx"] >= 0,
                _soft_score_sharded(raw, fits, soft_w), 0.0)
        if has_spread:
            g = pod.get("spread_gidx", jnp.int32(-1))
            use_spread = jnp.where(g >= 0, 1.0, 0.0)
            score = score + spread_w * use_spread * _spread_score_sharded(
                carry["spread"][jnp.maximum(g, 0)], fits, zone_of, zinit,
                zoh)
        masked = jnp.where(fits, score, NEG)
        # tie-break hash on the GLOBAL row id — identical inputs to the
        # single-device kernel's (row, seq) penalty
        penalized = _tie_penalized(masked, rows_g, pod["seq"])
        lmax = jnp.max(penalized)
        lbest = jnp.argmax(penalized).astype(jnp.int32)  # first max, local
        if _X64_ARGMAX and jax.config.jax_enable_x64:
            # single-collective winner election: key = (mono(score) -
            # 2^31) * 2^32 + (INT32_MAX - row). mono() is the standard
            # sign-flip map of the f32 bit pattern into [0, 2^32) that
            # preserves float order (negatives reverse-complemented,
            # positives offset past them), so pmax(key) picks the max
            # score and, among bit-equal scores, the MIN global row —
            # exactly the pmax+pmin pair's answer. -0.0 is canonicalized
            # first: it is ==0.0 to the comparison path but bit-distinct,
            # the one case where bit order and float order disagree.
            zmax = jnp.where(lmax == 0.0, jnp.float32(0.0), lmax)
            b = lax.bitcast_convert_type(zmax, jnp.int32).astype(jnp.int64)
            mono = jnp.where(b >= 0, b + jnp.int64(0x80000000),
                             jnp.int64(-1) - b)
            row_key = (jnp.int64(2147483647)
                       - (offset + lbest).astype(jnp.int64))
            key = ((mono - jnp.int64(0x80000000)) * jnp.int64(1 << 32)
                   + row_key)
            gkey = lax.pmax(key, NODE_AXIS)
            best = (jnp.int64(2147483647)
                    - (gkey % jnp.int64(1 << 32))).astype(jnp.int32)
        else:
            gmax = lax.pmax(lmax, NODE_AXIS)
            best = lax.pmin(jnp.where(lmax == gmax, offset + lbest,
                                      _INT32_MAX), NODE_AXIS)
        lb = best - offset
        owner = (lb >= 0) & (lb < Nl)
        lbc = jnp.clip(lb, 0, Nl - 1)
        chosen = lax.pmax(jnp.where(owner, masked[lbc], NEG), NODE_AXIS)
        ok = (chosen > _NEG_THRESHOLD) & pod["active"]
        ok_f = jnp.where(ok, 1.0, 0.0)
        lb_w = jnp.where(owner, lb, Nl)      # out of range off-shard
        used = carry["used"].at[lb_w].add(ok_f * cls["class_req"][u],
                                          mode="drop")
        nz_used = carry["nz_used"].at[lb_w].add(ok_f * cls["class_nz"][u],
                                                mode="drop")
        pod_count = carry["pod_count"].at[lb_w].add(ok_f, mode="drop")
        # winner-column refresh, owner-local (non-owners compute a
        # garbage column from the clamped row and drop the write)
        if has_nom:
            col = _class_col(node_cfg, cls, unique_masks, unique_scores,
                             rw, used[lbc] + nom["used"][lbc],
                             nz_used[lbc],
                             pod_count[lbc] + nom["count"][lbc], lbc)
        else:
            col = _class_col(node_cfg, cls, unique_masks, unique_scores,
                             rw, used[lbc], nz_used[lbc], pod_count[lbc],
                             lbc)
        out = {"used": used, "nz_used": nz_used, "pod_count": pod_count,
               "ms": carry["ms"].at[:, lb_w].set(col, mode="drop")}
        if has_spread:
            sm = pod.get("spread_match")
            if sm is None:
                sm = jnp.zeros((carry["spread"].shape[0],), jnp.float32)
            out["spread"] = carry["spread"].at[:, lb_w].add(sm * ok_f,
                                                            mode="drop")
        if has_topo:
            out.update(_topo_scatter_sharded(anti_dom, carry, pod, lbc,
                                             owner, ok, has_dir2))
        if has_soft:
            # the winner's domain ids live on the owning shard: one pmax
            # broadcast (-1 padding loses to any real dom id), then every
            # shard applies the identical replicated scatter-add
            wtids = pod["soft_write_tids"]
            wt = jnp.maximum(wtids, 0)
            wd = lax.pmax(jnp.where(owner, soft_dom[wt, lbc],
                                    jnp.int32(-1)), NODE_AXIS)
            wval = jnp.where((wtids >= 0) & (wd >= 0) & ok,
                             pod["soft_write_w"], 0.0)
            out["soft_cnt"] = carry["soft_cnt"].at[
                wt, jnp.maximum(wd, 0)].add(wval)
        assign = jnp.where(ok, best, jnp.int32(-1))
        return out, (assign, chosen)

    carry0 = {"used": usage["used"], "nz_used": usage["nonzero_used"],
              "pod_count": usage["pod_count"], "ms": ms0}
    if has_topo:
        carry0["topo_cnt"] = pod_batch["anti_cnt0"]
        carry0["topo_tot"] = jnp.zeros((anti_dom.shape[0],), jnp.float32)
        if has_dir2:
            carry0["topo_carry"] = jnp.zeros_like(pod_batch["anti_cnt0"])
    if has_spread:
        sp0 = usage.get("spread")
        carry0["spread"] = sp0 if sp0 is not None else spread_base
    if has_soft:
        sc0 = usage.get("soft_cnt")
        carry0["soft_cnt"] = sc0 if sc0 is not None else soft_cnt0
    P = per_pod["seq"].shape[0]
    want = max(1, _STEP_GROUP)
    G = min(1 << (want.bit_length() - 1), P)

    def step(carry, podg):
        outs = []
        for g in range(G):
            pod = {k: v[g] for k, v in podg.items()}
            carry, out = one_pod(carry, pod)
            outs.append(out)
        return carry, (jnp.stack([o[0] for o in outs]),
                       jnp.stack([o[1] for o in outs]))

    per_pod_g = {k: v.reshape((P // G, G) + v.shape[1:])
                 for k, v in per_pod.items()}
    final, (assign_g, scores_g) = lax.scan(step, carry0, per_pod_g)
    new_usage = {"used": final["used"],
                 "nonzero_used": final["nz_used"],
                 "pod_count": final["pod_count"]}
    if has_spread:
        new_usage["spread"] = final["spread"]
    if has_soft:
        new_usage["soft_cnt"] = final["soft_cnt"]
    return assign_g.reshape(P), scores_g.reshape(P), new_usage


def _topo_scatter_sharded(anti_dom, carry, pod, lbc, owner, ok, has_dir2):
    """_topo_scatter under shard_map: the dom ids at the winner's column
    live on the owning shard, so each table's [K] dom vector is broadcast
    with one pmax (non-owners contribute -1, the 'no label' value, and
    real dom ids are >= 0 — pmax recovers the owner's exact vector); the
    replicated counters then apply the identical scatter-add on every
    shard, keeping the carry in sync without further communication."""
    from ..sharding import NODE_AXIS
    mtids = pod["match_tids"]
    mt = jnp.maximum(mtids, 0)
    md = lax.pmax(jnp.where(owner, anti_dom[mt, lbc], jnp.int32(-1)),
                  NODE_AXIS)
    val = ((mtids >= 0) & (md >= 0) & ok).astype(jnp.float32)
    out = {"topo_cnt": carry["topo_cnt"].at[
               mt, jnp.maximum(md, 0)].add(val),
           "topo_tot": carry["topo_tot"].at[mt].add(val)}
    if has_dir2:
        atids2 = pod["canti_tids"]
        at2 = jnp.maximum(atids2, 0)
        ad = lax.pmax(jnp.where(owner, anti_dom[at2, lbc], jnp.int32(-1)),
                      NODE_AXIS)
        aval = ((atids2 >= 0) & (ad >= 0) & ok).astype(jnp.float32)
        out["topo_carry"] = carry["topo_carry"].at[
            at2, jnp.maximum(ad, 0)].add(aval)
    return out


@partial(jax.jit, static_argnums=(0,))
def schedule_batch_sharded(mesh, node_cfg: dict, usage: dict,
                           pod_batch: dict, nom: dict = None):
    """schedule_batch for class-table batches on a 1-D "nodes" mesh:
    the shard-mapped scan above, with every input placed by the
    name-keyed partition rules (sharding.spec_for). Same returns as
    schedule_batch; decisions bit-identical (tier-1 CPU-sharded smoke +
    the bench's sharded parity fixtures pin this). `nom` is the phantom
    nominated-reservation overlay, sharded with the mirror rows."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from ..sharding import NODE_AXIS, spec_for
    cfg_specs = {k: spec_for(k, jnp.ndim(v)) for k, v in node_cfg.items()}
    usage_specs = {k: spec_for(k, jnp.ndim(v)) for k, v in usage.items()}
    batch_specs = {k: spec_for(k, jnp.ndim(v)) for k, v in pod_batch.items()}
    usage_out = {"used": P(NODE_AXIS, None),
                 "nonzero_used": P(NODE_AXIS, None),
                 "pod_count": P(NODE_AXIS)}
    if "spread_base" in pod_batch:
        usage_out["spread"] = P(None, NODE_AXIS)
    if "soft_dom" in pod_batch:
        usage_out["soft_cnt"] = P()   # replicated accumulators
    out_specs = (P(), P(), usage_out)
    if nom is None:
        fn = shard_map(lambda c, u, b: _sharded_class_scan(c, u, b),
                       mesh=mesh,
                       in_specs=(cfg_specs, usage_specs, batch_specs),
                       out_specs=out_specs, check_rep=False)
        return fn(node_cfg, usage, pod_batch)
    nom_specs = {k: spec_for(k, jnp.ndim(v)) for k, v in nom.items()}
    fn = shard_map(_sharded_class_scan, mesh=mesh,
                   in_specs=(cfg_specs, usage_specs, batch_specs,
                             nom_specs),
                   out_specs=out_specs, check_rep=False)
    return fn(node_cfg, usage, pod_batch, nom)


@partial(jax.jit, donate_argnums=(0, 1))
def apply_dirty(node_cfg: dict, usage: dict, idx: jnp.ndarray,
                cfg_rows: dict, usage_rows: dict) -> Tuple[dict, dict]:
    """Scatter O(delta) dirty rows (cache.go:210-246's generation scan,
    shipped as one packed upload) into the device-resident state. Padded
    slots carry an OUT-OF-RANGE row index (the mirror pads with
    `capacity`, one past the last row) and are dropped by the scatter's
    mode="drop" — a pad row must never alias row 0 or clamp onto the last
    real row (covered by tests/test_pipeline.py's pad-row fixture)."""
    new_cfg = {k: node_cfg[k].at[idx].set(cfg_rows[k], mode="drop")
               for k in node_cfg}
    new_usage = {k: usage[k].at[idx].set(usage_rows[k], mode="drop")
                 for k in usage}
    return new_cfg, new_usage


@jax.jit
def pack_results(assign: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """[2, P] int32 — assign and bitcast scores in ONE fetchable buffer so a
    batch costs a single device->host round trip."""
    return jnp.stack([assign, lax.bitcast_convert_type(scores, jnp.int32)])


def unpack_results(packed) -> Tuple[jnp.ndarray, jnp.ndarray]:
    import numpy as np
    arr = np.asarray(packed)
    return arr[0], arr[1].view(np.float32)
