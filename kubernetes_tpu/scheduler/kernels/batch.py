"""Batched Filter+Score+Assign on device.

Replaces the reference's per-pod hot loop (pkg/scheduler/core/
generic_scheduler.go — findNodesThatFit :457 with 16 goroutines,
PrioritizeNodes :672, selectHost :286) with device kernels over a frozen
node snapshot:

  filter_score(node_cfg, usage, pod_batch) -> (fits[P,N] bool, score[P,N])
    the full pods x nodes feasibility mask and score matrix — one fused XLA
    computation, no sampling (vs numFeasibleNodesToFind's 50% shortcut,
    generic_scheduler.go:434-453).

  schedule_batch(node_cfg, usage, pod_batch) -> (assign[P], score[P], usage')
    a lax.scan over the pod axis that reproduces the reference's SERIAL
    semantics exactly — each pod sees node usage updated by every earlier
    bind (the reference achieves this with cache.AssumePod between
    iterations, scheduler.go:514) — but never leaves the device: per step it
    recomputes resource feasibility + resource scores against the running
    usage, combines the batch-invariant mask/score terms, argmaxes, and
    scatter-adds the winner's requests onto the usage tensors.

State layout (host mirror: tensorize.TensorMirror):
  node_cfg — bind-invariant per-node config: alloc [N,R], max_pods [N],
    node_ok/mem_pressure/valid [N] bool. Only informer events change it.
  usage    — bind-varying per-node accounting: used [N,R],
    nonzero_used [N,2], pod_count [N]. schedule_batch returns the
    post-batch value so consecutive batches can chain ON DEVICE without a
    host round trip (core.BatchScheduler's drain fast path).

Transfer discipline (the TPU is reached over a high-latency tunnel): the
pod batch never ships [P, N] matrices. The batch-invariant mask and score
terms are deduplicated host-side — pods sharing constraint terms (one
Deployment's pods share selectors/tolerations) share a row:
    unique_masks  [U, N] bool   +  mask_idx  [P] int32
    unique_scores [S, N] f32    +  score_idx [P] int32
U and S are typically 1-8 where P is thousands, so per-batch upload is
O(P*R + U*N), a few hundred KB instead of the dense O(P*N) hundreds of MB.

Scores follow the reference's integer arithmetic (LeastRequested
least_requested.go:53, BalancedAllocation balanced_resource_allocation.go:77)
via f32 floor; priorities.py is the parity oracle.

Tie-break: a sub-integer pseudo-random penalty keyed on (node row, pod seq)
rotates uniformly among max-score ties, mirroring selectHost's round-robin
intent (:286-296); parity fixtures compare score classes, not tie order.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

MAX_PRIORITY = 10.0
NEG = jnp.float32(-1e30)

# column layout (keep in sync with tensorize.py)
COL_CPU = 0
COL_MEM = 1


def _least_requested(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                     cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """least_requested.go:53 — ((cap-req)*10/cap int div, avg of cpu+mem)."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu = jnp.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                    jnp.floor((cap_cpu - req_cpu) * MAX_PRIORITY / jnp.maximum(cap_cpu, 1.0)),
                    0.0)
    mem = jnp.where((cap_mem > 0) & (req_mem <= cap_mem),
                    jnp.floor((cap_mem - req_mem) * MAX_PRIORITY / jnp.maximum(cap_mem, 1.0)),
                    0.0)
    return jnp.floor((cpu + mem) / 2.0)


def _balanced_allocation(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                         cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """balanced_resource_allocation.go:77 — 10 - |cpuFrac-memFrac|*10."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu_frac = jnp.where(cap_cpu > 0, req_cpu / jnp.maximum(cap_cpu, 1.0), 1.0)
    mem_frac = jnp.where(cap_mem > 0, req_mem / jnp.maximum(cap_mem, 1.0), 1.0)
    diff = jnp.abs(cpu_frac - mem_frac)
    score = jnp.floor((1.0 - diff) * MAX_PRIORITY)
    return jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, score)


def _pod_feasible(node_cfg: dict, used, pod_count, pod: dict,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """One pod's [N] feasibility against running usage."""
    fits_res = jnp.all(pod["req"][None, :] + used <= node_cfg["alloc"], axis=1)
    fits_count = pod_count + 1.0 <= node_cfg["max_pods"]
    blocked = pod["mem_pressure_blocked"] & node_cfg["mem_pressure"]
    return (fits_res & fits_count & node_cfg["node_ok"] &
            node_cfg["valid"] & mask & ~blocked)


def _pod_score(node_cfg: dict, nz_used, pod: dict,
               static_score: jnp.ndarray,
               rw: jnp.ndarray) -> jnp.ndarray:
    """One pod's [N] batch-varying score (resource priorities, weighted by
    rw = [LeastRequested, BalancedAllocation] from the Policy) plus the
    host-precomputed batch-invariant terms (its unique_scores row)."""
    cap_cpu = node_cfg["alloc"][:, COL_CPU]
    cap_mem = node_cfg["alloc"][:, COL_MEM]
    score = rw[0] * _least_requested(nz_used, pod["nonzero_req"],
                                     cap_cpu, cap_mem)
    score = score + rw[1] * _balanced_allocation(nz_used, pod["nonzero_req"],
                                                 cap_cpu, cap_mem)
    return score + static_score


_BATCH_INVARIANT = ("unique_masks", "unique_scores", "resource_weights")


def _split_batch(pod_batch: dict):
    """(per-pod scanned arrays, unique_masks, unique_scores, rw)."""
    per_pod = {k: v for k, v in pod_batch.items()
               if k not in _BATCH_INVARIANT}
    rw = pod_batch.get("resource_weights")
    if rw is None:
        rw = jnp.ones((2,), jnp.float32)
    return per_pod, pod_batch["unique_masks"], pod_batch["unique_scores"], rw


@jax.jit
def filter_score(node_cfg: dict, usage: dict, pod_batch: dict
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The full pods x nodes mask + score matrix against the frozen snapshot
    (no in-batch usage updates). vmap over the pod axis."""
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)

    def one(pod):
        mask = unique_masks[pod["mask_idx"]]
        static = unique_scores[pod["score_idx"]]
        fits = _pod_feasible(node_cfg, usage["used"], usage["pod_count"],
                             pod, mask)
        score = _pod_score(node_cfg, usage["nonzero_used"], pod, static, rw)
        return fits, jnp.where(fits, score, NEG)
    return jax.vmap(one)(per_pod)


@jax.jit
def schedule_batch(node_cfg: dict, usage: dict, pod_batch: dict,
                   nom: dict = None):
    """Serial-semantics greedy assignment, fully on device.

    Returns (assign [P] int32 node row or -1, chosen_score [P] f32,
    new_usage dict). new_usage chains into the next batch's call during a
    queue drain (core.BatchScheduler fast path) so N batches cost N device
    dispatches and zero usage re-uploads; the cache remains the source of
    truth between drains (assume/forget -> mirror dirty rows).

    `nom` carries aggregated nominated-pod reservations (preemption's
    freed space, scheduler.go:292-380): used [N,R], nz [N,2], count [N].
    Feasibility treats them as phantom usage so no pod steals a nominated
    node's space, except the nominee itself — each pod's own contribution
    is subtracted at its `nom_row` (its nominated node's row, -1 if none).
    Deviation from the reference's two-pass nominated check
    (generic_scheduler.go:598-664): the reservation shields against ALL
    other pods, not just lower-priority ones — strictly more conservative;
    a higher-priority pod pushed off a full nominated node preempts
    instead. Scores stay on real usage (matching PrioritizeNodes, which
    ranks against the snapshot)."""
    per_pod, unique_masks, unique_scores, rw = _split_batch(pod_batch)
    N = node_cfg["alloc"].shape[0]
    rows = jnp.arange(N, dtype=jnp.int32)
    if nom is None:
        nom = {"used": jnp.zeros_like(usage["used"]),
               "count": jnp.zeros_like(usage["pod_count"])}

    def step(carry, pod):
        used, nz_used, pod_count = carry
        mask = unique_masks[pod["mask_idx"]]
        static = unique_scores[pod["score_idx"]]
        self_oh = rows == pod.get("nom_row", jnp.int32(-1))
        eff_used = used + nom["used"] - \
            jnp.where(self_oh[:, None], pod["req"][None, :], 0.0)
        eff_count = pod_count + nom["count"] - self_oh.astype(jnp.float32)
        fits = _pod_feasible(node_cfg, eff_used, eff_count, pod, mask)
        score = _pod_score(node_cfg, nz_used, pod, static, rw)
        masked = jnp.where(fits, score, NEG)
        # selectHost rotates among max-score ties across cycles (:286-296):
        # sub-integer hash penalty keyed on (row, pod seq). Base scores are
        # integers spaced >= 1 and the penalty is < 0.5, so cross-class
        # ranking is intact.
        h = jnp.bitwise_and(rows * jnp.int32(-1640531527) +
                            pod["seq"] * jnp.int32(40503), 0xFFFF)
        tie_penalty = h.astype(jnp.float32) * jnp.float32(0.5 / 65536.0)
        best = jnp.argmax(masked - tie_penalty).astype(jnp.int32)
        ok = fits[best] & pod["active"]
        onehot = (rows == best) & ok
        oh_f = onehot.astype(jnp.float32)
        used = used + oh_f[:, None] * pod["req"][None, :]
        nz_used = nz_used + oh_f[:, None] * pod["nonzero_req"][None, :]
        pod_count = pod_count + oh_f
        assign = jnp.where(ok, best, jnp.int32(-1))
        return (used, nz_used, pod_count), (assign, masked[best])

    carry0 = (usage["used"], usage["nonzero_used"], usage["pod_count"])
    (used, nz_used, pod_count), (assign, scores) = lax.scan(
        step, carry0, per_pod)
    return assign, scores, {"used": used, "nonzero_used": nz_used,
                            "pod_count": pod_count}


@partial(jax.jit, donate_argnums=(0, 1))
def apply_dirty(node_cfg: dict, usage: dict, idx: jnp.ndarray,
                cfg_rows: dict, usage_rows: dict) -> Tuple[dict, dict]:
    """Scatter O(delta) dirty rows (cache.go:210-246's generation scan,
    shipped as one packed upload) into the device-resident state. Padded
    slots carry idx = -1 and are dropped (out-of-bounds scatter mode)."""
    new_cfg = {k: node_cfg[k].at[idx].set(cfg_rows[k], mode="drop")
               for k in node_cfg}
    new_usage = {k: usage[k].at[idx].set(usage_rows[k], mode="drop")
                 for k in usage}
    return new_cfg, new_usage


@jax.jit
def pack_results(assign: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """[2, P] int32 — assign and bitcast scores in ONE fetchable buffer so a
    batch costs a single device->host round trip."""
    return jnp.stack([assign, lax.bitcast_convert_type(scores, jnp.int32)])


def unpack_results(packed) -> Tuple[jnp.ndarray, jnp.ndarray]:
    import numpy as np
    arr = np.asarray(packed)
    return arr[0], arr[1].view(np.float32)
