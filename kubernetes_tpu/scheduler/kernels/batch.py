"""Batched Filter+Score+Assign on device.

Replaces the reference's per-pod hot loop (pkg/scheduler/core/
generic_scheduler.go — findNodesThatFit :457 with 16 goroutines,
PrioritizeNodes :672, selectHost :286) with two kernels:

  filter_score(node_state, pod_batch) -> (fits[P,N] bool, score[P,N] f32)
    the full pods x nodes feasibility mask and score matrix against a frozen
    snapshot — one fused XLA computation, no sampling
    (vs numFeasibleNodesToFind's 50% shortcut, :434-453).

  schedule_batch(node_state, pod_batch) -> (assign[P] i32, new node usage)
    a lax.scan over the pod axis that reproduces the reference's SERIAL
    semantics exactly — each pod sees node usage updated by every earlier
    bind (the reference achieves this with cache.AssumePod between
    iterations, scheduler.go:514) — but never leaves the device: per step it
    recomputes resource feasibility + resource scores against the running
    usage, combines the batch-invariant mask/score terms, argmaxes, and
    scatter-adds the winner's requests onto the usage tensors.

Scores follow the reference's integer arithmetic (LeastRequested
least_requested.go:53, BalancedAllocation balanced_resource_allocation.go:77)
via f32 floor; priorities.py is the parity oracle.

Tie-break: jnp.argmax takes the lowest max-score row, where the reference
round-robins among ties (selectHost :286-296); parity fixtures compare score
classes, not tie order.

All shapes are static (padded buckets); int/bool tensors stay in VMEM-friendly
dtypes; the P-step scan compiles to a single device program so a 50k-pod batch
costs zero host round-trips.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

MAX_PRIORITY = 10.0
NEG = jnp.float32(-1e30)

# column layout (keep in sync with tensorize.py)
COL_CPU = 0
COL_MEM = 1


def _least_requested(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                     cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """least_requested.go:53 — ((cap-req)*10/cap int div, avg of cpu+mem)."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu = jnp.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                    jnp.floor((cap_cpu - req_cpu) * MAX_PRIORITY / jnp.maximum(cap_cpu, 1.0)),
                    0.0)
    mem = jnp.where((cap_mem > 0) & (req_mem <= cap_mem),
                    jnp.floor((cap_mem - req_mem) * MAX_PRIORITY / jnp.maximum(cap_mem, 1.0)),
                    0.0)
    return jnp.floor((cpu + mem) / 2.0)


def _balanced_allocation(nz_used: jnp.ndarray, nz_req: jnp.ndarray,
                         cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """balanced_resource_allocation.go:77 — 10 - |cpuFrac-memFrac|*10."""
    req_cpu = nz_used[:, 0] + nz_req[0]
    req_mem = nz_used[:, 1] + nz_req[1]
    cpu_frac = jnp.where(cap_cpu > 0, req_cpu / jnp.maximum(cap_cpu, 1.0), 1.0)
    mem_frac = jnp.where(cap_mem > 0, req_mem / jnp.maximum(cap_mem, 1.0), 1.0)
    diff = jnp.abs(cpu_frac - mem_frac)
    score = jnp.floor((1.0 - diff) * MAX_PRIORITY)
    return jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, score)


def _pod_feasible(node_state: dict, used, nz_used, pod_count, pod: dict
                  ) -> jnp.ndarray:
    """One pod's [N] feasibility against running usage."""
    fits_res = jnp.all(pod["req"][None, :] + used <= node_state["alloc"], axis=1)
    fits_count = pod_count + 1.0 <= node_state["max_pods"]
    blocked = pod["mem_pressure_blocked"] & node_state["mem_pressure"]
    return (fits_res & fits_count & node_state["node_ok"] &
            node_state["valid"] & pod["static_mask"] & ~blocked)


def _pod_score(node_state: dict, nz_used, pod: dict) -> jnp.ndarray:
    """One pod's [N] batch-varying score (resource priorities) plus the
    host-precomputed batch-invariant terms (static_score)."""
    cap_cpu = node_state["alloc"][:, COL_CPU]
    cap_mem = node_state["alloc"][:, COL_MEM]
    score = _least_requested(nz_used, pod["nonzero_req"], cap_cpu, cap_mem)
    score = score + _balanced_allocation(nz_used, pod["nonzero_req"],
                                         cap_cpu, cap_mem)
    return score + pod["static_score"]


@jax.jit
def filter_score(node_state: dict, pod_batch: dict
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The full pods x nodes mask + score matrix against the frozen snapshot
    (no in-batch usage updates). vmap over the pod axis."""
    def one(pod):
        fits = _pod_feasible(node_state, node_state["used"],
                             node_state["nonzero_used"],
                             node_state["pod_count"], pod)
        score = _pod_score(node_state, node_state["nonzero_used"], pod)
        return fits, jnp.where(fits, score, NEG)
    return jax.vmap(one)(pod_batch)


@jax.jit
def schedule_batch(node_state: dict, pod_batch: dict):
    """Serial-semantics greedy assignment, fully on device.

    Returns (assign [P] int32 node row or -1, chosen_score [P] f32,
    new_usage dict). The production path does NOT consume new_usage: binds
    flow through cache.assume_pod, whose dirty rows refresh the mirror O(delta)
    next cycle (single source of truth). It exists for tests and for a future
    multi-batch pipelining mode that chains batches device-side.
    """
    N = node_state["alloc"].shape[0]
    # selectHost rotates among max-score nodes across cycles (:286-296). Here:
    # a sub-integer pseudo-random penalty keyed on (row, pod seq) — uniform
    # choice within a tie class, robust to row gaps. Base scores are integers
    # spaced >= 1, and the penalty is < 0.5, so cross-class ranking is intact.
    rows = jnp.arange(N, dtype=jnp.int32)

    def step(carry, pod):
        used, nz_used, pod_count = carry
        fits = _pod_feasible(node_state, used, nz_used, pod_count, pod)
        score = _pod_score(node_state, nz_used, pod)
        masked = jnp.where(fits, score, NEG)
        h = jnp.bitwise_and(rows * jnp.int32(-1640531527) +
                            pod["seq"] * jnp.int32(40503), 0xFFFF)
        tie_penalty = h.astype(jnp.float32) * jnp.float32(0.5 / 65536.0)
        best = jnp.argmax(masked - tie_penalty).astype(jnp.int32)
        ok = fits[best] & pod["active"]
        onehot = (jnp.arange(used.shape[0], dtype=jnp.int32) == best) & ok
        oh_f = onehot.astype(jnp.float32)
        used = used + oh_f[:, None] * pod["req"][None, :]
        nz_used = nz_used + oh_f[:, None] * pod["nonzero_req"][None, :]
        pod_count = pod_count + oh_f
        assign = jnp.where(ok, best, jnp.int32(-1))
        return (used, nz_used, pod_count), (assign, masked[best])

    carry0 = (node_state["used"], node_state["nonzero_used"],
              node_state["pod_count"])
    (used, nz_used, pod_count), (assign, scores) = lax.scan(
        step, carry0, pod_batch)
    return assign, scores, {"used": used, "nonzero_used": nz_used,
                            "pod_count": pod_count}
