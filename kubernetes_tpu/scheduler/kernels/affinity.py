"""Inter-pod (anti-)affinity template evaluation as device matmuls.

The M3 kernel (SURVEY §7.4): the reference's topologyPairsMaps lookups
(pkg/scheduler/algorithm/predicates/metadata.go:71-94 consumed per-node in
predicates.go InterPodAffinityMatches) become, for a whole batch of
constraint templates at once,

    viol[u, n] = sel_dom[u]     · (1 - has_dom[:, n])   # aff terms need the
                                                        # topology key
               + sel_present[u] · (1 - present[:, n])   # non-waived affinity
                                                        # needs a match
               + sel_absent[u]  · present[:, n]         # anti-affinity
                                                        # forbids a match
    mask[u, n] = viol[u, n] == 0

three [U, T] × [T, N] matmuls that land on the MXU. The topology index
(scheduler/topology.py) maintains the sparse counts incrementally and
routes evaluation here when U·T·N is large; small batches stay on host
numpy (identical arithmetic — tests/test_topology.py asserts equality).

Shapes are bucketed to powers of two so XLA compiles one kernel per bucket
pair, not one per batch.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def _bucket(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


@jax.jit
def _affinity_masks_jit(has_dom, present, sel_dom, sel_present, sel_absent):
    hd = has_dom.astype(jnp.float32)
    pr = (present & has_dom).astype(jnp.float32)
    viol = sel_dom @ (1.0 - hd) + sel_present @ (1.0 - pr) + sel_absent @ pr
    return viol == 0.0


@jax.jit
def _affinity_scores_jit(weights, counts):
    """[U, T] preferred-term weights × [T, N] match/carry counts — the
    segment-reduction form of interpod_affinity.go's pair-weight
    accumulation."""
    return weights @ counts


def affinity_masks(has_dom: np.ndarray, present: np.ndarray,
                   sel_dom: np.ndarray, sel_present: np.ndarray,
                   sel_absent: np.ndarray) -> np.ndarray:
    """Bucket-padded wrapper; returns the unpadded [U, N] bool mask."""
    T, N = has_dom.shape
    U = sel_dom.shape[0]
    Tb, Ub = _bucket(T), _bucket(U)
    hd = np.zeros((Tb, N), bool)
    hd[:T] = has_dom
    pr = np.zeros((Tb, N), bool)
    pr[:T] = present
    sd = np.zeros((Ub, Tb), np.float32)
    sd[:U, :T] = sel_dom
    sp = np.zeros((Ub, Tb), np.float32)
    sp[:U, :T] = sel_present
    sa = np.zeros((Ub, Tb), np.float32)
    sa[:U, :T] = sel_absent
    out = _affinity_masks_jit(jnp.asarray(hd), jnp.asarray(pr),
                              jnp.asarray(sd), jnp.asarray(sp),
                              jnp.asarray(sa))
    return np.asarray(out)[:U]


def affinity_scores(weights: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Bucket-padded [U, T] @ [T, N] preferred-affinity score accumulation."""
    U, T = weights.shape
    N = counts.shape[1]
    Tb, Ub = _bucket(T), _bucket(U)
    w = np.zeros((Ub, Tb), np.float32)
    w[:U, :T] = weights
    c = np.zeros((Tb, N), np.float32)
    c[:T] = counts
    return np.asarray(_affinity_scores_jit(jnp.asarray(w),
                                           jnp.asarray(c)))[:U]
