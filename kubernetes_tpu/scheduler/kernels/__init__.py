"""Device kernels for the scheduling hot loop."""

from .batch import schedule_batch, filter_score
from .gang import gang_schedule_batch, gang_schedule_reference

__all__ = ["schedule_batch", "filter_score", "gang_schedule_batch",
           "gang_schedule_reference"]
