"""Device kernels for the scheduling hot loop."""

from .batch import schedule_batch, filter_score

__all__ = ["schedule_batch", "filter_score"]
