"""SchedulingQueue — activeQ / backoffQ / unschedulableQ.

Ref: pkg/scheduler/internal/queue/scheduling_queue.go (917 LoC) and
pod_backoff.go. Three sub-queues:
  - activeQ: heap ordered by (priority desc, enqueue-timestamp asc)
    (scheduling_queue.go:157-166)
  - podBackoffQ: heap by backoff expiry; exponential 1s -> 10s cap
    (pod_backoff.go)
  - unschedulableQ: map; flushed to active/backoff when >= 60s old or when a
    cluster event invalidates previous failures (MoveAllToActiveQueue)

The moveRequestCycle / schedulingCycle race repair (:126-133,294-325) is kept:
a pod that failed in a cycle started before the last move request goes to
backoff instead of unschedulable, because an event it never saw might have
made it schedulable.

The TPU extension over the reference is `pop_batch`: the batch collector
drains up to B pods in one call instead of Pop()ing one, preserving the heap's
priority-then-FIFO order — this is what feeds the pods-axis of the kernels.

Gang awareness (`self.gang`, a scheduler.gang.GangManager): a popped pod
whose PodGroup is below minMember is PARKED — it stays pending but leaves
the active heap, so a starved gang cannot head-of-line-block the singleton
pods behind it. The member arrival that completes the gang releases every
parked member inside the same add() critical section, so the next
pop_batch drains the whole gang as one batch. Parked members older than
the park timeout cycle through the unschedulable/backoff machinery (the
slow-path re-evaluation for PodGroups whose spec changed).

Release ordering contract: EVERY path that returns a held pod to the
active heap — backoff expiry, unschedulable flush, gang park release,
move-all events — re-sorts it by (priority, arrival) at release time
(`_push_active` recomputes the pod's CURRENT priority and keeps its
original arrival timestamp), so a released gang can never pop ahead of a
newer higher-priority singleton, and a priority raised while a pod was
held is honored the moment it re-enters the heap. The serving-mode
priority lane reads the same invariant: `lane_depth`/`top_priority` are
maintained per-priority counts of the live heap, so the drain can size an
express batch as exactly the high-priority cohort at the heap's top.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Dict, List, Optional, Tuple

from ..api import helpers
from ..api.core import Pod
from ..api.scheduling import pod_group_key
from ..utils.clock import Clock, REAL_CLOCK
from .gang import ADMIT, PARK_QUOTA

DEFAULT_UNSCHEDULABLE_DURATION = 60.0  # unschedulableQTimeInterval (:49-51)
INITIAL_BACKOFF = 1.0                  # pod_backoff.go initialDuration
MAX_BACKOFF = 10.0                     # pod_backoff.go maxDuration


class PodBackoffMap:
    """Per-pod attempt counter -> exponential backoff (ref: pod_backoff.go)."""

    def __init__(self, clock: Clock):
        self._clock = clock
        self._attempts: Dict[str, int] = {}
        self._last_update: Dict[str, float] = {}

    def boost(self, key: str) -> None:
        self._attempts[key] = self._attempts.get(key, 0) + 1
        self._last_update[key] = self._clock.now()

    def backoff_time(self, key: str) -> float:
        """Absolute time the pod may be retried."""
        n = self._attempts.get(key, 0)
        if n == 0:
            return 0.0
        return self._last_update[key] + min(INITIAL_BACKOFF * 2 ** (n - 1), MAX_BACKOFF)

    def clear(self, key: str) -> None:
        self._attempts.pop(key, None)
        self._last_update.pop(key, None)


class _PodInfo:
    __slots__ = ("pod", "timestamp", "unsched_since")

    def __init__(self, pod: Pod, timestamp: float):
        self.pod = pod
        self.timestamp = timestamp
        #: when the pod entered unschedulableQ (None while elsewhere);
        #: the flush-leftover timer measures THIS stay, not queue age —
        #: keying it to the original enqueue time released long-queued
        #: pods instantly instead of parking them the full interval
        self.unsched_since: Optional[float] = None


class NominatedPodMap:
    """node name -> pods nominated to it by preemption
    (ref: scheduling_queue.go nominatedPodMap). Thread-safe: the informer
    thread mutates it while the scheduling thread reads it to build the
    kernel's reservation tensors; `version` lets readers cache by change."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_node: Dict[str, List[Pod]] = {}
        self._node_of: Dict[str, str] = {}
        self.version = 0

    def add(self, pod: Pod, node_name: str = "") -> None:
        with self._lock:
            self._delete_locked(pod)
            nn = node_name or pod.status.nominated_node_name
            if not nn:
                return
            self._node_of[pod.metadata.key()] = nn
            self._by_node.setdefault(nn, []).append(pod)
            self.version += 1

    def delete(self, pod: Pod) -> None:
        with self._lock:
            self._delete_locked(pod)

    def _delete_locked(self, pod: Pod) -> None:
        key = pod.metadata.key()
        nn = self._node_of.pop(key, None)
        if nn is None:
            return
        pods = self._by_node.get(nn, [])
        self._by_node[nn] = [p for p in pods if p.metadata.key() != key]
        if not self._by_node[nn]:
            del self._by_node[nn]
        self.version += 1

    def pods_for_node(self, node_name: str) -> List[Pod]:
        with self._lock:
            return list(self._by_node.get(node_name, ()))

    def node_for(self, pod_key: str) -> Optional[str]:
        with self._lock:
            return self._node_of.get(pod_key)

    def by_node(self) -> Dict[str, List[Pod]]:
        with self._lock:
            return {n: list(ps) for n, ps in self._by_node.items()}


class SchedulingQueue:
    """The PriorityQueue (ref: scheduling_queue.go:106-138)."""

    def __init__(self, clock: Clock = REAL_CLOCK):
        self._clock = clock
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._seq = itertools.count()  # FIFO tiebreak within equal priority
        # activeQ heap entries: (-priority, timestamp, seq, key)
        self._active: List[Tuple[int, float, int, str]] = []
        # backoffQ heap entries: (expiry, seq, key)
        self._backoff: List[Tuple[float, int, str]] = []
        self._unschedulable: Dict[str, _PodInfo] = {}
        self._pod_info: Dict[str, _PodInfo] = {}
        self._in_active: set = set()
        # key -> the one live heap entry; a priority update re-pushes and
        # repoints this, turning the old tuple into a skipped stale entry
        # (ref: activeQ.Update reorders the heap, scheduling_queue.go:268)
        self._active_entry: Dict[str, Tuple[int, float, int, str]] = {}
        #: live-heap census by priority (stale heap entries excluded):
        #: the serving drain reads it to size priority-lane batches
        self._prio_counts: Dict[int, int] = {}
        self._in_backoff: set = set()
        #: gang-parked pods: pending (in _pod_info) but held off the active
        #: heap until their PodGroup reaches minMember (scheduler/gang.py)
        self._parked: Dict[str, _PodInfo] = {}
        #: GangManager, installed by the scheduler shell; None = no gating
        self.gang = None
        #: observability hooks, installed by the scheduler shell: span
        #: tracer (admit/park/backoff/unschedulable pod milestones), the
        #: per-pod last-failure attribution store, and the park-cause
        #: tally counter (scheduler_unschedulable_reasons_total)
        self.tracer = None
        self.attribution = None
        self.unsched_reasons = None
        self.backoff_map = PodBackoffMap(clock)
        self.nominated = NominatedPodMap()
        self._scheduling_cycle = 0
        self._move_request_cycle = -1
        #: last clock instant the lazy flush ran (see _flush_locked)
        self._last_flush_now: Optional[float] = None
        self._closed = False

    # ----------------------------------------------------------- feeding

    def add(self, pod: Pod) -> None:
        with self._cond:
            key = pod.metadata.key()
            info = _PodInfo(pod, self._clock.now())
            self._pod_info[key] = info
            self._unschedulable.pop(key, None)
            self._in_backoff.discard(key)
            self._parked.pop(key, None)
            self._push_active(key, info)
            self.nominated.add(pod)
            self._gang_notify_locked(pod)
            if self.tracer is not None:
                self.tracer.pod_event("queue", "admit", pod)
            self._cond.notify_all()

    def _gang_notify_locked(self, pod: Pod) -> None:
        """Register a (re)pending pod with the gang manager; an arrival
        that completes its gang releases the parked members right here, so
        the whole gang is poppable before the lock drops."""
        if self.gang is None:
            return
        for rkey in self.gang.pod_pending(pod):
            parked = self._parked.pop(rkey, None)
            if parked is not None:
                self._push_active(rkey, parked)

    def gang_group_changed(self, group_key: str) -> None:
        """A PodGroup appeared or its spec changed: reactivate any parked
        members its (new) minMember now admits."""
        with self._cond:
            if self.gang is None:
                return
            released = self.gang.group_changed(group_key)
            for rkey in released:
                parked = self._parked.pop(rkey, None)
                if parked is not None:
                    self._push_active(rkey, parked)
            if released:
                self._cond.notify_all()

    def update(self, old: Optional[Pod], new: Pod) -> None:
        with self._cond:
            key = new.metadata.key()
            info = self._pod_info.get(key)
            if info is not None:
                old_prio = helpers.pod_priority(info.pod)
                prev_pod = info.pod
                info.pod = new
                self.nominated.add(new)
                if self.gang is not None and \
                        pod_group_key(prev_pod) != pod_group_key(new):
                    # re-labeled into a different (or no) gang: purge the
                    # old membership — its key would otherwise inflate the
                    # old gang's member count forever — and reactivate a
                    # parked pod so the pop gate re-evaluates it fresh
                    self.gang.pod_gone(prev_pod)
                    parked = self._parked.pop(key, None)
                    if parked is not None:
                        self._push_active(key, parked)
                    self._gang_notify_locked(new)
                    self._cond.notify_all()
                if key in self._unschedulable and _spec_changed(old, new):
                    # updated pods get another chance immediately (:268-292)
                    del self._unschedulable[key]
                    self._push_active(key, info)
                    self._cond.notify_all()
                elif key in self._in_active and \
                        helpers.pod_priority(new) != old_prio:
                    # re-heapify: stale entry is invalidated by repointing
                    # _active_entry (ref: activeQ.Update reorders the heap)
                    self._drop_active(key)
                    self._push_active(key, info)
                    self._cond.notify_all()
            else:
                self.add(new)

    def delete(self, pod: Pod) -> None:
        with self._cond:
            key = pod.metadata.key()
            self._pod_info.pop(key, None)
            self._unschedulable.pop(key, None)
            self._drop_active(key)
            self._in_backoff.discard(key)
            self._parked.pop(key, None)
            if self.gang is not None:
                self.gang.pod_gone(pod)
            self.nominated.delete(pod)
            self.backoff_map.clear(key)
            if self.attribution is not None:
                self.attribution.discard(key)

    def _push_active(self, key: str, info: _PodInfo) -> None:
        """(Re)enter the active heap sorted by (priority, arrival): the
        pod's CURRENT priority is read here — at release time, for held
        pods — and its arrival timestamp is preserved, so backoff/park
        release can never order a stale cohort ahead of a newer
        higher-priority pod."""
        if key in self._in_active:
            return
        info.unsched_since = None
        prio = helpers.pod_priority(info.pod)
        entry = (-prio, info.timestamp, next(self._seq), key)
        heapq.heappush(self._active, entry)
        self._active_entry[key] = entry
        self._in_active.add(key)
        self._prio_counts[prio] = self._prio_counts.get(prio, 0) + 1

    def _drop_active(self, key: str) -> None:
        """Remove a pod from the live-heap census; its heap entry goes
        stale (skipped at pop by the _active_entry identity check)."""
        if key not in self._in_active:
            return
        self._in_active.discard(key)
        entry = self._active_entry.pop(key, None)
        if entry is not None:
            prio = -entry[0]
            n = self._prio_counts.get(prio, 0) - 1
            if n > 0:
                self._prio_counts[prio] = n
            else:
                self._prio_counts.pop(prio, None)

    # ----------------------------------------------------------- popping

    @property
    def scheduling_cycle(self) -> int:
        with self._lock:
            return self._scheduling_cycle

    def pop(self, timeout: Optional[float] = None) -> Optional[Pod]:
        pods = self.pop_batch(1, timeout=timeout)
        return pods[0] if pods else None

    def pop_batch(self, max_pods: int, timeout: Optional[float] = None,
                  on_pop=None) -> List[Pod]:
        """Drain up to max_pods from activeQ in priority-then-FIFO order.
        Blocks until at least one pod is available (or timeout/close). Each
        call is one scheduling cycle (the whole batch shares it).

        on_pop(n) runs under the queue lock before the pods are returned, so
        a caller can record them as in-flight atomically with their removal
        from the pending set (idle detection would otherwise see a window
        where popped pods are neither pending nor in-flight)."""
        deadline = None if timeout is None else self._clock.now() + timeout
        with self._cond:
            while True:
                self._flush_locked()
                if self._active or self._closed:
                    break
                wait = 0.05
                if deadline is not None:
                    remaining = deadline - self._clock.now()
                    if remaining <= 0:
                        return []
                    wait = min(wait, remaining)
                self._cond.wait(wait)
            if self._closed and not self._active:
                return []
            self._scheduling_cycle += 1
            out: List[Pod] = []
            while self._active and len(out) < max_pods:
                entry = heapq.heappop(self._active)
                key = entry[3]
                if key not in self._in_active or \
                        self._active_entry.get(key) is not entry:
                    continue  # stale entry (pod deleted or re-prioritized)
                self._drop_active(key)
                info = self._pod_info.get(key)
                if info is None:
                    continue
                if info.pod.metadata.deletion_timestamp is not None:
                    # deleting pods never schedule (ref: scheduleOne skips
                    # pods with a DeletionTimestamp, scheduler.go:445-455)
                    del self._pod_info[key]
                    self.backoff_map.clear(key)
                    self.nominated.delete(info.pod)
                    continue
                verdict = ADMIT if self.gang is None \
                    else self.gang.pop_gate(info.pod)
                if verdict != ADMIT:
                    # gang member held OUT of the heap but kept pending;
                    # a completing arrival, PodGroup change, or freed
                    # quota slot reactivates it. The pods behind it keep
                    # popping — no head-of-line blocking. A quota park
                    # gets its own attribution naming the blocking quota
                    # so it never reads as a scheduler failure.
                    self._parked[key] = info
                    if self.tracer is not None:
                        self.tracer.pod_event("queue", "park", info.pod)
                    if verdict == PARK_QUOTA:
                        block = self.gang.quota_block_for(info.pod)
                        reason = "QuotaExhausted"
                        msg = block.message(pod_group_key(info.pod)) \
                            if block is not None else \
                            f"gang {pod_group_key(info.pod)} parked: " \
                            f"active-gang quota exhausted"
                    else:
                        reason = "PodGroupNotReady"
                        msg = (f"gang {pod_group_key(info.pod)} below "
                               f"minMember; parked off the active heap")
                    if self.unsched_reasons is not None:
                        self.unsched_reasons.inc(reason=reason)
                    if self.attribution is not None:
                        self.attribution.record(
                            key, reason, msg,
                            cycle=self._scheduling_cycle)
                    continue
                # popped pods leave the pending set; a failed attempt re-adds
                # them via add_unschedulable_if_not_present (ref: Pop removes
                # from activeQ; in-flight pods live only in the cycle)
                del self._pod_info[key]
                out.append(info.pod)
            if on_pop is not None and out:
                on_pop(len(out))
            return out

    # ------------------------------------------------- failure / requeue

    def add_unschedulable_if_not_present(self, pod: Pod, pod_scheduling_cycle: int
                                         ) -> None:
        """Ref: AddUnschedulableIfNotPresent (:294-325). If a move request
        arrived during this pod's cycle, it goes to backoff (retry soon) rather
        than parking in unschedulableQ."""
        with self._cond:
            key = pod.metadata.key()
            if key in self._in_active or key in self._in_backoff:
                return
            info = self._pod_info.get(key)
            if info is None:
                info = _PodInfo(pod, self._clock.now())
                self._pod_info[key] = info
            info.pod = pod
            self.backoff_map.boost(key)
            self.nominated.add(pod)
            if self._move_request_cycle >= pod_scheduling_cycle:
                self._push_backoff(key)
                if self.tracer is not None:
                    self.tracer.pod_event("queue", "backoff", pod)
            else:
                info.unsched_since = self._clock.now()
                self._unschedulable[key] = info
                if self.tracer is not None:
                    self.tracer.pod_event("queue", "unschedulable", pod)
            self._gang_notify_locked(pod)
            self._cond.notify_all()

    def _push_backoff(self, key: str) -> None:
        expiry = self.backoff_map.backoff_time(key)
        heapq.heappush(self._backoff, (expiry, next(self._seq), key))
        self._in_backoff.add(key)

    def move_all_to_active_queue(self) -> None:
        """A cluster event may have made unschedulable pods schedulable
        (ref: MoveAllToActiveQueue — still-in-backoff pods go to backoffQ)."""
        with self._cond:
            for key, info in list(self._unschedulable.items()):
                if self.backoff_map.backoff_time(key) > self._clock.now():
                    self._push_backoff(key)
                else:
                    self._push_active(key, info)
            self._unschedulable.clear()
            self._move_request_cycle = self._scheduling_cycle
            self._cond.notify_all()

    def assigned_pod_updated(self, pod: Pod) -> None:
        """An assigned pod changed; pods with affinity may now fit
        (ref: movePodsToActiveQueue on AssignedPodAdded/Updated)."""
        self.move_all_to_active_queue()

    def _flush_locked(self) -> None:
        """flushBackoffQCompleted (1s ticker) + flushUnschedulableQLeftover
        (30s ticker) collapsed into lazy flushing at pop time. Idempotent
        per clock instant: every hold created at time T expires strictly
        after T (backoff >= +1s, unschedulable +60s, park +PARK_TIMEOUT),
        so a repeat flush at the same `now` can release nothing — skipped,
        which spares the adaptive drain's drain_stats+pop_batch pair the
        second O(unschedulable) scan per cycle."""
        now = self._clock.now()
        if now == self._last_flush_now:
            return
        self._last_flush_now = now
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            if key not in self._in_backoff:
                continue
            self._in_backoff.discard(key)
            info = self._pod_info.get(key)
            if info is not None:
                self._push_active(key, info)
        for key, info in list(self._unschedulable.items()):
            since = info.unsched_since if info.unsched_since is not None \
                else info.timestamp
            if now - since >= DEFAULT_UNSCHEDULABLE_DURATION:
                del self._unschedulable[key]
                if self.backoff_map.backoff_time(key) > now:
                    self._push_backoff(key)
                else:
                    self._push_active(key, info)
        if self.gang is not None and self._parked:
            # quota fast path: an active-gang slot freed since the last
            # flush reactivates quota-parked gangs immediately (pop_gate
            # re-checks the quota, so an unlucky gang just re-parks)
            for key in self.gang.quota_released():
                info = self._parked.pop(key, None)
                if info is not None:
                    self._push_active(key, info)
            # starved gang slow path: long-parked members cycle through the
            # standard backoff machinery (boosted, so repeats decay) and
            # re-park on pop if their gang is still short
            for key in self.gang.expired_parked(now):
                info = self._parked.pop(key, None)
                if info is not None:
                    self.backoff_map.boost(key)
                    self._push_backoff(key)

    # ----------------------------------------------------------- admin

    def pending_pods(self) -> List[Pod]:
        with self._lock:
            return [i.pod for i in self._pod_info.values()]

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pod_info)

    # ------------------------------------------------ lane introspection

    def active_depth(self) -> int:
        """Pods poppable RIGHT NOW (expired backoff/unschedulable holds
        are flushed first) — the queue-depth signal the serving drain's
        adaptive batch sizing reads."""
        with self._lock:
            self._flush_locked()
            return len(self._in_active)

    def lane_depth(self, min_priority: int) -> int:
        """How many poppable pods sit at/above `min_priority` — the
        express-lane cohort size. They are by construction the heap's
        top, so a pop of at least this many always drains the whole
        lane first (a cap floored above the cohort pops bulk pods
        behind it in the same batch)."""
        with self._lock:
            self._flush_locked()
            return sum(n for p, n in self._prio_counts.items()
                       if p >= min_priority)

    def drain_stats(self, min_priority: int) -> Tuple[int, int]:
        """(active_depth, lane_depth) under ONE lock with ONE lazy
        flush — the adaptive drain reads both every cycle, and separate
        calls would repeat the O(unschedulable) flush scan on the hot
        path."""
        with self._lock:
            self._flush_locked()
            lane = sum(n for p, n in self._prio_counts.items()
                       if p >= min_priority)
            return len(self._in_active), lane

    def top_priority(self) -> Optional[int]:
        """Highest priority among poppable pods (None when idle)."""
        with self._lock:
            self._flush_locked()
            return max(self._prio_counts) if self._prio_counts else None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


def _spec_changed(old: Optional[Pod], new: Pod) -> bool:
    if old is None:
        return True
    return (old.spec != new.spec or
            old.metadata.labels != new.metadata.labels or
            old.status.nominated_node_name != new.status.nominated_node_name)
