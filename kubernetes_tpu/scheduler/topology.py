"""Incremental topology-bucket index for inter-pod (anti-)affinity.

The M3 component of the north-star redesign (SURVEY §7.4). The reference
rebuilds its `topologyPairsMaps` from scratch for EVERY pod in EVERY
scheduling cycle by scanning every pod on every node
(pkg/scheduler/algorithm/predicates/metadata.go:71-94 — O(nodes × pods ×
terms) per attempt, the cost the 16-way ParallelizeUntil fan-out exists to
hide). Here the same maps are maintained INCREMENTALLY from the scheduler
cache's dirty-node feed (the same O(Δ) generation scan that drives the
tensor mirror) as sparse (term × topology-domain) count matrices:

    term      = interned (namespaces, selector, topologyKey) — the unit the
                reference re-derives per pod; pods stamped from one
                controller template share every term
    domain    = interned (topologyKey, value) bucket — "zone-3",
                "host node-17" (ref: the (topologyKey, value) pairs of
                topologyPairsMaps)
    counts    = #pods matching a term per domain (match side) and
                #pods carrying a term per domain (carry side, weighted for
                preferred terms)

A batch then evaluates required (anti-)affinity for ALL its constraint
templates at once: per-term count vectors are gathered over the node→domain
arrays into [T, N] presence matrices and combined per template — on host
numpy for small T, or as masked matmuls on device
(kernels/affinity.py) when templates × nodes is large. Either way the
per-batch cost is O(T·N) array work instead of O(templates × nodes × pods)
python, and the cluster-wide scan is gone entirely.

Semantics parity: predicates.match_inter_pod_affinity /
priorities.interpod_affinity_scores over a fresh PredicateMetadata are the
oracle; tests/test_topology.py fuzzes this module against them.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..api import labels as labelsmod
from ..api.core import Pod
from ..api.meta import LabelSelector

# count matrices maintained per term (carry side: pods CARRYING the term;
# match side: pods MATCHED by the term)
K_MATCH = "match"            # match counts (required + preferred, own terms)
K_CARRY_ANTI = "carry_anti"  # pods carrying the term as required anti-affinity
K_CARRY_AFF = "carry_aff"    # ... as required affinity (symmetric hard credit)
K_CARRY_PAFF = "carry_paff"  # preferred affinity, weight-summed
K_CARRY_PANTI = "carry_panti"  # preferred anti-affinity, weight-summed

#: route template evaluation through the device matmul kernel above this
#: many (templates × terms × nodes) f32 ops. Host BLAS handles hundreds of
#: MFLOPs faster than a device round trip over the tunnel; the MXU wins
#: once distinct selectors per batch grow into the thousands
DEVICE_EVAL_THRESHOLD = 2_000_000_000


class _Term:
    """One interned (namespaces, selector, topologyKey) term."""

    __slots__ = ("tid", "tk", "namespaces", "selector", "match_registered")

    def __init__(self, tid: int, tk: str, namespaces: Tuple[str, ...],
                 selector: Optional[LabelSelector]):
        self.tid = tid
        self.tk = tk
        self.namespaces = namespaces
        self.selector = selector
        #: match counts are maintained only after a query-side registration
        #: (ensure_match backfills, then the incremental feed keeps it fresh)
        self.match_registered = False

    def matches_pod(self, pod: Pod) -> bool:
        return pod.metadata.namespace in self.namespaces and \
            labelsmod.matches(self.selector, pod.metadata.labels)


class _NodeRec:
    """Per-node bookkeeping for incremental updates."""

    __slots__ = ("labels", "pods", "contrib")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        # pod key -> (resourceVersion fingerprint, pod ref)
        self.pods: Dict[str, Tuple[str, Pod]] = {}
        # pod key -> [(kind, tid, dom, weight)] — exactly what was added to
        # the count matrices for this pod, so removal is an exact inverse
        self.contrib: Dict[str, List[Tuple[str, int, int, float]]] = {}


class AffinityProfile:
    """One constraint template's resolved terms (the batch-evaluation unit;
    pods sharing a residual signature share the profile)."""

    __slots__ = ("req_aff", "req_anti", "carried_anti", "constrained")

    def __init__(self):
        self.req_aff: List[Tuple[int, bool]] = []   # (tid, waived)
        self.req_anti: List[int] = []
        self.carried_anti: List[int] = []           # carry-side tids matching the pod
        self.constrained = False


class TopologyIndex:
    def __init__(self, mirror):
        self.mirror = mirror  # row_of / capacity alignment for [N] vectors
        self._terms: Dict[Tuple, _Term] = {}
        self._by_id: List[_Term] = []
        # tk -> (value -> per-tk domain id); doms never shrink
        self._doms: Dict[str, Dict[str, int]] = {}
        # tk -> [capacity] int32 node-row -> dom id (-1 = label absent)
        self._node_dom: Dict[str, np.ndarray] = {}
        # kind -> tid -> (dom -> count/weight)
        self._counts: Dict[str, Dict[int, Dict[int, float]]] = {
            K_MATCH: {}, K_CARRY_ANTI: {}, K_CARRY_AFF: {},
            K_CARRY_PAFF: {}, K_CARRY_PANTI: {}}
        self._nodes: Dict[str, _NodeRec] = {}
        #: bumped on every mutating apply; invalidates materialized vectors
        self.version = 0
        #: bumped only when a node->domain mapping changes (node add /
        #: relabel / row reuse, new domain value, new topology key) — the
        #: invalidation key for cached [T, N] term tables, which pod-only
        #: churn (the steady-state batch stream) never touches
        self.dom_epoch = 0
        #: bumped only on profile-relevant transitions: a new registered
        #: term, a match total crossing zero (waived bits), or the set of
        #: ACTIVE required-anti carry terms changing (carried_anti lists).
        #: Per-pod count increments beyond the first never bump it, so
        #: template profiles cache across a whole drain
        self.profile_epoch = 0
        #: registered-term match totals, maintained incrementally for the
        #: zero-crossing detection above
        self._match_total: Dict[int, float] = {}
        self._anti_active: Set[int] = set()
        #: term-id tuple -> (dom_epoch, capacity, [T, N] dom table, n_doms)
        self._table_cache: Dict[Tuple, Tuple[int, int, np.ndarray, int]] = {}
        self.table_builds = 0
        self.table_hits = 0
        #: (term-id tuple, padded T) -> (dom_epoch, capacity, device
        #: table sharded by the name rules, n_doms) — the sharded drain's
        #: upload cache: repeat batches over a stable node topology reuse
        #: ONE device-resident [T, N] table instead of re-uploading per
        #: batch (term_table_device)
        self._table_dev_cache: Dict[Tuple, Tuple[int, int, object, int]] = {}
        self.table_dev_builds = 0
        self.table_dev_hits = 0
        self._vec_cache: Dict[Tuple, np.ndarray] = {}
        self._vec_cache_version = -1
        #: (kind, tid) -> [capacity] bool "some pod of `kind` sits in this
        #: node's domain" — the required_masks building block, maintained
        #: INCREMENTALLY from (term, domain) count zero-crossings instead
        #: of being regathered from count vectors every batch. Pod churn
        #: that only moves a count between two positive values touches
        #: nothing; a 0<->positive crossing rewrites the crossing domain's
        #: rows of the one affected vector. Node-topology changes
        #: (dom_epoch) and capacity growth invalidate wholesale.
        self._presence: Dict[Tuple[str, int], np.ndarray] = {}
        #: per-vector change counters (the mask-row cache's dependency key)
        self._presence_ver: Dict[Tuple[str, int], int] = {}
        self._presence_key: Tuple[int, int] = (-1, -1)
        #: bumped on every wholesale presence invalidation (dom_epoch /
        #: capacity) so stale mask-row deps can never alias fresh ones
        self._presence_gen = 0
        #: profile-term-content -> (deps, [capacity] bool row): the final
        #: per-template [N] mask row, reused across batches while none of
        #: its terms' presence vectors moved — the steady-state cost of
        #: required_masks drops to dict lookups
        self._mask_row_cache: Dict[Tuple, Tuple[Tuple, np.ndarray]] = {}
        self.mask_row_builds = 0
        self.mask_row_hits = 0
        # (namespace, labels-canon) -> frozenset of matching tids; pods
        # stamped from one template share the entry, so selector matching
        # runs once per template, not once per pod (invalidated when the
        # term table grows)
        self._match_cache: Dict[Tuple, frozenset] = {}
        self._match_cache_nterms = 0
        #: lazy activation: an affinity-free cluster pays only a cheap
        #: `spec.affinity is not None` scan per dirty node — the per-pod
        #: rv-diff bookkeeping starts at the FIRST affinity carrier or
        #: term (one O(cluster) rebuild), not on every uniform batch
        self._active = False
        self._last_snapshot = None

    # ------------------------------------------------------------ interning

    def _intern(self, tk: str, namespaces: Tuple[str, ...],
                selector: Optional[LabelSelector]) -> _Term:
        key = (tk, tuple(sorted(namespaces)),
               labelsmod.canonical_selector(selector))
        term = self._terms.get(key)
        if term is None:
            term = _Term(len(self._by_id), tk, tuple(sorted(namespaces)),
                         selector)
            self._terms[key] = term
            self._by_id.append(term)
            if tk not in self._doms:
                self._doms[tk] = {}
                nd = np.full((self.mirror.t.capacity,), -1, np.int32)
                for name, rec in self._nodes.items():
                    row = self.mirror.row_of.get(name)
                    if row is not None:
                        nd[row] = self._dom_id(tk, rec.labels.get(tk))
                self._node_dom[tk] = nd
                self.dom_epoch += 1
        return term

    def _dom_id(self, tk: str, value: Optional[str]) -> int:
        if value is None:
            return -1
        doms = self._doms[tk]
        d = doms.get(value)
        if d is None:
            d = len(doms)
            doms[value] = d
            self.dom_epoch += 1  # new domain: n_domains in tables grew
        return d

    def match_set(self, pod: Pod) -> frozenset:
        """tids of ALL interned terms matching this pod, cached per
        (namespace, labels) template."""
        key = (pod.metadata.namespace,
               tuple(sorted(pod.metadata.labels.items())))
        if self._match_cache_nterms != len(self._by_id):
            self._match_cache.clear()
            self._match_cache_nterms = len(self._by_id)
        hit = self._match_cache.get(key)
        if hit is None:
            hit = frozenset(t.tid for t in self._by_id if t.matches_pod(pod))
            self._match_cache[key] = hit
        return hit

    def _resolved_ns(self, term, owner: Pod) -> Tuple[str, ...]:
        """Empty namespaces means the term owner's namespace (ref:
        priorityutil.PodMatchesTermsNamespaceAndSelector)."""
        return tuple(term.namespaces) if term.namespaces \
            else (owner.metadata.namespace,)

    def ensure_match(self, tk: str, namespaces: Tuple[str, ...],
                     selector: Optional[LabelSelector]) -> _Term:
        """Register a term for match-count maintenance, backfilling from the
        pods the index already holds (one O(pods) scan per NEW term — the
        amortized replacement for the reference's per-cycle full scan)."""
        # a term arriving from a PENDING pod is the other activation edge:
        # the index must hold records before the backfill scan below
        self._activate()
        term = self._intern(tk, namespaces, selector)
        if term.match_registered:
            return term
        term.match_registered = True
        counts = self._counts[K_MATCH].setdefault(term.tid, {})
        total = 0.0
        for name, rec in self._nodes.items():
            dom = self._dom_id(tk, rec.labels.get(tk))
            if dom < 0:
                continue
            for key, (_rv, pod) in rec.pods.items():
                if term.matches_pod(pod):
                    counts[dom] = counts.get(dom, 0) + 1
                    total += 1.0
                    rec.contrib.setdefault(key, []).append(
                        (K_MATCH, term.tid, dom, 1.0))
        if total:
            self._match_total[term.tid] = \
                self._match_total.get(term.tid, 0) + total
        self.version += 1
        #: a newly registered term starts maintaining counts: profiles
        #: resolved before this registration never referenced it, but the
        #: bump keeps the invariant simple (registration is rare — once
        #: per new template term, not per batch)
        self.profile_epoch += 1
        return term

    # ------------------------------------------------------ incremental feed

    def apply(self, snapshot, dirty_names) -> None:
        """Consume the cache's dirty-node list (call right after
        TensorMirror.apply — row_of must already reflect the delta)."""
        self._last_snapshot = snapshot
        if not self._active:
            if not self._dirty_has_affinity(snapshot, dirty_names):
                return
            self._activate()  # rebuilds from the FULL snapshot
            return
        self._apply_records(snapshot, dirty_names)

    def _dirty_has_affinity(self, snapshot, dirty_names) -> bool:
        for name in dirty_names:
            ni = snapshot.node_infos.get(name)
            if ni is None:
                continue
            for p in ni.pods:
                aff = p.spec.affinity
                if aff is not None and (aff.pod_affinity is not None or
                                        aff.pod_anti_affinity is not None):
                    return True
        return False

    def _activate(self) -> None:
        """First affinity carrier/term seen: switch to incremental
        maintenance, seeded by one full-cluster pass."""
        if self._active:
            return
        self._active = True
        snap = self._last_snapshot
        if snap is not None:
            self._apply_records(snap, list(snap.node_infos))

    def _apply_records(self, snapshot, dirty_names) -> None:
        changed = False
        for name in dirty_names:
            ni = snapshot.node_infos.get(name)
            if ni is None or ni.node is None:
                changed |= self._drop_node(name)
                continue
            labels = ni.node.metadata.labels
            rec = self._nodes.get(name)
            if rec is not None and rec.labels != labels:
                # topology labels moved: every contribution's dom is stale
                self._drop_node(name)
                rec = None
                changed = True
            if rec is None:
                rec = _NodeRec(dict(labels))
                self._nodes[name] = rec
                changed = True
            row = self.mirror.row_of.get(name)
            if row is not None:
                for tk, nd in self._node_dom.items():
                    if len(nd) < self.mirror.t.capacity:
                        grown = np.full((self.mirror.t.capacity,), -1,
                                        np.int32)
                        grown[:len(nd)] = nd
                        nd = self._node_dom[tk] = grown
                    new_dom = self._dom_id(tk, labels.get(tk))
                    if nd[row] != new_dom:
                        nd[row] = new_dom
                        self.dom_epoch += 1  # row's domain moved
            # pod diff by (key, resourceVersion): rebinds/updates recompute,
            # untouched pods keep their recorded contributions
            fresh = {p.metadata.key(): (p.metadata.resource_version, p)
                     for p in ni.pods}
            for key in list(rec.pods):
                if fresh.get(key, (None,))[0] != rec.pods[key][0]:
                    self._sub_pod(rec, key)
                    changed = True
            for key, (rv, pod) in fresh.items():
                if key not in rec.pods:
                    self._add_pod(rec, key, rv, pod)
                    changed = True
        if changed:
            self.version += 1

    def _drop_node(self, name: str) -> bool:
        rec = self._nodes.pop(name, None)
        if rec is None:
            return False
        for key in list(rec.pods):
            self._sub_pod(rec, key)
        return True

    def _sub_pod(self, rec: _NodeRec, key: str) -> None:
        rec.pods.pop(key, None)
        for kind, tid, dom, w in rec.contrib.pop(key, ()):
            counts = self._counts[kind].get(tid)
            if counts is None:
                continue
            v = counts.get(dom, 0) - w
            if v <= 0:
                counts.pop(dom, None)
                self._presence_update(kind, tid, dom, False)
            else:
                counts[dom] = v
            if kind == K_MATCH:
                t = self._match_total.get(tid, 0) - w
                if t <= 0:
                    self._match_total.pop(tid, None)
                    self.profile_epoch += 1  # waived bits may flip back
                else:
                    self._match_total[tid] = t
            elif kind == K_CARRY_ANTI and not counts and \
                    tid in self._anti_active:
                self._anti_active.discard(tid)
                self.profile_epoch += 1  # carried_anti lists shrink

    def _add_pod(self, rec: _NodeRec, key: str, rv: str, pod: Pod) -> None:
        rec.pods[key] = (rv, pod)
        contrib: List[Tuple[str, int, int, float]] = []

        def credit(kind: str, term: _Term, dom: int, w: float) -> None:
            counts = self._counts[kind].setdefault(term.tid, {})
            prev = counts.get(dom, 0)
            counts[dom] = prev + w
            if prev <= 0:
                self._presence_update(kind, term.tid, dom, True)
            contrib.append((kind, term.tid, dom, w))
            if kind == K_MATCH:
                t = self._match_total.get(term.tid)
                if t is None:
                    self.profile_epoch += 1  # total crossed zero: waived
                    self._match_total[term.tid] = w
                else:
                    self._match_total[term.tid] = t + w
            elif kind == K_CARRY_ANTI and term.tid not in self._anti_active:
                self._anti_active.add(term.tid)
                self.profile_epoch += 1  # carried_anti lists grow

        aff = pod.spec.affinity
        if aff is not None:
            pa, paa = aff.pod_affinity, aff.pod_anti_affinity
            for kind, terms in (
                    (K_CARRY_AFF, pa.required_during_scheduling_ignored_during_execution if pa else ()),
                    (K_CARRY_ANTI, paa.required_during_scheduling_ignored_during_execution if paa else ())):
                for t in terms or ():
                    term = self._intern(
                        t.topology_key, self._resolved_ns(t, pod),
                        t.label_selector)
                    dom = self._dom_id(term.tk, rec.labels.get(term.tk))
                    if dom >= 0:
                        credit(kind, term, dom, 1.0)
            for kind, wterms in (
                    (K_CARRY_PAFF, pa.preferred_during_scheduling_ignored_during_execution if pa else ()),
                    (K_CARRY_PANTI, paa.preferred_during_scheduling_ignored_during_execution if paa else ())):
                for wt in wterms or ():
                    t = wt.pod_affinity_term
                    term = self._intern(
                        t.topology_key, self._resolved_ns(t, pod),
                        t.label_selector)
                    dom = self._dom_id(term.tk, rec.labels.get(term.tk))
                    if dom >= 0 and wt.weight:
                        credit(kind, term, dom, float(wt.weight))
        for tid in self.match_set(pod):
            term = self._by_id[tid]
            if term.match_registered:
                dom = self._dom_id(term.tk, rec.labels.get(term.tk))
                if dom >= 0:
                    credit(K_MATCH, term, dom, 1.0)
        if contrib:
            rec.contrib[key] = contrib

    # ------------------------------------------------------------- queries

    def has_required_anti_carriers(self) -> bool:
        """True when any pod in the cluster carries required anti-affinity —
        the only carried constraint that can mask OTHER pods' feasibility."""
        return any(self._counts[K_CARRY_ANTI].values())

    def has_score_carriers(self) -> bool:
        """True when any carried term can contribute to the inter-pod
        affinity PRIORITY: required affinity (symmetric hard credit) or
        preferred terms. Required anti-affinity carriers mask feasibility
        but never score — a cluster holding only those skips the static
        scorer entirely."""
        c = self._counts
        return (any(c[K_CARRY_AFF].values()) or any(c[K_CARRY_PAFF].values())
                or any(c[K_CARRY_PANTI].values()))

    def dom_of(self, node_name: str, tk: str) -> int:
        rec = self._nodes.get(node_name)
        if rec is None or tk not in self._doms:
            return -1
        val = rec.labels.get(tk)
        if val is None:
            return -1  # label absent ≠ empty-string label value
        return self._doms[tk].get(val, -1)

    def term(self, tid: int) -> _Term:
        return self._by_id[tid]

    def required_profile(self, pod: Pod) -> AffinityProfile:
        """Resolve a pod template's required-(anti-)affinity evaluation plan
        (registers match terms as needed)."""
        prof = AffinityProfile()
        aff = pod.spec.affinity
        if aff is not None and aff.pod_affinity is not None:
            for t in aff.pod_affinity.required_during_scheduling_ignored_during_execution or ():
                term = self.ensure_match(
                    t.topology_key, self._resolved_ns(t, pod),
                    t.label_selector)
                total = sum(self._counts[K_MATCH].get(term.tid, {}).values())
                # special case (predicates.go:1476-1497 / the oracle's
                # match_inter_pod_affinity): a term matching the incoming pod
                # itself with no match anywhere is waived (first pod of a
                # self-affine group can land; the node still needs the key)
                waived = total == 0 and term.matches_pod(pod)
                prof.req_aff.append((term.tid, waived))
                prof.constrained = True
        if aff is not None and aff.pod_anti_affinity is not None:
            for t in aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution or ():
                term = self.ensure_match(
                    t.topology_key, self._resolved_ns(t, pod),
                    t.label_selector)
                prof.req_anti.append(term.tid)
                prof.constrained = True
        if any(self._counts[K_CARRY_ANTI].values()):
            mset = self.match_set(pod)
            for tid, counts in self._counts[K_CARRY_ANTI].items():
                if counts and tid in mset:
                    prof.carried_anti.append(tid)
                    prof.constrained = True
        return prof

    def _presence_sync(self) -> bool:
        """Wholesale-invalidate the presence vectors when the node->domain
        layout or the row capacity moved (the only changes the per-domain
        delta updates cannot express). Returns True when a flush happened."""
        key = (self.dom_epoch, self.mirror.t.capacity)
        if self._presence_key == key:
            return False
        self._presence_key = key
        self._presence.clear()
        self._presence_ver.clear()
        self._presence_gen += 1
        return True

    def _presence_update(self, kind: str, tid: int, dom: int,
                         present: bool) -> None:
        """A (term, domain) count crossed zero: rewrite that domain's rows
        of the materialized presence vector (if one exists). O(N) per
        CROSSING — steady pod churn within occupied domains costs zero,
        where the per-batch regather this replaces paid O(terms × N)
        per batch unconditionally."""
        if self._presence_key != (self.dom_epoch, self.mirror.t.capacity):
            return  # stale wholesale; the next access rebuilds anyway
        vec = self._presence.get((kind, tid))
        if vec is None:
            return
        nd = self._node_dom_vec(self._by_id[tid].tk)
        vec[nd[:len(vec)] == dom] = present
        self._presence_ver[(kind, tid)] = \
            self._presence_ver.get((kind, tid), 0) + 1

    def presence_vec(self, kind: str, tid: int) -> np.ndarray:
        """[capacity] bool — `kind` count > 0 in this node's domain for
        term `tid` (False where the topology label is absent). Built once,
        then maintained by _presence_update deltas. Callers must not
        mutate the returned array."""
        self._presence_sync()
        key = (kind, tid)
        vec = self._presence.get(key)
        if vec is not None:
            return vec
        term = self._by_id[tid]
        nd = self._node_dom_vec(term.tk)
        cap = self.mirror.t.capacity
        counts = self._counts[kind].get(tid)
        if not counts:
            vec = np.zeros((cap,), bool)
        else:
            ndom = len(self._doms[term.tk])
            dense = np.zeros((ndom + 1,), bool)
            for dom, v in counts.items():
                dense[dom] = v > 0
            vec = dense[np.where(nd >= 0, nd, ndom)[:cap]]
        self._presence[key] = vec
        self._presence_ver.setdefault(key, 0)
        return vec

    def _vec(self, kind: str, tid: int) -> np.ndarray:
        """[capacity] f32 counts of `kind` for term `tid`, gathered over the
        term's topology-key node→domain array. Cached per index version."""
        if self._vec_cache_version != self.version:
            self._vec_cache.clear()
            self._vec_cache_version = self.version
        key = (kind, tid)
        hit = self._vec_cache.get(key)
        if hit is not None and len(hit) == self.mirror.t.capacity:
            return hit
        term = self._by_id[tid]
        nd = self._node_dom_vec(term.tk)
        counts = self._counts[kind].get(tid)
        if not counts:
            vec = np.zeros((self.mirror.t.capacity,), np.float32)
        else:
            ndom = len(self._doms[term.tk])
            dense = np.zeros((ndom + 1,), np.float32)
            for dom, v in counts.items():
                dense[dom] = v
            vec = dense[np.where(nd >= 0, nd, ndom)]
        self._vec_cache[key] = vec
        return vec

    def _node_dom_vec(self, tk: str) -> np.ndarray:
        nd = self._node_dom.get(tk)
        cap = self.mirror.t.capacity
        if nd is None:
            # tk interned but never registered through _intern's dom init
            self._doms.setdefault(tk, {})
            nd = np.full((cap,), -1, np.int32)
            for name, rec in self._nodes.items():
                row = self.mirror.row_of.get(name)
                if row is not None:
                    nd[row] = self._dom_id(tk, rec.labels.get(tk))
            self._node_dom[tk] = nd
        elif len(nd) < cap:
            grown = np.full((cap,), -1, np.int32)
            grown[:len(nd)] = nd
            nd = self._node_dom[tk] = grown
        return nd

    def has_dom_vec(self, tk: str) -> np.ndarray:
        return self._node_dom_vec(tk) >= 0

    def term_table(self, terms: Tuple[int, ...],
                   use_cache: bool = True) -> Tuple[np.ndarray, int]:
        """([T, capacity] int32 node->domain row per term, n_domains) for
        an in-scan term set — the host half of the kernel's (anti-)affinity
        tables. Cached by (term tuple, dom_epoch, capacity): pod churn
        between batches never rebuilds it, only an actual node-topology
        change does (the O(epoch changes) rebuild contract the bench's
        phase breakdown asserts). Callers must not mutate the returned
        array (PodBatchTensors copies it into padded device tables)."""
        cap = self.mirror.t.capacity
        if use_cache:
            hit = self._table_cache.get(terms)
            if hit is not None and hit[0] == self.dom_epoch \
                    and hit[1] == cap:
                self.table_hits += 1
                return hit[2], hit[3]
        T = len(terms)
        dom = np.full((T, cap), -1, np.int32)
        n_domains = 1
        for j, tid in enumerate(terms):
            term = self._by_id[tid]
            # _node_dom_vec handles missing/short entries (capacity-sized,
            # -1 for label-absent rows)
            nd = self._node_dom_vec(term.tk)
            dom[j] = nd[:cap]
            if len(nd):
                n_domains = max(n_domains, int(nd.max()) + 1)
        self.table_builds += 1
        if use_cache:
            if len(self._table_cache) > 64:
                self._table_cache.clear()
            self._table_cache[terms] = (self.dom_epoch, cap, dom, n_domains)
        return dom, n_domains

    def term_table_device(self, terms: Tuple[int, ...], mesh,
                          use_cache: bool = True, dom=None,
                          n_domains: Optional[int] = None):
        """(padded [T, capacity] dom table ON DEVICE sharded by the
        name-keyed rules, n_domains) — the device half of term_table for
        the sharded drain. T is bucketed exactly like
        PodBatchTensors.set_topology_terms (power of two, min 8) so the
        cached upload can be handed to it as dom_dev. Epoch-cached with
        the same (dom_epoch, capacity) key as the host table: steady
        pod churn re-uses one device-resident table across every batch
        of a drain; only a node-topology change re-uploads. A caller
        that already built the host table passes (dom, n_domains) so a
        cache-disabled run (KTPU_TOPO_TABLE_CACHE=0) does not build it
        twice."""
        from .sharding import put
        from .tensorize import _bucket
        cap = self.mirror.t.capacity
        T = _bucket(len(terms), minimum=8)
        key = (terms, T)
        if use_cache:
            hit = self._table_dev_cache.get(key)
            if hit is not None and hit[0] == self.dom_epoch \
                    and hit[1] == cap:
                self.table_dev_hits += 1
                return hit[2], hit[3]
        if dom is None or n_domains is None:
            dom, n_domains = self.term_table(terms, use_cache=use_cache)
        dom_p = np.full((T, cap), -1, np.int32)
        dom_p[:dom.shape[0]] = dom
        dev = put(mesh, "anti_dom", dom_p)
        self.table_dev_builds += 1
        if use_cache:
            if len(self._table_dev_cache) > 64:
                self._table_dev_cache.clear()
            self._table_dev_cache[key] = (self.dom_epoch, cap, dev,
                                          n_domains)
        return dev, n_domains

    def node_domain_vector(self, tk: str) -> np.ndarray:
        """[capacity] int32 node-row -> topology-domain id for `tk` (-1
        where the node lacks the label). The gang scheduler's ICI-domain
        constraint (kernels/gang.py) rides the same incrementally-
        maintained node→domain arrays the (anti-)affinity masks gather
        over. Forces activation: domain interning needs per-node records
        even in an affinity-free cluster."""
        self._activate()
        self._doms.setdefault(tk, {})
        return self._node_dom_vec(tk)

    def _profile_mask_row(self, prof: AffinityProfile) -> np.ndarray:
        """One profile's [capacity] feasible-node mask from the
        incrementally maintained presence vectors, cached until any of
        its terms' vectors move (a count-delta zero-crossing or a
        wholesale node-topology flush). Steady-state batches pay dict
        lookups instead of the O(k·N) boolean recombination; callers
        must not mutate the returned row."""
        self._presence_sync()   # settle the gen BEFORE recording deps
        key = (tuple(prof.req_aff), tuple(prof.req_anti),
               tuple(prof.carried_anti))
        deps = [self._presence_gen, self.mirror.t.capacity]
        for tid, _waived in prof.req_aff:
            deps.append(self._presence_ver.get((K_MATCH, tid), 0))
        for tid in prof.req_anti:
            deps.append(self._presence_ver.get((K_MATCH, tid), 0))
        for tid in prof.carried_anti:
            deps.append(self._presence_ver.get((K_CARRY_ANTI, tid), 0))
        deps = tuple(deps)
        hit = self._mask_row_cache.get(key)
        if hit is not None and hit[0] == deps:
            self.mask_row_hits += 1
            return hit[1]
        row = np.ones((self.mirror.t.capacity,), bool)
        for tid, waived in prof.req_aff:
            # presence is False wherever the label is absent, but a
            # WAIVED term still requires the node to carry the key
            row &= self.has_dom_vec(self._by_id[tid].tk)
            if not waived:
                row &= self.presence_vec(K_MATCH, tid)
        for tid in prof.req_anti:
            row &= ~self.presence_vec(K_MATCH, tid)
        for tid in prof.carried_anti:
            row &= ~self.presence_vec(K_CARRY_ANTI, tid)
        if len(self._mask_row_cache) > 4096:
            self._mask_row_cache.clear()
        self._mask_row_cache[key] = (deps, row)
        self.mask_row_builds += 1
        return row

    def required_masks(self, profiles: List[AffinityProfile]) -> np.ndarray:
        """[U, capacity] bool — each profile's feasible-node mask, from
        the incrementally maintained (term, domain) presence vectors
        (count-delta zero-crossings, not per-batch regathers). Routes
        through the device matmul kernel (kernels/affinity.py) when
        templates × terms × nodes is big enough for the MXU to win.
        Callers must not mutate the returned rows."""
        U = len(profiles)
        cap = self.mirror.t.capacity
        terms: List[Tuple[str, int]] = []
        t_index: Dict[Tuple[str, int], int] = {}
        for prof in profiles:
            for tid, waived in prof.req_aff:
                for k in ((K_MATCH, tid),):
                    if k not in t_index:
                        t_index[k] = len(terms)
                        terms.append(k)
            for tid in prof.req_anti:
                k = (K_MATCH, tid)
                if k not in t_index:
                    t_index[k] = len(terms)
                    terms.append(k)
            for tid in prof.carried_anti:
                k = (K_CARRY_ANTI, tid)
                if k not in t_index:
                    t_index[k] = len(terms)
                    terms.append(k)
        T = len(terms)
        if T == 0:
            return np.ones((U, cap), bool)
        if U * T * cap >= DEVICE_EVAL_THRESHOLD:
            present = np.stack([self.presence_vec(kind, tid)
                                for kind, tid in terms])
            has_dom = np.stack([self.has_dom_vec(self._by_id[tid].tk)
                                for _, tid in terms])
            sel_dom = np.zeros((U, T), np.float32)   # aff: node needs tk
            sel_present = np.zeros((U, T), np.float32)  # non-waived: match
            sel_absent = np.zeros((U, T), np.float32)   # anti: match forbids
            for u, prof in enumerate(profiles):
                for tid, waived in prof.req_aff:
                    t = t_index[(K_MATCH, tid)]
                    sel_dom[u, t] = 1.0
                    if not waived:
                        sel_present[u, t] = 1.0
                for tid in prof.req_anti:
                    sel_absent[u, t_index[(K_MATCH, tid)]] = 1.0
                for tid in prof.carried_anti:
                    sel_absent[u, t_index[(K_CARRY_ANTI, tid)]] = 1.0
            from .kernels.affinity import affinity_masks
            return np.asarray(affinity_masks(
                has_dom, present, sel_dom, sel_present, sel_absent))
        # host path: per-profile cached mask rows — a batch whose
        # templates' presence vectors haven't moved since the last batch
        # recombines NOTHING (the stacked copy is the only O(U·N) left)
        return np.stack([self._profile_mask_row(prof)
                         for prof in profiles])

    def score_vector(self, pod: Pod,
                     hard_pod_affinity_weight: float) -> Optional[np.ndarray]:
        """[capacity] f32 raw inter-pod affinity priority — the
        interpod_affinity_scores oracle as count-matrix gathers:
          + w × matches for the pod's preferred affinity terms
          - w × matches for its preferred anti-affinity terms
          + carried preferred weights (±) for terms matching the pod
          + hard_pod_affinity_weight × carried required-affinity matches
        Returns None when nothing can contribute."""
        total: Optional[np.ndarray] = None

        def acc(vec: np.ndarray, w: float):
            nonlocal total
            if total is None:
                total = np.zeros((self.mirror.t.capacity,), np.float32)
            total += w * vec

        aff = pod.spec.affinity
        if aff is not None:
            for sign, wterms in (
                    (1.0, aff.pod_affinity.preferred_during_scheduling_ignored_during_execution
                     if aff.pod_affinity else ()),
                    (-1.0, aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution
                     if aff.pod_anti_affinity else ())):
                for wt in wterms or ():
                    t = wt.pod_affinity_term
                    if not wt.weight:
                        continue
                    term = self.ensure_match(
                        t.topology_key, self._resolved_ns(t, pod),
                        t.label_selector)
                    acc(self._vec(K_MATCH, term.tid), sign * float(wt.weight))
        mset = None
        for kind, w in ((K_CARRY_AFF, float(hard_pod_affinity_weight)),
                        (K_CARRY_PAFF, 1.0), (K_CARRY_PANTI, -1.0)):
            if kind == K_CARRY_AFF and not w:
                continue
            for tid, counts in self._counts[kind].items():
                if not counts:
                    continue
                if mset is None:
                    mset = self.match_set(pod)
                if tid in mset:
                    acc(self._vec(kind, tid), w)
        if total is None or not total.any():
            return None
        return total


class BatchOverlay:
    """In-batch winner tracking for the repair pass — the serial reference
    sees each earlier bind via cache.AssumePod between iterations
    (scheduler.go:514); the batch kernel's mask is frozen at batch start, so
    (anti-)affinity created by EARLIER WINNERS IN THE SAME BATCH is
    validated here with O(terms) dict lookups per winner (the PredicateMetadata
    clone+add_pod machinery this replaces was O(winners × pairs))."""

    def __init__(self, index: TopologyIndex):
        self.index = index
        self._match: Dict[Tuple[int, int], int] = {}      # (tid, dom) -> n
        self._match_total: Dict[int, int] = {}
        self._carry_anti: Dict[Tuple[int, int], int] = {}
        self._anti_terms: List[int] = []                  # tids added in-batch
        self._anti_term_seen: Set[int] = set()

    @property
    def has_anti(self) -> bool:
        return bool(self._anti_terms)

    def add_winner(self, pod: Pod, node_name: str) -> None:
        idx = self.index
        for tid in idx.match_set(pod):
            term = idx._by_id[tid]
            if term.match_registered:
                dom = idx.dom_of(node_name, term.tk)
                if dom >= 0:
                    k = (term.tid, dom)
                    self._match[k] = self._match.get(k, 0) + 1
                    self._match_total[term.tid] = \
                        self._match_total.get(term.tid, 0) + 1
        aff = pod.spec.affinity
        if aff is not None and aff.pod_anti_affinity is not None:
            for t in aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution or ():
                term = idx._intern(t.topology_key,
                                   idx._resolved_ns(t, pod), t.label_selector)
                dom = idx.dom_of(node_name, term.tk)
                if dom >= 0:
                    k = (term.tid, dom)
                    self._carry_anti[k] = self._carry_anti.get(k, 0) + 1
                    if term.tid not in self._anti_term_seen:
                        self._anti_term_seen.add(term.tid)
                        self._anti_terms.append(term.tid)

    def conflicts(self, pod: Pod, prof: AffinityProfile,
                  node_name: str) -> bool:
        """Would earlier winners invalidate this pod's assignment? (The
        batch-start mask already enforced pre-batch state; only ADDITIONS
        can break an assignment — affinity matches never disappear
        in-batch.)"""
        idx = self.index
        for tid in prof.req_anti:
            term = idx._by_id[tid]
            dom = idx.dom_of(node_name, term.tk)
            if dom >= 0 and self._match.get((tid, dom), 0) > 0:
                return True
        for tid, waived in prof.req_aff:
            # a waived term activates once an in-batch winner matches it:
            # later pods must co-locate (the serial semantics — pod 2 of a
            # self-affine group follows pod 1)
            if waived and self._match_total.get(tid, 0) > 0:
                term = idx._by_id[tid]
                dom = idx.dom_of(node_name, term.tk)
                if dom < 0 or self._match.get((tid, dom), 0) == 0:
                    return True
        if self._anti_terms:
            # only terms some in-batch winner carries have overlay entries;
            # prof.carried_anti needs no separate pass (same interned tids)
            mset = idx.match_set(pod)
            for tid in self._anti_terms:
                if tid not in mset:
                    continue
                term = idx._by_id[tid]
                dom = idx.dom_of(node_name, term.tk)
                if dom >= 0 and self._carry_anti.get((tid, dom), 0) > 0:
                    return True
        return False
