"""Batch scheduling core — the genericScheduler equivalent.

Ref: pkg/scheduler/core/generic_scheduler.go. Where the reference's
`Schedule` handles ONE pod (snapshot -> findNodesThatFit -> PrioritizeNodes ->
selectHost, :184-254), `BatchScheduler.schedule` handles a whole batch:

    cache.update_snapshot      O(delta) generation scan   (cache.go:210-246)
    mirror.apply(dirty)        O(delta) rows to HBM
    PodBatchTensors            term-compile the pod axis
    kernels.schedule_batch     serial-semantics assign scan, on device
    -> [(pod, node_name | None)]

No node sampling: the reference trades decision quality for speed via
numFeasibleNodesToFind (50%, :434-453); the batch kernel evaluates every node
for every pod in one shot, so sampling is unnecessary.

MatchInterPodAffinity runs through the incremental topology index
(topology.py — the M3 sparse topologyPairsMaps analog): per batch, every
constraint template's node mask is one vectorized evaluation over [T, N]
term-presence matrices (device matmuls for large T), fed into the kernel's
unique-mask rows. Volume predicates (NoDiskConflict, Max*VolumeCount,
zone/binding) still run per-node on the host, only for pods that carry
volumes. In-batch interactions are validated post-kernel by the repair
pass: ports/disk/attach against overlay NodeInfos, (anti-)affinity against
a BatchOverlay of winner term counts; a conflict demotes the pod to retry
(the next cycle sees the winner via assume).

Failure diagnosis (`explain`) reruns the python predicates to produce the
reference's per-node FitError reasons (:598-664) — off the hot path, only for
pods that failed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import helpers
from ..api.core import Pod
from ..api.serde import deepcopy_obj
from .cache import Cache, Snapshot
from .nodeinfo import NodeInfo, pod_has_affinity_constraints
from . import predicates as preds
from . import sharding as sharding_mod
from .tensorize import PodBatchTensors, TensorMirror, TermCompiler
from .topology import AffinityProfile, BatchOverlay, TopologyIndex


@dataclass
class FitError(Exception):
    """Ref: core.FitError — why a pod fit nowhere. total_nodes is the
    cluster size; not_examined > 0 means the diagnosis was capped."""
    pod: Optional[Pod] = None
    failed_predicates: Dict[str, List[str]] = field(default_factory=dict)
    total_nodes: int = 0
    not_examined: int = 0

    def error(self) -> str:
        # aggregate like the reference's FitError.Error()
        counts: Dict[str, int] = {}
        for reasons in self.failed_predicates.values():
            for r in reasons:
                counts[r] = counts.get(r, 0) + 1
        parts = [f"{n} {r}" for r, n in sorted(counts.items())]
        total = self.total_nodes or len(self.failed_predicates)
        msg = "0/%d nodes are available: %s." % (total, ", ".join(parts))
        if self.not_examined:
            msg += (f" ({self.not_examined} node(s) not examined: "
                    f"diagnosis capped)")
        return msg


@dataclass
class ScheduleResult:
    pod: Pod
    node_name: Optional[str]          # None -> unschedulable (or retry)
    score: float = 0.0
    retry: bool = False               # lost an in-batch conflict; requeue
    reassigned: bool = False          # repair moved it off the kernel's pick


@dataclass
class PendingBatch:
    """A dispatched-but-unfetched batch (schedule_launch output): the device
    scan runs while the host commits the previous batch."""
    pods: List[Pod]
    profiles: Dict[int, AffinityProfile]
    batch: PodBatchTensors
    packed: object                    # [2, P] device handle (assign+scores)
    new_usage: dict                   # device usage after this batch
    residual_free: bool               # no repair possible -> usage chainable
    usage_epoch: int = 0              # mirror.usage_epoch at launch
    #: residual was affinity-only (no volumes/extenders/static scores):
    #: the NEXT batch may still chain usage on device — its stale affinity
    #: mask is repaired via stale_winners (below)
    affinity_chainable: bool = False
    #: True when this batch launched chained on a predecessor whose results
    #: were not yet committed; the drain fills stale_winners/phantom from
    #: that predecessor's commit before this batch is finished
    chained: bool = False
    #: the predecessor batch's committed (pod, node) winners — absent from
    #: this batch's snapshot/index/mask; repair validates against them via
    #: the BatchOverlay exactly like same-batch winners
    stale_winners: Optional[List[Tuple[Pod, str]]] = None
    #: the predecessor lost winners after this batch's usage was chained
    #: (repair demotions / commit drops): chained usage over-states, so
    #: kernel-unassigned pods here must RETRY, not park as unschedulable
    phantom: bool = False
    #: gang placement units [(pod indices, topology key, is_gang)] when
    #: this batch routed through the all-or-nothing kernel; finish uses
    #: them to demote whole gangs when repair invalidates any member
    gang_units: Optional[list] = None
    #: True when the in-scan topology tables (or their provable inertness)
    #: cover EVERY in-batch (anti-)affinity interaction AND the batch
    #: carries no ports/volumes/extenders: with no stale winners, the
    #: repair pass has nothing left to validate and is skipped outright
    inscan_cover: bool = False
    #: True when this batch ran the shard_map kernel (per-shard
    #: filter+score, cross-shard argmax) — schedule_finish attributes its
    #: fetch wait to scheduler_shard_sync_seconds
    sharded: bool = False
    #: structural signatures of the in-scan spread / soft tables (None
    #: when absent): a successor may chain THROUGH this batch's carried
    #: counts only when its own tables resolve to the same structure —
    #: see schedule_launch's carry-chaining gate
    spread_sig: Optional[Tuple] = None
    soft_sig: Optional[Tuple] = None
    #: [P/K, 2] int32 device handle of per-cohort (accepted,
    #: first_collision) stats when this batch ran the speculative cohort
    #: kernel (kernels/speculative.py); schedule_finish folds it into the
    #: scheduler_speculative_* counters
    spec_stats: object = None
    #: (node_cfg, usage, dev_batch, nom) captured for the divergence
    #: oracle (KTPU_SPEC_ORACLE=1): schedule_finish replays the serial
    #: scan on the identical inputs and attributes any mismatch
    spec_inputs: object = None


class _RepairReassigner:
    """Host-side serial re-solve for pods the repair pass would demote.

    The serial reference never demotes: pod i simply picks its best node
    GIVEN pods 1..i-1 (scheduler.go:514 assume-between-iterations). The
    kernel approximates that with a frozen constraint mask; when repair
    finds pod i's kernel pick invalidated by an earlier winner, this class
    reproduces the kernel's exact scoring on host numpy — running usage
    including every surviving earlier winner, the same resource priorities
    (kernels/batch.py _least_requested/_balanced_allocation, f32 floors),
    static rows, and (row, seq) tie-break hash — and walks candidates in
    that order so the repair can place the pod where the serial order
    would have, instead of burning a retry round.

    Usage base: mirror host truth + stale (chained predecessor) winners +
    surviving winners of THIS batch. In the mid-drain chained case the
    predecessor's winners may already be folded into host truth, making
    the base conservatively overstated for reassigned pods — feasibility
    never overpacks, and the single-batch (parity fixture) case is exact.
    """

    MAX_CANDIDATES = 64

    def __init__(self, mirror: TensorMirror, batch: PodBatchTensors,
                 stale_winners):
        self.mirror = mirror
        self.batch = batch
        self._stale = list(stale_winners or [])
        self._log: List[Tuple[int, str]] = []   # winners before materialize
        self._used = None
        self.reassigned_any = False

    def add_winner(self, i: int, node_name: str) -> None:
        if self._used is None:
            self._log.append((i, node_name))
        else:
            self._apply(i, node_name)

    def _apply(self, i: int, node_name: str) -> None:
        row = self.mirror.row_of.get(node_name)
        if row is None:
            return
        self._used[row] += self.batch.req[i]
        self._nz[row] += self.batch.nonzero_req[i]
        self._cnt[row] += 1.0

    def _materialize(self) -> None:
        from .nodeinfo import pod_resource, pod_resource_nonzero
        from .tensorize import COL_CPU, COL_EPH, COL_MEM, _f32_ceil
        t = self.mirror.t
        self._used = t.used.copy()
        self._nz = t.nonzero_used.copy()
        self._cnt = t.pod_count.copy()
        self._rows = np.arange(t.capacity, dtype=np.int64)
        for w_pod, w_node in self._stale:
            row = self.mirror.row_of.get(w_node)
            if row is None:
                continue
            r = pod_resource(w_pod)
            self._used[row, COL_CPU] += _f32_ceil(r.milli_cpu)
            self._used[row, COL_MEM] += _f32_ceil(r.memory)
            self._used[row, COL_EPH] += _f32_ceil(r.ephemeral_storage)
            for rname, v in r.scalar_resources.items():
                self._used[row, self.mirror.vocab.col(rname)] += _f32_ceil(v)
            nz_cpu, nz_mem = pod_resource_nonzero(w_pod)
            self._nz[row, 0] += nz_cpu
            self._nz[row, 1] += nz_mem
            self._cnt[row] += 1.0
        for i, node_name in self._log:
            self._apply(i, node_name)
        self._log = []

    def candidates(self, i: int):
        """Yield node names in the kernel's (score - tie penalty) order,
        feasible against the running usage; capped."""
        if self._used is None:
            self._materialize()
        from .tensorize import COL_CPU, COL_MEM
        t = self.mirror.t
        b = self.batch
        req = b.req[i]
        fits = b.unique_masks[b.mask_idx[i]] & t.node_ok & t.valid
        if b.mem_pressure_blocked[i]:
            fits = fits & ~t.mem_pressure
        fits = fits & ((self._used + req[None, :]) <= t.alloc).all(axis=1)
        fits = fits & (self._cnt + 1.0 <= t.max_pods)
        if not fits.any():
            return
        cap_cpu = t.alloc[:, COL_CPU]
        cap_mem = t.alloc[:, COL_MEM]
        nzr = b.nonzero_req[i]
        req_cpu = self._nz[:, 0] + nzr[0]
        req_mem = self._nz[:, 1] + nzr[1]
        safe_cpu = np.maximum(cap_cpu, 1.0)
        safe_mem = np.maximum(cap_mem, 1.0)
        lr_c = np.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                        np.floor((cap_cpu - req_cpu) * 10.0 / safe_cpu), 0.0)
        lr_m = np.where((cap_mem > 0) & (req_mem <= cap_mem),
                        np.floor((cap_mem - req_mem) * 10.0 / safe_mem), 0.0)
        lr = np.floor((lr_c + lr_m) / 2.0)
        cpu_frac = np.where(cap_cpu > 0, req_cpu / safe_cpu, 1.0)
        mem_frac = np.where(cap_mem > 0, req_mem / safe_mem, 1.0)
        ba = np.floor((1.0 - np.abs(cpu_frac - mem_frac)) * 10.0)
        ba = np.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, ba)
        rw = b.resource_weights
        score = rw[0] * lr + rw[1] * ba + b.unique_scores[b.score_idx[i]]
        # bit-identical tie-break to the kernel: low 16 bits are invariant
        # under int32 wraparound, so int64 + mask matches
        h = ((self._rows * -1640531527 + int(b.seq[i]) * 40503)
             & 0xFFFF).astype(np.float32)
        ranked = np.where(fits, score - h * np.float64(0.5 / 65536.0),
                          -np.inf)
        order = np.argsort(-ranked, kind="stable")
        for row in order[:self.MAX_CANDIDATES]:
            row = int(row)
            if not fits[row]:
                return
            name = self.mirror.name_of.get(row)
            if name is not None:
                yield name


def _pod_has_conflict_volumes(pod: Pod) -> bool:
    for v in pod.spec.volumes:
        if v.gce_persistent_disk or v.aws_elastic_block_store or v.rbd or v.iscsi:
            return True
    return False


def _pod_has_attach_volumes(pod: Pod) -> bool:
    """Direct attach-limited sources (CSI reaches pods only via PVCs, which
    _pod_has_pvc covers)."""
    for v in pod.spec.volumes:
        if v.gce_persistent_disk or v.aws_elastic_block_store or v.azure_disk:
            return True
    return False


def _pod_has_pvc(pod: Pod) -> bool:
    return any(v.persistent_volume_claim for v in pod.spec.volumes)


class BatchScheduler:
    def __init__(self, cache: Cache, listers=None,
                 weights: Optional[Dict[str, int]] = None,
                 hard_pod_affinity_weight: Optional[int] = None,
                 volume_binder=None,
                 pvc_lister=None, pv_lister=None,
                 nominated=None, pdb_lister=None, extenders=None,
                 mesh=None):
        from . import priorities as prios_mod
        from .queue import NominatedPodMap
        from .scorer import ScoreCompiler
        from .volumebinder import FakeVolumeBinder
        #: out-of-process extenders (ref: core/extender.go); filter joins
        #: the residual host path, prioritize merges into static scores
        self.extenders = list(extenders or [])
        #: shared with the SchedulingQueue; feeds the kernel's reservation
        #: tensors and preemption's nominated-to-clear list
        self.nominated = nominated if nominated is not None else NominatedPodMap()
        self.pdb_lister = pdb_lister or (lambda: [])
        self._nom_key = None
        self._nom_dev = None
        self._nom_rows_by_key: Dict[str, int] = {}
        self.volume_binder = volume_binder or FakeVolumeBinder()
        self.pvc_lister = pvc_lister      # (namespace, name) -> PVC | None
        self.pv_lister = pv_lister        # (name) -> PV | None
        self._zone_conflict = preds.no_volume_zone_conflict_factory(
            pvc_lister or (lambda ns, name: None),
            pv_lister or (lambda name: None))
        # Max{EBS,GCEPD,AzureDisk,CSI}VolumeCount — default-set members
        # (defaults.go:40-56), host-evaluated on the residual path
        self._volume_count_preds = preds.default_max_volume_count_predicates(
            pvc_lister, pv_lister)
        self.cache = cache
        self.snapshot = Snapshot()
        self.mirror = TensorMirror(mesh=mesh)
        self.terms = TermCompiler(self.mirror)
        #: the M3 incremental topologyPairsMaps analog (topology.py)
        self.topology = TopologyIndex(self.mirror)
        self.scorer = ScoreCompiler(
            self.mirror, self.terms, listers=listers, weights=weights,
            hard_pod_affinity_weight=(
                hard_pod_affinity_weight if hard_pod_affinity_weight is not None
                else prios_mod.HARD_POD_AFFINITY_WEIGHT),
            topology=self.topology)
        self._seq_base = 0  # selectHost round-robin state across batches
        # True while host-computed static scores contribute (chain pre-check)
        self._static_likely = False
        #: gang.GangManager, installed by the scheduler shell; batches
        #: carrying PodGroup members route through the all-or-nothing
        #: kernel (kernels/gang.py) instead of schedule_batch
        self.gang = None
        #: tenancy.DRFAccount, installed by the scheduler shell: the
        #: preemption kernels fold its over-share ranks into the victim
        #: band sort so over-share tenants' pods price cheaper (None, or
        #: KTPU_DRF=0, keeps tenant-blind pricing)
        self.drf = None
        import os as _os
        #: soft-score sub-batch size, resolved ONCE at construction (like
        #: KTPU_ALIGN_SPLIT) — re-reading the environment per batch was a
        #: silent per-drain cost and an unannounced behavior knob
        self.soft_score_chunk = int(_os.environ.get(
            "SCHED_SOFT_SCORE_CHUNK", str(self.SOFT_SCORE_CHUNK)))
        #: KTPU_TOPO_TABLE_CACHE=0 disables the epoch-keyed term-table and
        #: profile caches (the tier-1 cached==uncached smoke's control)
        self.topo_table_cache = _os.environ.get(
            "KTPU_TOPO_TABLE_CACHE", "1") != "0"
        #: KTPU_CLASS_SCAN=0 pins non-gang batches to the classic per-pod
        #: kernel — the parity control for the class-indexed fast path
        #: (bench.py affinity measures class-scan vs classic with it)
        self.class_scan = _os.environ.get("KTPU_CLASS_SCAN", "1") != "0"
        #: KTPU_SPECULATIVE=1 routes unsharded class-table batches to the
        #: speculative cohort kernel (kernels/speculative.py): vmapped
        #: cohort proposals with exact collision detection and serial
        #: whole-cohort repair — decisions stay bit-identical to the
        #: serial class scan (default off; Scheduler(speculative=True)
        #: sets it too)
        self.speculative = _os.environ.get("KTPU_SPECULATIVE", "0") != "0"
        #: KTPU_SPEC_ORACLE=1 replays EVERY speculative batch through the
        #: serial scan and counts/attributes mismatches (the divergence
        #: oracle — a measurement harness, not a production mode)
        self.spec_oracle = _os.environ.get("KTPU_SPEC_ORACLE", "0") != "0"
        #: bounded attribution log of oracle divergences (newest last);
        #: expected empty — each entry is a per-pod dict from
        #: kernels.speculative.divergence_report
        from collections import deque as _deque
        self.spec_divergence_log = _deque(maxlen=64)
        #: per-batch (cohort_width, n_cohorts, n_collided, repaired_pods)
        #: records — the bench's cohort-size distribution source
        self.spec_batch_log = _deque(maxlen=256)
        #: KTPU_PREEMPT_KERNEL=0 pins preemption to the serial per-node
        #: victim search (preemption.py) — the measured control for the
        #: batched victim-pricing kernel (kernels/preempt.py)
        self.preempt_kernel = _os.environ.get(
            "KTPU_PREEMPT_KERNEL", "1") != "0"
        #: (node, generation, prio, ...) -> victim units: amortizes the
        #: preemption tensorize across a storm (kernels/preempt.py)
        self._preempt_unit_cache: Dict[Tuple, list] = {}
        #: launches that actually chained on a predecessor's device usage
        #: (tests pin that spread/soft batches keep chaining)
        self.chained_launches = 0
        #: residual-sig -> (profile_epoch, AffinityProfile): template
        #: profile resolution survives across batches until a profile-
        #: relevant topology change (new term, zero-crossing count)
        self._profile_cache: Dict[Tuple, Tuple[int, AffinityProfile]] = {}
        #: scheduler.SchedulerMetrics, installed by the shell (None in
        #: bare-algorithm tests); used for in-scan fallback counters
        self.sched_metrics = None
        #: observability.SpanTracer, installed by the shell: the device
        #: path's stage spans (tensorize / scan wait) ride the same
        #: flight recorder as the shell's launch/commit/bind spans
        self.tracer = None
        self._fallback_streak: Dict[str, int] = {}
        #: (pod-list, plan) from the most recent _soft_plan: the drain's
        #: soft_batch_limit and the launch's _assign_soft_terms see the
        #: SAME list object when the batch wasn't truncated, so the O(P)
        #: channel-planning pass runs once per batch, not twice
        self._soft_plan_memo: Optional[Tuple[List[Pod], Optional[dict]]] = \
            None
        #: per-drain phase accounting, surfaced by bench.py's affinity
        #: breakdown: host term-prep wall vs device scan wait vs
        #: repair/reassign wall, plus profile-cache effectiveness
        #: (term-table cache counters live on the TopologyIndex)
        self.phase_stats = {"term_prep_s": 0.0, "scan_wait_s": 0.0,
                            "repair_s": 0.0, "profile_builds": 0,
                            "profile_hits": 0}

    def reset_phase_stats(self) -> None:
        for k in self.phase_stats:
            self.phase_stats[k] = 0 if isinstance(
                self.phase_stats[k], int) else 0.0

    def refresh(self) -> None:
        dirty = self.cache.update_snapshot(self.snapshot)
        self.mirror.apply(self.snapshot, dirty)
        self.topology.apply(self.snapshot, dirty)
        if dirty:
            # precise score gating: required-anti-only clusters never
            # produce an inter-pod priority contribution
            self.scorer.set_cluster_has_affinity_pods(
                self.topology.has_score_carriers())

    # ------------------------------------------------------- residual host path

    def _needs_residual(self, pod: Pod) -> bool:
        """MatchInterPodAffinity / NoDiskConflict / volume predicates need
        an extra mask row (extender filters are handled separately so they
        don't drag every pod through the template path). Unconstrained pods
        are masked only when some existing pod carries REQUIRED
        anti-affinity — the one carried constraint that can exclude them
        (preferred terms only score; carried required affinity only
        credits)."""
        return (pod_has_affinity_constraints(pod)
                or self.topology.has_required_anti_carriers()
                or _pod_has_conflict_volumes(pod) or _pod_has_pvc(pod)
                or _pod_has_attach_volumes(pod))

    def _has_filter_extenders(self) -> bool:
        return any(e.config.filter_verb for e in self.extenders)

    def _encoded_live_nodes(self):
        """(live_nodes, encoded_items), cached by mirror epoch — the filter
        and prioritize extender paths share one full-cluster JSON encode
        per snapshot instead of one each per batch."""
        if getattr(self, "_enc_nodes_epoch", None) != self.mirror.epoch:
            from ..api import serde as serde_mod
            live = [ni.node for ni in self.snapshot.node_infos.values()
                    if ni.node is not None]
            self._enc_nodes = (live, [serde_mod.encode(n) for n in live])
            self._enc_nodes_epoch = self.mirror.epoch
        return self._enc_nodes

    def _passes_basic_checks(self, pod: Pod) -> bool:
        """Ref: podPassesBasicChecks (generic_scheduler.go:188) — referenced
        PVCs must exist and not be deleting."""
        if self.pvc_lister is None:
            return True
        for vol in pod.spec.volumes:
            if not vol.persistent_volume_claim:
                continue
            pvc = self.pvc_lister(pod.metadata.namespace,
                                  vol.persistent_volume_claim.claim_name)
            if pvc is None or pvc.metadata.deletion_timestamp is not None:
                return False
        return True

    @staticmethod
    def _canon_pod_aff_term(t) -> Tuple:
        from ..api import labels as labelsmod
        return (labelsmod.canonical_selector(t.label_selector),
                t.topology_key, tuple(sorted(t.namespaces)))

    def _residual_sig(self, pod: Pod) -> Tuple:
        """Everything the residual evaluation can depend on:
        controller-stamped pods share it, so profile resolution, the
        vectorized affinity mask, and the volume per-node pass run once per
        TEMPLATE per batch, not once per pod (the affinity analog of the
        mask-row dedupe in PodBatchTensors). Structured canon, not repr() —
        a deep dataclass repr per pod per batch was the residual path's
        largest host cost. Cached on the pod object (like tensorize's
        _tsig): a pod retried across batches re-canonicalizes nothing —
        informer updates replace the object, so staleness can't stick."""
        sig = pod.__dict__.get("_rsig")
        if sig is not None:
            return sig
        aff = pod.spec.affinity
        aff_canon: Tuple = ()
        if aff is not None:
            parts = []
            for pa in (aff.pod_affinity, aff.pod_anti_affinity):
                if pa is None:
                    parts.append(None)
                    continue
                parts.append((
                    tuple(self._canon_pod_aff_term(t) for t in
                          pa.required_during_scheduling_ignored_during_execution or ()),
                    tuple((wt.weight,
                           self._canon_pod_aff_term(wt.pod_affinity_term))
                          for wt in
                          pa.preferred_during_scheduling_ignored_during_execution or ())))
            aff_canon = tuple(parts)
        vols = tuple(sorted(
            (v.name,
             v.persistent_volume_claim.claim_name
             if v.persistent_volume_claim else "",
             repr(v.gce_persistent_disk), repr(v.aws_elastic_block_store),
             repr(v.azure_disk), repr(v.rbd), repr(v.iscsi))
            for v in pod.spec.volumes))
        sig = (pod.metadata.namespace,
               tuple(sorted(pod.metadata.labels.items())),
               aff_canon, vols)
        pod.__dict__["_rsig"] = sig
        return sig

    def _residual_mask(self, pods: List[Pod]
                       ) -> Tuple[Optional[np.ndarray],
                                  Dict[int, AffinityProfile],
                                  Optional[np.ndarray]]:
        """(extra mask [P, N] | None, profiles, extra group ids [P] | None).
        Group ids name each pod's extra-mask ROW by template (two pods in
        one group provably share the row), so tensorization can dedupe
        mask rows by id instead of hashing 8K of row bytes per pod; None
        when filter extenders are in play (their masks are pod-addressed,
        no sharing is provable)."""
        profiles: Dict[int, AffinityProfile] = {}
        extra: Optional[np.ndarray] = None
        filter_extenders = [e for e in self.extenders
                            if e.config.filter_verb]
        live_nodes = []
        enc_nodes: Optional[list] = None
        if filter_extenders:
            live_nodes, enc_nodes = self._encoded_live_nodes()
        # pass 1: group internal-path pods by template signature; extenders
        # apply per pod (their masks are pod-addressed)
        sig_index: Dict[Tuple, int] = {}
        sig_reps: List[Pod] = []
        pod_sig = np.full((len(pods),), -1, np.int64)
        for i, pod in enumerate(pods):
            internal = self._needs_residual(pod)
            if not internal and not filter_extenders:
                continue
            if extra is None:
                extra = np.ones((len(pods), self.mirror.t.capacity), bool)
            if not self._passes_basic_checks(pod):
                extra[i, :] = False
                pod_sig[i] = -2  # group id for the shared all-False row
                continue
            if internal:
                sig = self._residual_sig(pod)
                u = sig_index.get(sig)
                if u is None:
                    u = len(sig_reps)
                    sig_index[sig] = u
                    sig_reps.append(pod)
                pod_sig[i] = u
            if filter_extenders and not self._apply_filter_extenders(
                    filter_extenders, pod, live_nodes, extra, i, enc_nodes):
                continue
        if not sig_reps:
            return extra, profiles, \
                (None if filter_extenders else pod_sig)
        # pass 2: one vectorized affinity evaluation for ALL templates
        # (topology.required_masks — numpy or device matmuls by size), plus
        # the per-node volume loop only for templates that carry volumes.
        # Profile resolution is memoized ACROSS batches by template
        # signature, invalidated by the topology index's profile_epoch
        # (new terms, zero-crossing match/anti-carry counts — the only
        # state a resolved profile depends on)
        sig_profiles = [self._cached_profile(sig, p)
                        for sig, p in zip(sig_index, sig_reps)]
        constrained = [u for u, pr in enumerate(sig_profiles)
                       if pr.constrained]
        aff_rows: Dict[int, np.ndarray] = {}
        if constrained:
            rows = self.topology.required_masks(
                [sig_profiles[u] for u in constrained])
            for j, u in enumerate(constrained):
                aff_rows[u] = rows[j]
        vol_rows = [self._volume_row(rep) for rep in sig_reps]
        # templates whose residual row is provably all-True collapse back
        # to "no extra row" (id -1): one .all() per TEMPLATE keeps the
        # dedupe-by-id win while label-distinct but unconstrained
        # templates share the no-extra mask row instead of each minting
        # an identical all-True [N] row in the unique-mask bucket
        inert_u = [
            (aff_rows.get(u) is None or bool(aff_rows[u].all()))
            and (vol_rows[u] is None or bool(vol_rows[u].all()))
            for u in range(len(sig_reps))]
        for i in range(len(pods)):
            u = int(pod_sig[i])
            if u < 0:
                continue
            row = aff_rows.get(u)
            if row is not None:
                extra[i] &= row
            if vol_rows[u] is not None:
                extra[i] &= vol_rows[u]
            if sig_profiles[u].constrained:
                profiles[i] = sig_profiles[u]
            if inert_u[u]:
                pod_sig[i] = -1
        return extra, profiles, (None if filter_extenders else pod_sig)

    def _cached_profile(self, sig: Tuple, pod: Pod) -> AffinityProfile:
        """required_profile memoized by template signature across batches
        (a controller's 16k-pod burst resolves its constraint plan once per
        topology profile-epoch, not once per batch). Resolution itself may
        register new match terms — the epoch is read AFTER computing so
        the cached entry reflects the post-registration state."""
        if not self.topo_table_cache:
            self.phase_stats["profile_builds"] += 1
            return self.topology.required_profile(pod)
        hit = self._profile_cache.get(sig)
        if hit is not None and hit[0] == self.topology.profile_epoch:
            self.phase_stats["profile_hits"] += 1
            return hit[1]
        prof = self.topology.required_profile(pod)
        if len(self._profile_cache) > 4096:
            self._profile_cache.clear()
        self._profile_cache[sig] = (self.topology.profile_epoch, prof)
        self.phase_stats["profile_builds"] += 1
        return prof

    def _volume_row(self, pod: Pod) -> Optional[np.ndarray]:
        """One template's [capacity] volume-predicate mask (NoDiskConflict,
        Max*VolumeCount, zone conflict, volume binding), or None when the
        pod carries no volume constraints — the only predicates left on the
        per-node host loop."""
        has_disk = _pod_has_conflict_volumes(pod)
        has_pvc = _pod_has_pvc(pod)
        has_attach = has_pvc or _pod_has_attach_volumes(pod)
        if not (has_disk or has_pvc or has_attach):
            return None
        from types import SimpleNamespace
        meta = SimpleNamespace(memo={})  # Max*VolumeCount wanted-set memo
        row_mask = np.zeros((self.mirror.t.capacity,), bool)
        for name, ni in self.snapshot.node_infos.items():
            row = self.mirror.row_of.get(name)
            if row is None:
                continue
            ok = True
            if has_disk:
                ok, _ = preds.no_disk_conflict(pod, meta, ni)
            if ok and has_attach:
                for fn in self._volume_count_preds.values():
                    ok, _ = fn(pod, meta, ni)
                    if not ok:
                        break
            if ok and has_pvc:
                ok, _ = self._zone_conflict(pod, meta, ni)
                if ok and ni.node is not None:
                    ok = self.volume_binder.find_pod_volumes(pod, ni.node)
            row_mask[row] = ok
        return row_mask

    def _apply_filter_extenders(self, filter_extenders, pod: Pod,
                                live_nodes, extra: np.ndarray,
                                i: int, enc_nodes=None) -> bool:
        """AND each extender's feasible set into the pod's row. The batch
        deviation from the reference: extenders see ALL live nodes, not
        only internal-predicate survivors (core/extender.go runs after
        findNodesThatFit) — the intersection is identical. Returns False
        when a non-ignorable extender failed (the pod is unschedulable
        this cycle, ref: Filter error handling :258)."""
        from .extender import ExtenderError
        for e in filter_extenders:
            try:
                names, _failed = e.filter(pod, live_nodes, enc_nodes)
            except ExtenderError:
                if e.is_ignorable():
                    continue
                extra[i, :] = False
                return False
            allowed = np.zeros((extra.shape[1],), bool)
            for nm in names:
                row = self.mirror.row_of.get(nm)
                if row is not None:
                    allowed[row] = True
            extra[i] &= allowed
        return True

    def _apply_prioritize_extenders(self, pods: List[Pod],
                                    batch: "PodBatchTensors",
                                    static) -> None:
        """Merge extender prioritize scores into the batch's static score
        rows (ref: PrioritizeNodes :774-804 — weighted extender scores add
        to the internal sum). Errors are ignored per extender, matching
        the reference's ignorable-prioritize behavior."""
        from .extender import ExtenderError
        N = self.mirror.t.capacity
        live_nodes, enc_nodes = self._encoded_live_nodes()
        ext = np.zeros((len(pods), N), np.float32)
        for i, pod in enumerate(pods):
            for e in self.extenders:
                if not e.config.prioritize_verb:
                    continue
                try:
                    scores = e.prioritize(pod, live_nodes, enc_nodes)
                except ExtenderError:
                    continue
                for nm, s in scores.items():
                    row = self.mirror.row_of.get(nm)
                    if row is not None:
                        ext[i, row] += s
        if static is not None:
            idx, rows = static
            base = rows[np.asarray(idx[:len(pods)])]
        else:
            base = np.zeros((len(pods), N), np.float32)
        batch.set_static_scores(
            np.arange(len(pods), dtype=np.int32), base + ext)

    #: max batch size for pods whose soft scores would drift in-batch;
    #: env-tunable. SelectorSpread and preferred inter-pod (anti-)affinity
    #: both run IN-SCAN (running group counts / credit accumulators, on
    #: every kernel incl. the gang kernel's trial carry), so sub-chunking
    #: engages only when a batch OVERFLOWS the in-scan caps.
    SOFT_SCORE_CHUNK = 256

    def topo_scan_likely(self, pods: List[Pod]) -> bool:
        """True when this batch carries required ANTI-affinity — the
        in-scan counter workload whose per-step [K, N] gathers still make
        power-of-two padding worth splitting away (drain_pipelined's
        alignment split: +24% at r06, down from +33% pre-class-scan).
        Required AFFINITY batches measure FASTER unsplit (their tight
        feasible sets retry across launches), so they keep the padded
        single scan."""
        if self.topology.has_required_anti_carriers():
            return True
        return any(
            p.spec.affinity is not None
            and p.spec.affinity.pod_anti_affinity is not None
            and p.spec.affinity.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution
            for p in pods)

    def soft_batch_limit(self, pods: List[Pod]) -> int:
        """How many of these pods may schedule in ONE kernel batch without
        visible soft-score drift. Preferred inter-pod (anti-)affinity
        scores change with every in-batch winner; the serial reference
        re-scores per pod via assume-between-iterations. When the batch's
        soft term union fits the in-scan credit tables
        (_assign_soft_terms), the kernel re-scores per pod itself and the
        whole batch launches at once; only an overflowing union still
        schedules in SOFT_SCORE_CHUNK sub-batches. Spread beyond the
        in-scan group cap chunks as before."""
        chunk = self.soft_score_chunk
        if len(pods) <= chunk or chunk <= 0:
            return len(pods)
        if self.scorer.weights.get("InterPodAffinityPriority"):
            has_pref = any(
                p.spec.affinity is not None and (
                    (p.spec.affinity.pod_affinity is not None and
                     p.spec.affinity.pod_affinity
                     .preferred_during_scheduling_ignored_during_execution)
                    or (p.spec.affinity.pod_anti_affinity is not None and
                        p.spec.affinity.pod_anti_affinity
                        .preferred_during_scheduling_ignored_during_execution))
                for p in pods)
            if has_pref:
                if self._soft_plan_cached(pods) is None:
                    # channel-union overflow: sub-chunk so frozen credits
                    # refresh between launches. Gang batches used to chunk
                    # UNCONDITIONALLY here (soft_gang); the gang kernel's
                    # trial/committed soft accumulators lifted that, so
                    # the counter now marks only gang batches that STILL
                    # overflow the in-scan caps — wired, not silent
                    if self.gang is not None:
                        from .gang import pod_group_key
                        if any(pod_group_key(p) is not None for p in pods):
                            self._count_inscan_fallback("soft_gang")
                    return chunk
        # spread carriers beyond the in-scan group cap would otherwise run
        # the whole batch on frozen counts — chunk so they refresh
        listers = self.scorer.listers
        if listers is not None and \
                self.scorer.weights.get("SelectorSpreadPriority"):
            memo: Dict[Tuple, bool] = {}
            n_groups = 0
            for pod in pods:
                key = (pod.metadata.namespace,
                       tuple(sorted(pod.metadata.labels.items())))
                v = memo.get(key)
                if v is None:
                    v = bool(listers.selectors_for_pod(pod))
                    memo[key] = v
                    if v:
                        n_groups += 1
                        if n_groups > self.SPREAD_GROUP_CAP:
                            return chunk
        return len(pods)

    #: in-scan spread group cap per batch; overflow groups fall back to
    #: the static (batch-start) spread row
    SPREAD_GROUP_CAP = 7

    def _assign_spread_groups(self, pods: List[Pod],
                              batch: PodBatchTensors) -> Optional[Tuple]:
        """Group pods by (namespace, labels) whose selectors make them
        spread carriers; install per-group base counts + zone ids so the
        kernel scores SelectorSpread from RUNNING counts (the serial
        semantics — selector_spreading.go:277 re-counts per pod).

        Returns the batch's spread chain SIGNATURE (ordered group
        template keys + everything the carried [G, N] counts' meaning
        depends on), or None when no spread tables ride. Two batches
        with equal signatures name group g identically, so a chained
        launch may seed its count carry from the predecessor's finals."""
        listers = self.scorer.listers
        weight = self.scorer.weights.get("SelectorSpreadPriority", 0)
        if listers is None or not weight:
            return None
        from . import priorities as prios
        self.scorer._refresh_epoch()
        base_rows: List[np.ndarray] = []
        group_sel: List[Tuple[str, list]] = []   # (namespace, selectors)
        group_keys: List[Tuple] = []             # (ns, labels) per group
        memo: Dict[Tuple, Optional[int]] = {}
        for i, pod in enumerate(pods):
            key = (pod.metadata.namespace,
                   tuple(sorted(pod.metadata.labels.items())))
            g = memo.get(key, -2)
            if g == -2:
                g = None
                meta = prios.PriorityMetadata(pod, listers)
                if meta.pod_selectors and \
                        len(base_rows) < self.SPREAD_GROUP_CAP:
                    counts = self.scorer._spread_counts(pod, meta)
                    if counts is not None:
                        g = len(base_rows)
                        base_rows.append(np.asarray(counts, np.float32))
                        group_sel.append((pod.metadata.namespace,
                                          meta.pod_selectors))
                        group_keys.append(key)
                memo[key] = g
            if g is not None:
                batch.spread_gidx[i] = g
        if not base_rows:
            return None
        # canonical group order: slot g is sorted-template-key order, not
        # first-pod order — batches popping the same templates in a
        # rotated pod order land on the SAME signature, so the chained
        # count carry stays consumable. Pure renumbering: every per-group
        # structure below permutes consistently, decisions are invariant
        order = sorted(range(len(base_rows)), key=lambda g: group_keys[g])
        remap = {old: new for new, old in enumerate(order)}
        base_rows = [base_rows[g] for g in order]
        group_sel = [group_sel[g] for g in order]
        group_keys = [group_keys[g] for g in order]
        gidx = batch.spread_gidx
        for i in range(len(pods)):
            if gidx[i] >= 0:
                gidx[i] = remap[int(gidx[i])]
        # cross-group match matrix: a winner must bump every group whose
        # selectors match its labels, not only its own (ns, labels) group
        G = len(base_rows)
        match = np.zeros((len(pods), G), np.float32)
        mmemo: Dict[Tuple, np.ndarray] = {}
        for i, pod in enumerate(pods):
            key = (pod.metadata.namespace,
                   tuple(sorted(pod.metadata.labels.items())))
            row = mmemo.get(key)
            if row is None:
                row = np.zeros((G,), np.float32)
                for g, (ns, sels) in enumerate(group_sel):
                    if ns == pod.metadata.namespace and \
                            all(sel(pod.metadata.labels) for sel in sels):
                        row[g] = 1.0
                mmemo[key] = row
            match[i] = row
        batch.set_spread(np.stack(base_rows), self.scorer._zone_ids,
                         self.scorer._n_zones, float(weight), match=match)
        return (tuple(group_keys), self.scorer._n_zones, float(weight),
                self.mirror.epoch, self.scorer.spread_sel_gen,
                self.mirror.t.capacity)

    #: in-scan topology term cap per batch; bigger batches fall back to
    #: the repair overlay + reassignment path entirely
    TOPO_TERM_CAP = 512
    #: per-pod in-scan term fan-out cap (the kernel's K axis)
    TOPO_KMAX = 16

    def _count_inscan_fallback(self, reason: str) -> None:
        """No silent caps: every in-scan fallback (kmax/term-cap overflow,
        soft term-union overflow) is counted by reason and logged once per
        streak."""
        if self.sched_metrics is not None:
            self.sched_metrics.topo_inscan_fallbacks.inc(reason=reason)
        streak = self._fallback_streak.get(reason, 0)
        if streak == 0:
            import logging
            logging.getLogger(__name__).warning(
                "in-scan topology fallback (%s): batch takes the repair/"
                "chunked path; further occurrences counted in "
                "scheduler_topo_inscan_fallbacks_total", reason)
        self._fallback_streak[reason] = streak + 1

    def _count_capped_scan(self, cap: str, n: int) -> None:
        """No silent caps (KTPU005): a truncated candidate search is
        counted by cap name and logged once per streak, like the
        in-scan fallbacks above."""
        if self.sched_metrics is not None:
            self.sched_metrics.capped_scans.inc(cap=cap)
        streak = self._fallback_streak.get(cap, 0)
        if streak == 0:
            import logging
            logging.getLogger(__name__).warning(
                "capped scan (%s): %d candidates truncated to the "
                "documented cap; further occurrences counted in "
                "scheduler_capped_scans_total", cap, n)
        self._fallback_streak[cap] = streak + 1

    def _end_inscan_streak(self, *reasons: str) -> None:
        """A batch made it through the in-scan caps: close these reasons'
        fallback streaks so the NEXT overflow logs again (the per-streak
        contract; without this the warning fires once per process)."""
        for reason in reasons:
            self._fallback_streak[reason] = 0

    def _assign_topology_terms(self, pods: List[Pod],
                               batch: PodBatchTensors,
                               profiles: Dict[int, AffinityProfile]) -> str:
        """In-scan required (anti-)affinity tables: the kernel scan tracks
        per-(term, domain) winner-match AND winner-carry counts so each
        pod's feasibility respects EARLIER SAME-BATCH winners in both
        anti-affinity directions — the serial reference's
        assume-between-iterations visibility (scheduler.go:514), which the
        frozen batch-start mask lacks. The repair overlay stays as the
        validator for ports/volumes/chained-predecessor winners.

        Returns coverage: "installed" (tables active), "inert" (provably
        no in-batch (anti-)affinity interaction exists to validate), or
        "fallback" (caps overflowed; only the repair overlay validates).

        Terms NO batch member matches are hoisted out entirely: their
        counters could never move in-scan (only winner matches bump them),
        so the pre-batch static mask already covers them — the per-pod K
        axis then chains only genuinely carried terms through the scan.
        The [T, N] dom table comes from the topology index's epoch-keyed
        cache (one gather per node-topology change, not per batch)."""
        if not profiles:
            return "inert"
        idx = self.topology
        anti_tids: List[int] = []
        aff_tids: List[int] = []
        seen: set = set()
        for prof in profiles.values():
            for tid in prof.req_anti:
                if tid not in seen:
                    seen.add(tid)
                    anti_tids.append(tid)
            for tid, waived in prof.req_aff:
                if waived and tid not in seen:
                    seen.add(tid)
                    aff_tids.append(tid)
        if not anti_tids and not aff_tids:
            return "inert"
        # hoist: restrict the term union to terms some batch member
        # MATCHES — an unmatched term's in-scan counter is provably static
        cand = seen
        matched: set = set()
        match_sets: Dict[Tuple, frozenset] = {}
        for pod in pods:
            mkey = (pod.metadata.namespace,
                    tuple(sorted(pod.metadata.labels.items())))
            ms = match_sets.get(mkey)
            if ms is None:
                ms = idx.match_set(pod)
                match_sets[mkey] = ms
            matched |= ms & cand
            if len(matched) == len(cand):
                break
        # sorted: the table's cache key is the term-id tuple, and batches
        # popping the same templates in a different pod order must land on
        # the same cached [T, N] table (positions are per-batch anyway)
        terms = sorted(tid for tid in set(anti_tids + aff_tids)
                       if tid in matched)
        if not terms:
            return "inert"  # every candidate term is in-batch inert
        if len(terms) > self.TOPO_TERM_CAP:
            self._count_inscan_fallback("term_cap")
            return "fallback"
        P = len(pods)
        dom, n_domains = idx.term_table(tuple(terms),
                                        use_cache=self.topo_table_cache)
        # sharded drain: the padded [T, N] table also lives ON DEVICE,
        # epoch-cached and sharded by the name rules, so steady-state
        # batches skip the per-batch table upload entirely
        dom_dev = None
        if self.mirror.mesh is not None:
            dom_dev, _ = idx.term_table_device(
                tuple(terms), self.mirror.mesh,
                use_cache=self.topo_table_cache,
                dom=dom, n_domains=n_domains)
        tpos = {tid: j for j, tid in enumerate(terms)}
        # per-pod [K] term-index lists (-1 padded): the kernel's cost per
        # scan step is O(K*N), independent of the batch's term union
        anti_l: List[List[int]] = []
        aff_l: List[List[int]] = []
        match_l: List[List[int]] = []
        kmax = 1
        match_memo: Dict[Tuple, List[int]] = {}
        for i, pod in enumerate(pods):
            prof = profiles.get(i)
            a: List[int] = []
            f: List[int] = []
            if prof is not None:
                a = [tpos[tid] for tid in prof.req_anti if tid in tpos]
                f = [tpos[tid] for tid, waived in prof.req_aff
                     if waived and tid in tpos]
            mkey = (pod.metadata.namespace,
                    tuple(sorted(pod.metadata.labels.items())))
            m = match_memo.get(mkey)
            if m is None:
                ms = match_sets.get(mkey)
                if ms is None:
                    # the hoist pass short-circuits once every candidate
                    # term is matched — later templates fill in here
                    ms = idx.match_set(pod)
                    match_sets[mkey] = ms
                m = [tpos[tid] for tid in ms if tid in tpos]
                match_memo[mkey] = m
            kmax = max(kmax, len(a), len(f), len(m))
            anti_l.append(a)
            aff_l.append(f)
            match_l.append(m)
        if kmax > self.TOPO_KMAX:
            self._count_inscan_fallback("kmax")
            return "fallback"  # degenerate fan-out: repair path handles it
        # direction 2 (winner CARRIES anti term t, later pod MATCHES it):
        # a pod needs an in-scan read on t only when the block isn't
        # already implied by its own direction-1 read — i.e. unless the
        # pod itself carries t AND every batch carrier of t also matches
        # it (then {carriers} ⊆ {matchers} makes direction 1 strictly
        # stronger). The common self-anti shape (each pod carries AND
        # matches its own color) needs NO direction-2 state at all, so
        # the extra [T, D] carry table ships only when some pure matcher
        # exists.
        carrier_pos: set = set()
        carrier_ok: Dict[int, bool] = {}
        for i in range(len(pods)):
            mset = set(match_l[i])
            for t in anti_l[i]:
                carrier_pos.add(t)
                if t not in mset:
                    carrier_ok[t] = False
        cmatch_l: List[List[int]] = []
        dir2_read: set = set()
        for i in range(len(pods)):
            aset = set(anti_l[i])
            cm = [t for t in match_l[i]
                  if t in carrier_pos
                  and not (t in aset and carrier_ok.get(t, True))]
            dir2_read.update(cm)
            cmatch_l.append(cm)
        canti_l = [[t for t in anti_l[i] if t in dir2_read]
                   for i in range(len(pods))] if dir2_read else None
        if dir2_read:
            kmax = max(kmax, max(len(l) for l in cmatch_l),
                       max(len(l) for l in canti_l))
            if kmax > self.TOPO_KMAX:
                self._count_inscan_fallback("kmax")
                return "fallback"

        def to_arr(lists: List[List[int]]) -> np.ndarray:
            K = max(1, kmax)
            out = np.full((P, K), -1, np.int32)
            for i, l in enumerate(lists):
                out[i, :len(l)] = l
            return out
        batch.set_topology_terms(
            dom, n_domains, to_arr(anti_l), to_arr(aff_l), to_arr(match_l),
            cmatch_tids=to_arr(cmatch_l) if dir2_read else None,
            canti_tids=to_arr(canti_l) if dir2_read else None,
            dom_dev=dom_dev)
        self._end_inscan_streak("term_cap", "kmax")
        return "installed"

    #: in-scan soft (preferred inter-pod affinity) channel caps: a batch
    #: whose credit-channel union or per-pod fan-out overflows these falls
    #: back to SOFT_SCORE_CHUNK sub-batching (counted, never silent)
    SOFT_TERM_CAP = 64
    SOFT_KMAX = 16

    def _soft_plan_cached(self, pods: List[Pod]):
        """_soft_plan, computed once per pod-list object. Keyed by list
        IDENTITY: a truncated batch (drain slices pods[:limit]) is a new
        list and recomputes; the plan itself only depends on batch specs
        plus match-set membership of tids the first call interned, both
        stable between pop and launch on the drain thread."""
        memo = self._soft_plan_memo
        if memo is not None and memo[0] is pods:
            return memo[1]
        plan = self._soft_plan(pods)
        self._soft_plan_memo = (pods, plan)
        return plan

    def _soft_plan(self, pods: List[Pod]):
        """Channel plan for in-scan preferred inter-pod (anti-)affinity
        credits, or None when the batch can't (or needn't) run them
        in-scan. Channels are per-(kind, term) accumulators a winner
        writes and later pods read at their nodes' domains:
            m:  winners MATCHING the term (readers: the term's owners, ±w)
            ca: winners carrying the term as required affinity
                (readers: matching pods, × hard_pod_affinity_weight)
            cp/cn: winners carrying it as preferred (anti-)affinity,
                weight-summed (readers: matching pods, × ±1)
        — exactly the topology index's count kinds, scoped to one batch."""
        w = self.scorer.weights.get("InterPodAffinityPriority", 0)
        if not w:
            return None
        idx = self.topology
        hard_w = float(self.scorer.hard_pod_affinity_weight)
        channels: Dict[Tuple[str, int], int] = {}
        chan_list: List[Tuple[str, int]] = []

        def slot(kind: str, tid: int) -> int:
            k = (kind, tid)
            s = channels.get(k)
            if s is None:
                s = len(chan_list)
                channels[k] = s
                chan_list.append(k)
            return s

        # pass 1: template dedupe; own preferred read terms + carried
        # write channels (a winner's contribution to later pods)
        tmpl_key: Dict[Tuple, int] = {}
        tmpl_pods: List[Pod] = []
        tmpl_pref: List[List[Tuple[int, float]]] = []
        tmpl_carry: List[List[Tuple[str, int, float]]] = []
        tmpl_of = np.zeros((len(pods),), np.int32)
        for i, pod in enumerate(pods):
            key = self._residual_sig(pod)
            t = tmpl_key.get(key)
            if t is None:
                t = len(tmpl_pods)
                tmpl_key[key] = t
                tmpl_pods.append(pod)
                pref: List[Tuple[int, float]] = []
                carry: List[Tuple[str, int, float]] = []
                aff = pod.spec.affinity
                pa = aff.pod_affinity if aff else None
                paa = aff.pod_anti_affinity if aff else None
                for sign, kind, wterms in (
                        (1.0, "cp",
                         pa.preferred_during_scheduling_ignored_during_execution
                         if pa else ()),
                        (-1.0, "cn",
                         paa.preferred_during_scheduling_ignored_during_execution
                         if paa else ())):
                    for wt in wterms or ():
                        if not wt.weight:
                            continue
                        term = idx.ensure_match(
                            wt.pod_affinity_term.topology_key,
                            idx._resolved_ns(wt.pod_affinity_term, pod),
                            wt.pod_affinity_term.label_selector)
                        slot("m", term.tid)
                        pref.append((term.tid, sign * float(wt.weight)))
                        carry.append((kind, term.tid, float(wt.weight)))
                if hard_w and pa is not None:
                    for rt in pa.required_during_scheduling_ignored_during_execution or ():
                        term = idx._intern(
                            rt.topology_key, idx._resolved_ns(rt, pod),
                            rt.label_selector)
                        carry.append(("ca", term.tid, 1.0))
                for kind, tid, _cw in carry:
                    slot(kind, tid)
                tmpl_pref.append(pref)
                tmpl_carry.append(carry)
            tmpl_of[i] = t
        if not any(tmpl_pref):
            # no batch member carries preferred terms: only the frozen
            # symmetric-credit drift remains, which the static rows cover
            # (the same contract as the old chunk trigger) — required-only
            # batches keep the incremental class-scan fast path
            return None
        if not chan_list:
            return None  # no in-batch credit can move: static rows suffice
        # canonical template order (repr: residual sigs mix None/str/tuple
        # and are not directly comparable) — like the channel sort below,
        # this keeps rotated-pod-order batches on one chain signature
        # (soft_base row r must mean the same template batch to batch).
        # Pure renumbering; per-template structures permute consistently
        tkeys = list(tmpl_key)
        torder = sorted(range(len(tmpl_pods)),
                        key=lambda t: repr(tkeys[t]))
        tremap = {old: new for new, old in enumerate(torder)}
        tmpl_pods = [tmpl_pods[t] for t in torder]
        tmpl_pref = [tmpl_pref[t] for t in torder]
        tmpl_carry = [tmpl_carry[t] for t in torder]
        tmpl_of = np.asarray([tremap[int(t)] for t in tmpl_of], np.int32)
        tkeys = [tkeys[t] for t in torder]
        if len(chan_list) > self.SOFT_TERM_CAP:
            self._count_inscan_fallback("soft_terms")
            return None
        # canonical channel order: the dom table's cache key is the slot
        # term tuple, so pod-order-insensitive slot numbering keeps
        # repeat batches on the cached table
        chan_list = sorted(chan_list)
        channels = {k: s for s, k in enumerate(chan_list)}
        # pass 2: per-template read/write slot lists against the full
        # channel union
        read_kinds = {"ca": hard_w, "cp": 1.0, "cn": -1.0}
        tmpl_reads: List[List[Tuple[int, float]]] = []
        tmpl_writes: List[List[Tuple[int, float]]] = []
        kmax = 0
        for t, rep in enumerate(tmpl_pods):
            mset = idx.match_set(rep)
            reads = [(channels[("m", tid)], pw)
                     for tid, pw in tmpl_pref[t]]
            writes = [(channels[(kind, tid)], cw)
                      for kind, tid, cw in tmpl_carry[t]]
            for kind, tid in chan_list:
                if tid not in mset:
                    continue
                if kind == "m":
                    writes.append((channels[(kind, tid)], 1.0))
                else:
                    reads.append((channels[(kind, tid)],
                                  read_kinds[kind]))
            kmax = max(kmax, len(reads), len(writes))
            tmpl_reads.append(reads)
            tmpl_writes.append(writes)
        if kmax > self.SOFT_KMAX:
            self._count_inscan_fallback("soft_kmax")
            return None
        self._end_inscan_streak("soft_terms", "soft_kmax", "soft_gang")
        return {"chan_list": chan_list, "tmpl_of": tmpl_of,
                "tmpl_pods": tmpl_pods, "reads": tmpl_reads,
                "writes": tmpl_writes, "kmax": max(1, kmax),
                "weight": float(w), "hard_w": hard_w,
                # canonically ordered template keys: part of the soft
                # chain signature (soft_base row r must mean the same
                # template on both sides of a chained launch)
                "tmpl_sigs": tuple(tkeys)}

    def _assign_soft_terms(self, pods: List[Pod],
                           batch: PodBatchTensors) -> Optional[Tuple]:
        """Install in-scan preferred inter-pod (anti-)affinity credit
        tables: the kernel then re-scores soft credits per pod from
        running accumulators (the serial reference's re-score via
        assume-between-iterations), which lifts the SOFT_SCORE_CHUNK
        sub-batching for the common small-term-union case.

        Returns the batch's soft chain SIGNATURE (channel order +
        template order + everything the carried accumulators' meaning
        depends on), or None when no tables ride."""
        plan = self._soft_plan_cached(pods)
        self._soft_plan_memo = None   # batch consumed; drop the list ref
        if plan is None:
            return None
        idx = self.topology
        dom, n_domains = idx.term_table(
            tuple(tid for _, tid in plan["chan_list"]),
            use_cache=self.topo_table_cache)
        cap = self.mirror.t.capacity
        base_rows = []
        for rep in plan["tmpl_pods"]:
            raw = idx.score_vector(rep, plan["hard_w"])
            base_rows.append(raw if raw is not None
                             else np.zeros((cap,), np.float32))
        base = np.stack(base_rows)
        n = len(pods)
        K = plan["kmax"]
        read_tids = np.full((n, K), -1, np.int32)
        read_w = np.zeros((n, K), np.float32)
        write_tids = np.full((n, K), -1, np.int32)
        write_w = np.zeros((n, K), np.float32)
        for i in range(n):
            t = plan["tmpl_of"][i]
            for j, (s, rw) in enumerate(plan["reads"][t]):
                read_tids[i, j] = s
                read_w[i, j] = rw
            for j, (s, ww) in enumerate(plan["writes"][t]):
                write_tids[i, j] = s
                write_w[i, j] = ww
        batch.set_soft_terms(dom, n_domains, base, plan["tmpl_of"],
                             read_tids, read_w, write_tids, write_w,
                             plan["weight"])
        return (tuple(plan["chan_list"]), plan["tmpl_sigs"],
                plan["kmax"], plan["weight"], plan["hard_w"],
                n_domains, self.mirror.epoch, self.mirror.t.capacity)

    def _make_reassigner(self, batch: Optional[PodBatchTensors],
                         stale_winners):
        """A host-side serial re-solver for repair losers, or None when the
        batch can't support one (no tensors, or nominated reservations are
        in play — the kernel's nom handling has no host replica, so those
        rare cycles keep the retry path)."""
        if batch is None:
            return None
        if self.nominated is not None and self.nominated.by_node():
            return None
        return _RepairReassigner(self.mirror, batch, stale_winners)

    def _repair_batch(self, results: List[ScheduleResult],
                      profiles: Dict[int, AffinityProfile],
                      stale_winners=None,
                      batch: Optional[PodBatchTensors] = None) -> bool:
        """Validate host-evaluated predicates against earlier winners in the
        same batch; losers are demoted to retry or serially reassigned.
        Skipped when nothing in the batch carries ports/affinity/disk
        constraints. Affinity interactions run against a BatchOverlay of
        winner term counts (O(terms) dict lookups per pod) — the batch
        analog of the serial reference's cache.AssumePod visibility between
        scheduleOne iterations. Returns True when any kernel winner was
        demoted or reassigned — the kernel's in-scan counters then
        over-state (they counted the original placement), so
        kernel-unassigned pods must retry, not park."""
        # overlay NodeInfos (winner clones) are only consulted by the
        # ports/disk/attach checks — skip their maintenance entirely for
        # affinity-only batches (the deepcopy per winner is the cost)
        track_nodes = any(
            helpers.pod_host_ports(r.pod) or _pod_has_conflict_volumes(r.pod)
            or _pod_has_pvc(r.pod) or _pod_has_attach_volumes(r.pod)
            for r in results)
        if not track_nodes and not profiles and not stale_winners:
            return False
        overlay: Dict[str, NodeInfo] = {}
        #: affinity tracking only matters when some pod validates it or a
        #: chained predecessor's winners are invisible to this batch's mask
        aff_overlay = BatchOverlay(self.topology) \
            if profiles or stale_winners else None
        any_winners = False
        if aff_overlay is not None and stale_winners:
            # a chained predecessor's committed winners: this batch's
            # snapshot/index/mask predate them, so they participate in
            # repair exactly like earlier same-batch winners
            for w_pod, w_node in stale_winners:
                aff_overlay.add_winner(w_pod, w_node)
            any_winners = True
        # PV names earlier winners will reserve: two winners in one batch
        # must not both claim the single matching PV (the serial reference
        # reserves via AssumePodVolumes between scheduleOne iterations)
        taken_pvs: set = set()
        empty_profile = AffinityProfile()
        reassigner = self._make_reassigner(batch, stale_winners)

        def overlay_node(name: str) -> Optional[NodeInfo]:
            ni = overlay.get(name)
            if ni is None:
                base = self.snapshot.node_infos.get(name)
                if base is None:
                    return None
                ni = base.clone()
                overlay[name] = ni
            return ni

        def node_passes(i: int, pod: Pod, name: str, has_ports: bool,
                        has_disk: bool, has_attach: bool):
            """(ok, pvs) for placing pod i on `name` given earlier winners
            — the SAME checks the kernel pick runs through below."""
            pvs_local: List[str] = []
            if _pod_has_pvc(pod):
                ni = overlay_node(name)
                if ni is None or ni.node is None:
                    return False, pvs_local
                found = self.volume_binder.preview_bindings(
                    pod, ni.node, exclude=taken_pvs)
                if found is None:
                    return False, pvs_local
                pvs_local = found
            if any_winners and (has_ports or has_disk or has_attach):
                ni = overlay_node(name)
                if ni is None:
                    return False, pvs_local
                if has_ports:
                    ok, _ = preds.pod_fits_host_ports(pod, None, ni)
                    if not ok:
                        return False, pvs_local
                if has_disk:
                    ok, _ = preds.no_disk_conflict(pod, None, ni)
                    if not ok:
                        return False, pvs_local
                if has_attach:
                    # earlier winners on this node count against limits
                    for fn in self._volume_count_preds.values():
                        ok, _ = fn(pod, None, ni)
                        if not ok:
                            return False, pvs_local
            if aff_overlay is not None and any_winners and \
                    aff_overlay.conflicts(pod, profiles.get(i, empty_profile),
                                          name):
                return False, pvs_local
            return True, pvs_local

        def try_reassign(i: int, res: ScheduleResult, has_ports: bool,
                         has_disk: bool, has_attach: bool):
            """Serial re-solve: walk candidates in kernel score order until
            one passes every check. Returns that node's pvs, or None."""
            if reassigner is None:
                return None
            for cand in reassigner.candidates(i):
                if cand == res.node_name:
                    continue  # the failed pick
                ok, pvs_c = node_passes(i, res.pod, cand, has_ports,
                                        has_disk, has_attach)
                if ok:
                    res.node_name = cand
                    res.reassigned = True
                    reassigner.reassigned_any = True
                    return pvs_c
            return None

        winner_moved = False
        for i, res in enumerate(results):
            if res.node_name is None:
                continue
            pod = res.pod
            has_ports = bool(helpers.pod_host_ports(pod))
            has_disk = _pod_has_conflict_volumes(pod)
            has_attach = _pod_has_attach_volumes(pod) or _pod_has_pvc(pod)
            ok, pvs = node_passes(i, pod, res.node_name, has_ports,
                                  has_disk, has_attach)
            if not ok:
                winner_moved = True
                # the serial reference would just have picked the next-best
                # node for this pod; do that here instead of a retry round
                pvs = try_reassign(i, res, has_ports, has_disk, has_attach)
                if pvs is None:
                    res.node_name = None
                    res.retry = True
                    continue
            # record the winner in the overlays; its PVs block later pods
            taken_pvs.update(pvs)
            if track_nodes:
                bound = deepcopy_obj(pod)
                bound.spec.node_name = res.node_name
                ni = overlay_node(res.node_name)
                if ni is not None:
                    ni.add_pod(bound)
            if aff_overlay is not None:
                aff_overlay.add_winner(pod, res.node_name)
            if reassigner is not None:
                reassigner.add_winner(i, res.node_name)
            any_winners = True
        if reassigner is not None and reassigner.reassigned_any:
            # reassigned pods sit on different rows than the kernel's
            # adopted usage counted them on; no dirty row repairs that —
            # drop device usage so the next launch re-uploads host truth
            self.mirror.invalidate_usage()
        return winner_moved

    # ------------------------------------------------------------- schedule

    def schedule(self, pods: List[Pod]) -> List[ScheduleResult]:
        """Schedule a batch; results preserve input order (which is the
        queue's priority-then-FIFO order, so the scan's serial semantics
        match the reference's one-at-a-time loop).

        Device discipline (the TPU sits behind a high-latency tunnel): one
        dirty-row scatter + one scan dispatch + one packed fetch per batch.
        When the batch needed no host-side repair, the kernel's post-batch
        usage is adopted on device (TensorMirror.adopt_usage), so the next
        batch's scatter only rewrites rows the host actually disagrees on."""
        pending = self.schedule_launch(pods)
        if pending is None:
            return []
        return self.schedule_finish(pending)

    def schedule_launch(self, pods: List[Pod],
                        chain: Optional["PendingBatch"] = None,
                        chain_seq: Optional[int] = None
                        ) -> Optional["PendingBatch"]:
        """Front half of a batch: refresh + tensorize + device dispatch.
        Returns a PendingBatch whose results are fetched by schedule_finish —
        the device scan runs while the caller does host work (the pipelined
        drain overlaps batch N+1's kernel with batch N's bind/assume).

        `chain` pipelines this launch on the previous one *before its results
        are committed*: the kernel's usage input is the chain's post-batch
        device handle instead of the mirror's. Honored only when that handle
        is provably host truth + the chain's own assignments:
          - the chain batch is residual-free (no repair can demote a winner),
          - every cache mutation since the drain's bookkeeping point came
            from the drain's own assumes (`chain_seq`: either the expected
            mutation_seq, or a callable the pipelined drain supplies that
            performs the {mutation_seq == base + own assumes} comparison
            under the cache lock — the commit thread assumes concurrently,
            so a point-in-time integer cannot express the condition),
          - device state survived (no capacity/column resize), and
          - this batch carries no host-computed static scores (they would be
            one batch staler than the sequential path).
        Gang-carrying batches chain too (both directions): the gang kernel's
        trial/commit carry means its post-batch usage holds only COMMITTED
        gangs' placements, and every committed member is assumed (bind path
        or permit-gate reservation) — losses after the chain was taken
        (atomicity demotions, permit rejects) surface through the same
        phantom/epoch machinery as singleton losses.
        Otherwise returns None and the caller must flush the pipeline and
        relaunch unchained."""
        if not pods:
            return None
        from ..utils.features import DEFAULT_FEATURE_GATE
        from .kernels.batch import pack_results, schedule_batch
        dirty = self.cache.update_snapshot(self.snapshot)
        # volume predicates can NEVER ride a chain (PV reservations need
        # committed state); affinity CAN — its stale mask (snapshot lacks
        # the chain's uncommitted winners) is repaired post-kernel against
        # stale_winners, the same overlay that validates same-batch winners
        affinity_only = not self._has_filter_extenders() and all(
            not (_pod_has_conflict_volumes(p) or _pod_has_pvc(p)
                 or _pod_has_attach_volumes(p)) for p in pods)
        chain_intact = chain_seq is not None and (
            chain_seq() if callable(chain_seq)
            else self.cache.mutation_seq == chain_seq)
        chaining = (chain is not None
                    and (chain.residual_free or chain.affinity_chainable)
                    and DEFAULT_FEATURE_GATE.enabled("SchedulerDeviceChaining")
                    and chain_intact
                    and not self._static_likely
                    and self.mirror.device_ready()
                    and affinity_only)
        if chaining:
            self.mirror.apply_chained(self.snapshot, dirty)
            self.topology.apply(self.snapshot, dirty)
            if dirty:
                # keep the scorer's gate fresh on the chained path too: if
                # this drain's own commits introduced score-contributing
                # carriers, static_scores below turns non-None and refuses
                # the chain — matching the sequential path's scoring
                self.scorer.set_cluster_has_affinity_pods(
                    self.topology.has_score_carriers())
        else:
            # the dirty list is consumed either way — a chain refusal must
            # still apply it, or the mirror would never see these updates
            # (update_snapshot won't return them again)
            self.mirror.apply(self.snapshot, dirty)
            self.topology.apply(self.snapshot, dirty)
            if dirty:
                self.scorer.set_cluster_has_affinity_pods(
                    self.topology.has_score_carriers())
            if chain is not None:
                return None
        import time as _time
        tr = self.tracer if self.tracer is not None \
            and self.tracer.enabled else None
        t_tz = tr.now() if tr is not None else 0.0
        t_prep = _time.perf_counter()
        extra_mask, profiles, extra_group = self._residual_mask(pods)
        residual_free = extra_mask is None and not any(
            helpers.pod_host_ports(p) or _pod_has_conflict_volumes(p)
            for p in pods)
        affinity_chainable = affinity_only and not any(
            helpers.pod_host_ports(p) for p in pods)
        #: gang units present -> the all-or-nothing kernel decides this
        #: batch. Gang batches CHAIN like singleton batches: the kernel's
        #: trial/commit carry isolates uncommitted (rejected-gang) state,
        #: so its post-batch usage is exactly committed-gang placements —
        #: each of which the commit path assumes (bind or reservation)
        gang_units = self.gang.batch_groups(pods) \
            if self.gang is not None else None
        batch = PodBatchTensors(pods, self.mirror, self.terms,
                                extra_mask=extra_mask,
                                extra_group=extra_group,
                                seq_base=self._seq_base)
        self._seq_base += len(pods)
        w = self.scorer.weights
        batch.resource_weights[0] = w.get("LeastRequestedPriority", 1)
        batch.resource_weights[1] = w.get("BalancedResourceAllocation", 1)
        # gang batches skip the in-scan spread/topology tables — the
        # gang kernel's trial/commit scan does not carry them; repair
        # (with whole-gang demotion) validates affinity interactions,
        # matching the pre-in-scan semantics. Soft credit tables DO ride
        # gang batches (trial/committed accumulators in the gang carry —
        # what lifted the soft_gang sub-batching), and nominated
        # reservations ride both kernels as the same phantom overlay (a
        # mixed batch's singletons must not steal a preemptor's freed
        # space).
        spread_sig = None
        topo_cover = "fallback"
        if gang_units is None:
            spread_sig = self._assign_spread_groups(pods, batch)
            topo_cover = self._assign_topology_terms(pods, batch, profiles)
        soft_sig = self._assign_soft_terms(pods, batch)
        spread_present = spread_sig is not None
        soft_present = soft_sig is not None
        self.phase_stats["term_prep_s"] += _time.perf_counter() - t_prep
        if tr is not None:
            tr.record("scheduler", "tensorize", t_tz, tr.now(),
                      pods=len(pods))
        nom_dev = self._nominated_device()
        if nom_dev is not None:
            # each pod's own nominated row, from the EXACT snapshot the
            # reservation tensor was built from (pod.status and even the
            # live map may lag) — subtraction and tensor can never desync
            for i, pod in enumerate(pods):
                row = self._nom_rows_by_key.get(pod.metadata.key())
                if row is not None:
                    batch.nom_row[i] = row
        static = self.scorer.static_scores(pods, batch)
        has_prio_ext = any(e.config.prioritize_verb for e in self.extenders)
        # hysteresis: while host-computed static scores are in play, later
        # launches refuse the chain up front instead of discarding work.
        # In-scan spread/soft tables no longer force the flush: their
        # running counts CHAIN as carried device state (gated below), so
        # the old recompute-from-batch-start invalidation is gone
        self._static_likely = static is not None or has_prio_ext
        if has_prio_ext:
            if chaining:
                return None  # host scores would lag the uncommitted chain
            self._apply_prioritize_extenders(pods, batch, static)
        elif static is not None:
            if chaining:
                return None
            batch.set_static_scores(*static)
        if chaining and (spread_present or soft_present) and \
                not self._chain_carries(chain, batch, spread_sig, soft_sig):
            # the predecessor's carried counts don't structurally match
            # this batch's tables — relaunch sequentially from host truth
            return None
        if chaining and not self.mirror.device_ready():
            return None  # tensorize grew the column axis; chain handle stale
        if gang_units is None and self.class_scan:
            # the incremental class-indexed scan: per-(template, score-row)
            # masked-score rows in the carry, one column refresh per winner
            # (kernels/batch.py _schedule_batch_classes). Spread groups,
            # soft credits, and nominated reservations ride the carry /
            # phantom overlay, so EVERY non-gang batch takes the fast path
            batch.enable_class_scan()
        if chaining:
            node_cfg, usage = self.mirror.device_cfg(), chain.new_usage
            self.chained_launches += 1
        else:
            node_cfg, usage = self.mirror.device_cfg_usage()
        sharded = False
        spec_stats = None
        spec_inputs = None
        if gang_units is not None:
            from .kernels.gang import gang_schedule_batch
            assign_d, scores_d, new_usage = gang_schedule_batch(
                node_cfg, usage, batch.device(self.mirror.mesh),
                self._gang_device_table(gang_units, batch), nom_dev)
        elif batch._class_tables is not None \
                and sharding_mod.use_shard_map(self.mirror.mesh,
                                               self.mirror.t.capacity):
            # the sharded drain's hot path: per-shard filter+score with a
            # cross-shard argmax (kernels/batch.py schedule_batch_sharded)
            # — bit-identical decisions to the single-device class scan
            from .kernels.batch import schedule_batch_sharded
            sharded = True
            if self.sched_metrics is not None:
                self.sched_metrics.sharded_batches.inc()
            assign_d, scores_d, new_usage = schedule_batch_sharded(
                self.mirror.mesh, node_cfg, usage,
                batch.device(self.mirror.mesh), nom_dev)
        elif self.speculative and batch._class_tables is not None:
            # speculative cohort assignment (kernels/speculative.py):
            # vmapped K-pod cohort proposals against the frozen class
            # table, exact collision detection, serial whole-cohort
            # repair — bit-identical decisions to the serial scan, with
            # per-cohort stats folded into metrics by schedule_finish
            from .kernels.speculative import (_SPEC_MIN_PLAIN,
                                              cohort_width,
                                              schedule_batch_speculative)
            w = cohort_width(batch.req.shape[0])
            batch.set_speculative(w)
            # contention gate: a batch that is mostly non-plain trips
            # the structural fence on (nearly) every cohort, so the
            # election + exact collision checks are pure overhead —
            # measured over the ACTIVE prefix (pads are trivially plain
            # and would inflate the fraction)
            frac = (float(batch.spec_plain[:len(pods)].mean())
                    if pods else 0.0)
            if frac < _SPEC_MIN_PLAIN:
                batch.spec_plain = None
                batch.cohort_id = None
                assign_d, scores_d, new_usage = schedule_batch(
                    node_cfg, usage, batch.device(self.mirror.mesh),
                    nom_dev)
            else:
                dev = batch.device(self.mirror.mesh)
                assign_d, scores_d, new_usage, spec_stats = \
                    schedule_batch_speculative(node_cfg, usage, dev,
                                               nom_dev, width=w)
                if self.spec_oracle:
                    spec_inputs = (node_cfg, usage, dev, nom_dev)
        else:
            assign_d, scores_d, new_usage = schedule_batch(
                node_cfg, usage, batch.device(self.mirror.mesh), nom_dev)
        if self.sched_metrics is not None and self.mirror.mesh is not None:
            # padding added for shard divisibility is VISIBLE (KTPU005):
            # the gauge tracks the mirror's current shard-pad rows
            self.sched_metrics.mirror_shard_pad_rows.set(
                self.mirror.shard_pad_rows)
        return PendingBatch(pods=pods, profiles=profiles, batch=batch,
                            sharded=sharded,
                            packed=pack_results(assign_d, scores_d),
                            new_usage=new_usage,
                            residual_free=residual_free,
                            affinity_chainable=affinity_chainable,
                            chained=chaining,
                            usage_epoch=self.mirror.usage_epoch,
                            gang_units=gang_units,
                            spread_sig=spread_sig, soft_sig=soft_sig,
                            spec_stats=spec_stats,
                            spec_inputs=spec_inputs,
                            inscan_cover=(affinity_chainable
                                          and topo_cover != "fallback"))

    def _chain_carries(self, chain: "PendingBatch", batch: PodBatchTensors,
                       spread_sig: Optional[Tuple],
                       soft_sig: Optional[Tuple]) -> bool:
        """Gate for chaining THROUGH in-scan spread/soft tables.

        The kernel's spread counts and soft credit accumulators ride the
        chained usage handle ("spread" / "soft_cnt" finals), accumulating
        every in-chain winner over the ANCHOR batch's base rows. A
        successor may consume them only when its own tables resolve to
        the same STRUCTURE (group/channel/template order, zones, weights
        — the chain signatures), so slot g/s means the same thing on both
        sides. When the gate passes, this batch's freshly computed base
        rows are REPLACED with the chain predecessor's (transitively the
        anchor's): commits landing mid-chain fold those same winners into
        freshly computed rows, and anchor-base + chained-counts already
        accounts for every one of them exactly once — the sum equals the
        sequential path's recompute, which is what the chained-vs-
        unchained spread parity test pins."""
        nu = chain.new_usage
        if not isinstance(nu, dict):
            return False
        if spread_sig is not None and (
                chain.spread_sig != spread_sig or "spread" not in nu):
            return False
        if soft_sig is not None and (
                chain.soft_sig != soft_sig or "soft_cnt" not in nu):
            return False
        if spread_sig is not None:
            batch.spread_base = chain.batch.spread_base
            batch.spread_zone = chain.batch.spread_zone
            batch.spread_zinit = chain.batch.spread_zinit
        if soft_sig is not None:
            batch.soft_base = chain.batch.soft_base
        return True

    def _account_speculative(self, pending: "PendingBatch",
                             assign) -> None:
        """Fold a speculative batch's per-cohort stats into the
        scheduler_speculative_* counters and, under the divergence
        oracle, replay the serial scan on the captured inputs and
        attribute any mismatch (expected: none — the kernel's contract
        is bit-identity, and the counter existing is how production
        proves it rather than assumes it)."""
        import numpy as np
        st = np.asarray(pending.spec_stats)          # [n, 2]
        n = st.shape[0]
        width = pending.batch.req.shape[0] // max(n, 1)
        collided = st[:, 0] == 0
        repaired = int((width - st[collided, 1]).sum())
        m = self.sched_metrics
        if m is not None:
            m.speculative_cohorts.inc(n)
            m.speculative_collisions.inc(int(collided.sum()))
            m.speculative_repaired.inc(repaired)
        # per-batch record for the bench's cohort-size distribution
        # (counters aggregate across batches; the log keeps the widths)
        self.spec_batch_log.append(
            (int(width), int(n), int(collided.sum()), repaired))
        if pending.spec_inputs is not None:
            from .kernels.speculative import (divergence_report,
                                              speculative_reference)
            node_cfg, usage, dev, nom_dev = pending.spec_inputs
            ref_assign, _ = speculative_reference(node_cfg, usage, dev,
                                                  nom_dev)
            report = divergence_report(assign, ref_assign, width)
            if report:
                if m is not None:
                    m.speculative_divergences.inc(len(report))
                self.spec_divergence_log.extend(report)

    def schedule_finish(self, pending: "PendingBatch") -> List[ScheduleResult]:
        """Back half: fetch results, host repair, adopt chained usage."""
        import time as _time
        from .kernels.batch import unpack_results
        tr = self.tracer if self.tracer is not None \
            and self.tracer.enabled else None
        t_sw = tr.now() if tr is not None else 0.0
        t0 = _time.perf_counter()
        assign, scores = unpack_results(pending.packed)
        fetch_wait = _time.perf_counter() - t0
        self.phase_stats["scan_wait_s"] += fetch_wait
        if pending.sharded and self.sched_metrics is not None:
            # the fetch drains the cross-shard argmax pipeline: this is
            # the wall time spent synchronizing the mesh for this batch
            self.sched_metrics.shard_sync_seconds.observe(fetch_wait)
        if tr is not None:
            tr.record("scheduler", "scan_wait", t_sw, tr.now(),
                      pods=len(pending.pods))
        if pending.spec_stats is not None:
            self._account_speculative(pending, assign)
        out: List[ScheduleResult] = []
        for i, pod in enumerate(pending.pods):
            row = int(assign[i])
            name = self.mirror.name_of.get(row) if row >= 0 else None
            out.append(ScheduleResult(pod, name, float(scores[i])))
        if pending.phantom:
            # the chained-in usage counted winners the predecessor later
            # lost: an unassigned pod may have been starved by that phantom
            # space — retry instead of parking as unschedulable (the next
            # cycle launches unchained from repaired host truth)
            for r in out:
                if r.node_name is None:
                    r.retry = True
        t1 = _time.perf_counter()
        moved = False
        if not (pending.inscan_cover and not pending.stale_winners):
            moved = self._repair_batch(
                out, pending.profiles, pending.stale_winners,
                # no serial reassignment for gang batches: the reassigner
                # is blind to the gang's ICI-domain pin, so a "repaired"
                # member could land outside the slice — demote-and-retry
                # instead, and atomicity below demotes its gang with it
                batch=None if pending.gang_units else pending.batch)
        # else: the kernel's in-scan tables already enforced every
        # in-batch (anti-)affinity interaction (both directions + waived
        # co-location) and the batch carries no ports/volumes/extenders —
        # the overlay walk would re-prove what the scan decided
        self.phase_stats["repair_s"] += _time.perf_counter() - t1
        if pending.gang_units:
            self._enforce_gang_atomicity(out, pending.gang_units)
        if moved and pending.batch.anti_dom is not None:
            # the in-scan (anti-)affinity counters counted a winner the
            # repair moved/demoted: pods the scan left unassigned may have
            # been blocked by that placement — retry them instead of
            # parking (the next cycle's counters reflect host truth)
            for r in out:
                if r.node_name is None:
                    r.retry = True
        if not any(r.retry for r in out):
            # every surviving assignment flows through cache.assume_pod, so
            # the chained usage matches host truth (or gets scatter-repaired).
            # The epoch is checked INSIDE adopt_usage (atomically with the
            # write): an invalidate_usage after this batch launched means
            # its usage input carries the phantom state that invalidation
            # dropped — re-adopting would resurrect it, so it is refused.
            # Only the mirror's three usage tensors are adopted — the
            # spread/soft carry finals riding new_usage exist solely for
            # the NEXT chained launch (PendingBatch.new_usage keeps them).
            self.mirror.adopt_usage(
                {k: pending.new_usage[k]
                 for k in ("used", "nonzero_used", "pod_count")},
                epoch=pending.usage_epoch)
        return out

    def _enforce_gang_atomicity(self, results: List[ScheduleResult],
                                units: list) -> None:
        """Post-repair all-or-nothing: host repair may demote individual
        members (ports/affinity/volume conflicts the kernel cannot see); a
        gang that lost ANY member binds none, and the survivors retry
        together next cycle. Kernel-level rejections (the whole gang
        already unassigned) park as unschedulable instead and are counted
        as rejected."""
        gm = self.gang
        for idxs, _tk, is_gang, _pin in units:
            if not is_gang:
                continue
            rs = [results[i] for i in idxs]
            placed = sum(1 for r in rs if r.node_name is not None)
            if 0 < placed < len(rs):
                for r in rs:
                    r.node_name = None
                    r.reassigned = False
                    r.retry = True
            elif placed == 0 and gm is not None and gm.metrics is not None:
                gm.metrics.gangs_rejected.inc()

    def _gang_device_table(self, units: list, batch: PodBatchTensors) -> dict:
        """Flattened gang-entry tensors for kernels/gang.py (entry-stream
        layout documented there). The entry axis equals the batch's padded
        pod axis, so gang batches introduce no new XLA bucket shapes;
        padding entries are their own empty units. Topology-key domain
        vectors come from the incremental topology index
        (TopologyIndex.node_domain_vector)."""
        P = batch.req.shape[0]
        N = self.mirror.t.capacity
        pod_idx = np.full((P,), -1, np.int32)
        start = np.zeros((P,), bool)
        end = np.zeros((P,), bool)
        # pads default to their own (position-numbered) unit ids; real
        # units use list order, which pad positions can never collide with
        gang_id = np.arange(P, dtype=np.int32)
        entry_dom = np.full((P,), -1, np.int32)
        pin_dom = np.full((P,), -1, np.int32)
        # capacity-aware domain feasibility inputs: the gang's in-batch
        # member count and elementwise-max member request, read by the
        # kernel at each gang's start entry (kernels/gang.py has_cap)
        need = np.zeros((P,), np.float32)
        greq = np.zeros((P, batch.req.shape[1]), np.float32)
        req_np = np.asarray(batch.req)
        dom_index: Dict[str, int] = {}
        dom_rows: List[np.ndarray] = []
        t = 0
        for u, (idxs, tk, _is_gang, pin) in enumerate(units):
            d = -1
            p_id = -1
            if tk:
                d = dom_index.get(tk, -1)
                if d < 0:
                    d = len(dom_rows)
                    dom_index[tk] = d
                    dom_rows.append(self.topology.node_domain_vector(tk)
                                    [:N].astype(np.int32))
                if pin is not None:
                    # the gang's earlier batches reserved in this domain:
                    # seed the kernel's carry so stragglers only join it.
                    # Interning handles a value no live node carries (the
                    # slice vanished) — the id matches nothing and the
                    # members wait for the permit timeout to clear the pin
                    p_id = self.topology._dom_id(tk, pin)
            unit_greq = req_np[idxs].max(axis=0) if idxs else None
            for j, i in enumerate(idxs):
                pod_idx[t] = i
                start[t] = j == 0
                end[t] = j == len(idxs) - 1
                gang_id[t] = u
                entry_dom[t] = d
                pin_dom[t] = p_id
                need[t] = len(idxs)
                greq[t] = unit_greq
                t += 1
        start[t:] = True
        end[t:] = True
        from .tensorize import _bucket
        K = _bucket(len(dom_rows), minimum=1)
        dom_tab = np.full((K, N), -1, np.int32)
        if dom_rows:
            dom_tab[:len(dom_rows)] = np.stack(dom_rows)
        put = self.mirror.put_replicated
        out = {"pod_idx": put(pod_idx), "start": put(start),
               "end": put(end), "gang_id": put(gang_id),
               "entry_dom_idx": put(entry_dom), "pin_dom": put(pin_dom),
               "need": put(need), "greq": put(greq),
               # node axis shards with the mirror, by the name-keyed rule
               "dom_tab": self.mirror.put_named("dom_tab", dom_tab)}
        return out

    def _nominated_device(self) -> Optional[dict]:
        """Aggregated nominated-pod reservations as device tensors
        ({used [N,R], count [N]}), or None when nothing is nominated.
        Cached by (nominated.version, mirror.epoch, tensor shape) — the
        mirror epoch covers node-row reuse: a deleted node's row can be
        handed to a new node, and a stale tensor would charge the old
        reservation to the wrong node. Nominations are rare so the
        rebuild+upload almost never runs. Nominees already assumed into
        the cache are excluded — their usage is real, not phantom."""
        from ..utils.features import DEFAULT_FEATURE_GATE
        if not DEFAULT_FEATURE_GATE.enabled("SchedulerNominatedReservations"):
            return None
        ver = self.nominated.version
        shape = (self.mirror.t.capacity, self.mirror.t.n_cols)
        key = (ver, self.mirror.epoch, shape)
        if key == self._nom_key:
            return self._nom_dev
        from .nodeinfo import pod_resource
        from .tensorize import COL_CPU, COL_EPH, COL_MEM, _f32_ceil
        used = None
        count = None
        rows_by_key: Dict[str, int] = {}
        for node_name, pods in self.nominated.by_node().items():
            row = self.mirror.row_of.get(node_name)
            if row is None:
                continue
            for p in pods:
                if self.cache.assigned_node(p.metadata.key()) is not None:
                    continue
                if used is None:
                    used = np.zeros(shape, np.float32)
                    count = np.zeros((shape[0],), np.float32)
                r = pod_resource(p)
                used[row, COL_CPU] += _f32_ceil(r.milli_cpu)
                used[row, COL_MEM] += _f32_ceil(r.memory)
                used[row, COL_EPH] += _f32_ceil(r.ephemeral_storage)
                for rname, v in r.scalar_resources.items():
                    used[row, self.mirror.vocab.col(rname)] += _f32_ceil(v)
                count[row] += 1.0
                rows_by_key[p.metadata.key()] = row
        if used is None:
            self._nom_dev = None
        else:
            # node-axis tensors: shard with the mirror's mesh
            self._nom_dev = {"used": self.mirror.put_nodes(used),
                             "count": self.mirror.put_nodes(count)}
        #: pod key -> reserved row, exactly as charged into _nom_dev
        self._nom_rows_by_key = rows_by_key
        self._nom_key = key
        return self._nom_dev

    # ------------------------------------------------------------ preempt

    def _fits_predicates(self, pod: Pod) -> Dict[str, object]:
        """The predicate set a victim-search fit check runs (same assembly
        as explain())."""
        all_preds = dict(preds.DEFAULT_PREDICATES)
        if _pod_has_pvc(pod) or _pod_has_attach_volumes(pod):
            all_preds.update(self._volume_count_preds)
            all_preds["NoVolumeZoneConflict"] = self._zone_conflict
            all_preds["CheckVolumeBinding"] = \
                preds.check_volume_binding_factory(self.volume_binder)
        return all_preds

    #: max candidate nodes that undergo the full clone+reprieve victim
    #: search per preempting pod (see the ranking proxy in preempt())
    PREEMPT_CANDIDATE_CAP = 100

    def preempt(self, pod: Pod):
        """Ref: generic_scheduler.go Preempt (:310-369). Returns a
        PreemptionPlan or None. Pure computation — the shell performs the
        API writes (nominate, delete victims, clear lower nominations)."""
        from . import preemption as pre
        self.refresh()
        infos = self.snapshot.node_infos
        # A standing nomination on a still-viable node blocks re-preemption:
        # the kernel's reservation tensors guarantee the freed space, so the
        # pod only needs to wait for the victim deletions to reach the cache.
        # (The reference gates on victims still carrying a DeletionTimestamp,
        # :1130-1150 — useless here because the in-process store deletes
        # instantly; without this guard a retry racing the delete events
        # re-preempts a SECOND node.) A vanished/shrunk node drops the
        # reservation and falls through to a fresh preemption.
        nn = self.nominated.node_for(pod.metadata.key())
        if nn:
            ni = infos.get(nn)
            if ni is not None and pre.node_could_ever_fit(pod, ni):
                return None
            self.nominated.delete(pod)
        if not pre.pod_eligible_to_preempt_others(pod, infos):
            return None
        # candidate rows: pod-independent constraints must pass — failures
        # preemption can't fix (ref: nodesWherePreemptionMightHelp
        # unresolvable reasons); cached vectors, no per-node python
        t = self.mirror.t
        vec = (self.terms.tolerations_vector(pod)
               & self.terms.node_selector_vector(pod)
               & t.node_ok & t.valid)
        hv = self.terms.hostname_vector(pod)
        if hv is not None:
            vec = vec & hv
        pdbs = list(self.pdb_lister())
        candidates = []
        for row in np.nonzero(vec)[0]:
            name = self.mirror.name_of.get(int(row))
            ni = infos.get(name) if name else None
            if ni is None or not pre.resource_screen(pod, ni):
                continue
            candidates.append((name, ni))
        if self.preempt_kernel:
            # batched victim-pricing kernel: all candidates tensorized at
            # once (no CAP truncation — the scan is O(N·V) device work,
            # not per-node python clones)
            return self._preempt_kernel_plan(pod, candidates, infos, pdbs)
        # serial reprieve path only: full-predicate fit closure + the
        # cluster-wide metadata its per-node clones derive from
        all_preds = self._fits_predicates(pod)

        def fits(p, meta, ni) -> bool:
            ok, _ = preds.pod_fits_on_node(p, meta, ni, all_preds)
            return ok
        base_meta = preds.PredicateMetadata(pod, infos)
        if len(candidates) > self.PREEMPT_CANDIDATE_CAP:
            self._count_capped_scan("preempt_candidates", len(candidates))
            # cost bound: the clone + reprieve loop per candidate is host
            # python (the reference absorbs full-cluster cost with 16
            # goroutines, :996); rank by a cheap proxy for pick_one_node's
            # criteria — PDB-clean first (its FIRST criterion), then
            # lowest max victim priority, then fewest lower-priority pods
            # — and search only the best CAP. A mass high-priority burst
            # over 5k full nodes stays O(CAP×pods/node) instead of
            # O(nodes×pods/node) per pod.
            prio = helpers.pod_priority(pod)

            def touches_pdb(p) -> bool:
                from ..api import labels as labelsmod
                for pdb in pdbs:
                    if pdb.metadata.namespace == p.metadata.namespace and \
                            pdb.spec.selector is not None and \
                            labelsmod.matches(pdb.spec.selector,
                                              p.metadata.labels):
                        return True
                return False

            from .nodeinfo import pod_resource
            need = pod_resource(pod)

            def proxy(item):
                """Greedy estimate of the MINIMAL victim set (lowest
                priority first until the preemptor's resources fit) and
                pick_one_node's criteria over THAT set — ranking by all
                lower-priority pods instead over-penalizes nodes whose
                minimal set is tiny (a divergence the proxy-equivalence
                fixture exposed)."""
                _, ni = item
                lower = sorted(
                    (p for p in ni.pods
                     if helpers.pod_priority(p) < prio),
                    key=helpers.pod_priority)
                free_cpu = ni.allocatable.milli_cpu \
                    - ni.requested.milli_cpu
                free_mem = ni.allocatable.memory - ni.requested.memory
                # extended scalars too (google.com/tpu): a TPU-bound
                # preemptor on cpu-rich nodes would otherwise estimate
                # empty victim sets everywhere and rank arbitrarily
                free_sc = {k: ni.allocatable.scalar_resources.get(k, 0)
                           - ni.requested.scalar_resources.get(k, 0)
                           for k in need.scalar_resources}

                def fits_now():
                    return (free_cpu >= need.milli_cpu
                            and free_mem >= need.memory
                            and all(free_sc[k] >= v for k, v in
                                    need.scalar_resources.items()))
                victims = []
                for p in lower:
                    if fits_now():
                        break
                    r = pod_resource(p)
                    free_cpu += r.milli_cpu
                    free_mem += r.memory
                    for k in free_sc:
                        free_sc[k] += r.scalar_resources.get(k, 0)
                    victims.append(p)
                has_pdb = any(touches_pdb(p) for p in victims) if pdbs \
                    else False
                prios = [helpers.pod_priority(p) for p in victims]
                return (has_pdb, max(prios, default=0),
                        sum(prios), len(victims))
            candidates.sort(key=proxy)
            candidates = candidates[:self.PREEMPT_CANDIDATE_CAP]
        else:
            self._end_inscan_streak("preempt_candidates")
        victims_map: Dict[str, Tuple[List[Pod], int]] = {}
        for name, ni in candidates:
            sel = pre.select_victims_on_node(pod, ni, infos, fits, pdbs,
                                             base_meta=base_meta)
            if sel is not None:
                victims_map[name] = sel
        node = pre.pick_one_node_for_preemption(victims_map)
        if node is None:
            return None
        victims, nviol = victims_map[node]
        return pre.PreemptionPlan(
            node_name=node, victims=victims, num_pdb_violations=nviol,
            nominated_to_clear=pre.nominated_pods_to_clear(
                pod, node, self.nominated.pods_for_node(node)))

    def _overshare_ranks(self):
        """The DRF pricing input for the victim tables: quantized
        over-share ranks per tenant, or None when no DRF account is
        installed, the flag is off, or every tenant sits at/below fair
        share (the legacy tenant-blind order in all three cases)."""
        if self.drf is None:
            return None
        from ..tenancy.drf import drf_enabled
        if not drf_enabled():
            return None
        return self.drf.overshare_ranks() or None

    def _preempt_kernel_plan(self, pod: Pod, candidates, infos, pdbs):
        """The batched path: tensorize every candidate's victims into
        band-sorted [N, V] pricing tables, run the masked prefix-sum fit
        scan + lexicographic winner on device, expand the winner's
        chosen prefix back into pods. PDB-violating victims ride the
        last-resort band; gang victims are priced as whole PodGroups."""
        from .kernels import preempt as pk
        tabs = pk.build_victim_tables(pod, candidates, infos, pdbs,
                                      unit_cache=self._preempt_unit_cache,
                                      overshare=self._overshare_ranks())
        if tabs is None:
            return None
        from . import preemption as pre
        a = tabs.arrays
        winner_d, chosen_d, _k, nviol_d = pk.price_nodes(
            a["free0"], a["cfree0"], a["need"], a["need_cnt"], a["freed"],
            a["fcnt"], a["valid"], a["pdb"], a["top"], a["psum"],
            a["gcnt"], a["startr"], a["row_valid"])
        winner = int(winner_d)
        if winner < 0:
            return None
        victims = tabs.expand(winner, np.asarray(chosen_d[winner]))
        if not victims:
            return None
        node = tabs.names[winner]
        return pre.PreemptionPlan(
            node_name=node, victims=victims,
            num_pdb_violations=int(nviol_d[winner]),
            nominated_to_clear=pre.nominated_pods_to_clear(
                pod, node, self.nominated.pods_for_node(node)))

    def preempt_gang(self, members: List[Pod], min_member: int,
                     topology_key: str):
        """Whole-gang preemption: price `min_member` member placements
        against every ICI domain at once (kernels/preempt.py
        price_domains) and return a GangPreemptionPlan — the victims to
        evict plus a nomination per member spread across the winning
        domain's freed nodes, so the nominated-reservation overlay
        shields the whole slice until the gang lands. Pure computation;
        the shell performs the API writes. Returns None when no domain
        can ever hold the gang."""
        if not members or min_member < 1:
            return None
        from . import preemption as pre
        from .kernels import preempt as pk
        self.refresh()
        infos = self.snapshot.node_infos
        rep = members[0]
        t = self.mirror.t
        vec = (self.terms.tolerations_vector(rep)
               & self.terms.node_selector_vector(rep)
               & t.node_ok & t.valid)
        candidates = []
        for row in np.nonzero(vec)[0]:
            name = self.mirror.name_of.get(int(row))
            ni = infos.get(name) if name else None
            if ni is None or ni.node is None:
                continue
            dom = ni.node.metadata.labels.get(topology_key) \
                if topology_key else ""
            if dom is None:
                continue  # the label is the slice membership card
            candidates.append((name, ni, dom))
        pdbs = list(self.pdb_lister())
        tabs = pk.build_domain_tables(members, candidates, infos, pdbs,
                                      min_member,
                                      overshare=self._overshare_ranks())
        if tabs is None:
            return None
        a = tabs.arrays
        winner_d, chosen_d, nviol_d = pk.price_domains(
            a["base"], a["need"], a["dslots"], a["valid"], a["pdb"],
            a["top"], a["psum"], a["gcnt"], a["startr"], a["row_valid"])
        winner = int(winner_d)
        if winner < 0:
            return None
        chosen = np.asarray(chosen_d[winner])
        victims = tabs.expand(winner, chosen)
        # spread the members over the domain's post-eviction slots in
        # sorted node order — the nomination layout
        nominations: List[Tuple[Pod, str]] = []
        ordered = sorted(members, key=lambda p: p.metadata.key())
        it = iter(ordered)
        done = False
        for node, slots in tabs.node_slots(winner, chosen):
            for _ in range(slots):
                m = next(it, None)
                if m is None:
                    done = True
                    break
                nominations.append((m, node))
            if done:
                break
        if len(nominations) < min(min_member, len(ordered)):
            return None  # the slot estimate shrank under us; retry later
        return pre.GangPreemptionPlan(
            domain=tabs.domains[winner], victims=victims,
            nominations=nominations,
            num_pdb_violations=int(nviol_d[winner]))

    #: nodes examined per failure diagnosis; the reference pays full-cluster
    #: cost per ATTEMPT inside its parallelized hot loop, but here explain()
    #: is purely diagnostic (events), so a capped sample keeps a mass-
    #: unschedulable burst from burning minutes of host python — the
    #: aggregate message still reports the total node count
    EXPLAIN_NODE_CAP = 100

    def explain(self, pod: Pod, node_cap: Optional[int] = None) -> FitError:
        """Host-path per-node failure reasons for events/conditions.
        Diagnoses up to `node_cap` nodes (EXPLAIN_NODE_CAP default; None
        from callers means the default, 0 means unlimited)."""
        cap = self.EXPLAIN_NODE_CAP if node_cap is None else node_cap
        meta = preds.PredicateMetadata(pod, self.snapshot.node_infos)
        all_preds = self._fits_predicates(pod)
        failed: Dict[str, List[str]] = {}
        examined = 0
        total = len(self.snapshot.node_infos)
        for name, ni in self.snapshot.node_infos.items():
            if cap and examined >= cap:
                break
            examined += 1
            ok, reasons = preds.pod_fits_on_node(pod, meta, ni, all_preds)
            if not ok:
                failed[name] = reasons
        return FitError(pod=pod, failed_predicates=failed,
                        total_nodes=total,
                        not_examined=total - examined)
