"""Scheduler cache — authoritative in-memory cluster state with assumed pods.

Ref: pkg/scheduler/internal/cache/{cache.go,interface.go,node_tree.go}.

Pod state machine (interface.go:40-120):
    informer Add/Update/Delete  ->  add_pod / update_pod / remove_pod
    assume_pod  ->  (in-flight bind; counted against the node immediately)
    finish_binding  ->  starts the assumed-pod TTL
    confirmed by informer add  ->  assumed flag cleared
    TTL expiry without confirmation  ->  expired, removed (self-heal for lost
    bind confirmations)
    forget_pod  ->  bind failed, undo

Snapshots are O(delta): every NodeInfo mutation bumps a global monotonic
generation; `update_snapshot` copies only nodes whose generation exceeds the
snapshot's (ref: cache.go:210-246 UpdateNodeInfoSnapshot). The same dirty feed
drives the incremental tensor mirror (tensorize.py).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Set

from ..api.core import Node, Pod
from ..utils.clock import Clock, REAL_CLOCK
from .nodeinfo import NodeInfo

DEFAULT_ASSUMED_POD_TTL = 30.0  # ref: factory.go podInitialBackoff... 30s TTL


class Snapshot:
    """A frozen view of the cache the scheduling cycle works against
    (ref: NodeInfoSnapshot). node_infos maps name -> cloned NodeInfo."""

    def __init__(self):
        self.node_infos: Dict[str, NodeInfo] = {}
        self.generation = 0

    @property
    def node_names(self) -> List[str]:
        return list(self.node_infos)


class Cache:
    def __init__(self, clock: Clock = REAL_CLOCK, ttl: float = DEFAULT_ASSUMED_POD_TTL):
        self._clock = clock
        self._ttl = ttl
        self._lock = threading.RLock()
        self.mutation_seq = 0
        self._generation = itertools.count(1)
        self._nodes: Dict[str, NodeInfo] = {}
        # pod key -> (pod, node_name); membership in _assumed marks in-flight
        self._pod_states: Dict[str, Pod] = {}
        self._assumed: Set[str] = set()
        self._assumed_deadline: Dict[str, float] = {}
        self._node_tree = NodeTree()

    @property
    def lock(self):
        """The cache's RLock (reentrant). The pipelined drain takes it to
        make {assume_pod + its own-mutation counter bump} and
        {mutation_seq vs counter comparison} atomic steps — the chain
        validity protocol between the commit thread and the launch path
        (scheduler._tracked_assume / _chain_intact)."""
        return self._lock

    def node_names(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def pod_keys(self, include_assumed: bool = True) -> List[str]:
        """Cached pod keys (debugger/comparer introspection)."""
        with self._lock:
            if include_assumed:
                return list(self._pod_states)
            return [k for k in self._pod_states if k not in self._assumed]

    def pod_keys_snapshot(self):
        """(confirmed, assumed) under ONE lock acquisition — the comparer
        needs both from the same instant or a bind between two calls makes
        the race detector itself report a phantom divergence."""
        with self._lock:
            assumed = set(self._assumed)
            confirmed = {k for k in self._pod_states if k not in assumed}
            return confirmed, assumed

    def _bump(self, ni: NodeInfo) -> None:
        ni.generation = next(self._generation)
        # monotonic mutation counter: the pipelined drain chains device usage
        # only while every mutation since its last launch came from its own
        # assume_pod calls (scheduler.drain_pipelined's chain_seq check)
        self.mutation_seq += 1

    def _node_info(self, name: str) -> NodeInfo:
        ni = self._nodes.get(name)
        if ni is None:
            ni = NodeInfo()
            self._nodes[name] = ni
        return ni

    # ------------------------------------------------------------- pods

    def assume_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.metadata.key()
            if key in self._pod_states:
                raise ValueError(f"pod {key} is already in the cache")
            ni = self._node_info(pod.spec.node_name)
            ni.add_pod(pod)
            self._bump(ni)
            self._pod_states[key] = pod
            self._assumed.add(key)

    def assigned_node(self, key: str) -> Optional[str]:
        """Node the cache currently holds this pod on (None if absent) —
        the bind path uses it to tell its own racing confirm event apart
        from a genuine duplicate."""
        with self._lock:
            pod = self._pod_states.get(key)
            return pod.spec.node_name if pod is not None else None

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            key = pod.metadata.key()
            if key in self._assumed:
                self._assumed_deadline[key] = self._clock.now() + self._ttl

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.metadata.key()
            if key not in self._assumed:
                raise ValueError(f"pod {key} is not assumed")
            self._remove_pod_state(key)

    def forget_pods(self, pods) -> int:
        """Roll back a set of assumed reservations in ONE lock acquisition
        — the gang permit-timeout path drops a whole gang's reservations
        atomically, so no scheduling cycle can observe a half-rolled-back
        gang. Pods no longer assumed (confirmed or already forgotten) are
        skipped; returns the number actually rolled back."""
        with self._lock:
            n = 0
            for pod in pods:
                key = pod.metadata.key()
                if key in self._assumed:
                    self._remove_pod_state(key)
                    n += 1
            return n

    def _remove_pod_state(self, key: str) -> None:
        pod = self._pod_states.pop(key)
        self._assumed.discard(key)
        self._assumed_deadline.pop(key, None)
        ni = self._nodes.get(pod.spec.node_name)
        if ni is not None:
            ni.remove_pod(pod)
            self._bump(ni)
            if ni.node is None and not ni.pods:
                del self._nodes[pod.spec.node_name]

    def add_pod(self, pod: Pod) -> None:
        """Informer confirmed an assigned pod (ref: cache.go AddPod)."""
        with self._lock:
            key = pod.metadata.key()
            if key in self._assumed:
                cached = self._pod_states[key]
                if cached.spec.node_name != pod.spec.node_name:
                    # assumed to the wrong node; fix up
                    self._remove_pod_state(key)
                    ni = self._node_info(pod.spec.node_name)
                    ni.add_pod(pod)
                    self._bump(ni)
                    self._pod_states[key] = pod
                else:
                    self._assumed.discard(key)
                    self._assumed_deadline.pop(key, None)
                    self._pod_states[key] = pod
                return
            if key in self._pod_states:
                return  # duplicate add
            ni = self._node_info(pod.spec.node_name)
            ni.add_pod(pod)
            self._bump(ni)
            self._pod_states[key] = pod

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            key = old.metadata.key()
            if key in self._assumed:
                return  # informer lag; the Add confirmation handles it
            if key in self._pod_states:
                self._remove_pod_state(key)
            ni = self._node_info(new.spec.node_name)
            ni.add_pod(new)
            self._bump(ni)
            self._pod_states[key] = new

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.metadata.key()
            if key in self._pod_states:
                self._remove_pod_state(key)

    def is_assumed_pod(self, pod: Pod) -> bool:
        with self._lock:
            return pod.metadata.key() in self._assumed

    def assumed_pods(self) -> List[Pod]:
        """The in-flight (assumed, unconfirmed) pods — the set the chaos
        invariant checker sweeps for reservations pinned to dead nodes."""
        with self._lock:
            return [self._pod_states[k] for k in self._assumed]

    def get_pod(self, pod: Pod) -> Optional[Pod]:
        with self._lock:
            return self._pod_states.get(pod.metadata.key())

    # ------------------------------------------------------------- nodes

    def add_node(self, node: Node) -> None:
        with self._lock:
            ni = self._node_info(node.metadata.name)
            ni.set_node(node)
            self._bump(ni)
            self._node_tree.add(node)

    def update_node(self, old: Node, new: Node) -> None:
        with self._lock:
            ni = self._node_info(new.metadata.name)
            ni.set_node(new)
            self._bump(ni)
            self._node_tree.update(old, new)

    def remove_node(self, node: Node) -> None:
        with self._lock:
            name = node.metadata.name
            ni = self._nodes.get(name)
            if ni is None:
                return
            ni.node = None
            self._bump(ni)
            if not ni.pods:
                del self._nodes[name]
            self._node_tree.remove(node)

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for ni in self._nodes.values() if ni.node is not None)

    # ---------------------------------------------------------- snapshot

    def cleanup_expired_assumed_pods(self) -> int:
        """Ref: cache.go cleanupAssumedPods (run periodically). Returns the
        number of expired pods removed."""
        with self._lock:
            now = self._clock.now()
            expired = [k for k, dl in self._assumed_deadline.items() if dl <= now]
            for key in expired:
                self._remove_pod_state(key)
            return len(expired)

    def update_snapshot(self, snapshot: Snapshot) -> List[str]:
        """Copy nodes whose generation > snapshot.generation into the
        snapshot; remove deleted nodes. Returns the dirty node names —
        the delta feed for the tensor mirror (ref: cache.go:210-246)."""
        with self._lock:
            dirty: List[str] = []
            max_gen = snapshot.generation
            for name, ni in self._nodes.items():
                if ni.generation > snapshot.generation:
                    if ni.node is not None:
                        snapshot.node_infos[name] = ni.clone()
                        dirty.append(name)
                    max_gen = max(max_gen, ni.generation)
            if len(snapshot.node_infos) > self.node_count():
                live = {n for n, ni in self._nodes.items() if ni.node is not None}
                for name in list(snapshot.node_infos):
                    if name not in live:
                        del snapshot.node_infos[name]
                        dirty.append(name)
            snapshot.generation = max_gen
            return dirty

    def dump(self) -> Dict[str, NodeInfo]:
        """Debug snapshot (ref: internal/cache/debugger SIGUSR2 dump)."""
        with self._lock:
            return {n: ni.clone() for n, ni in self._nodes.items()}


class NodeTree:
    """Zone -> node-name lists with round-robin iteration, so node enumeration
    interleaves zones (ref: node_tree.go:31-46). ordered_names() is the
    zone-strided order intended for the tensor mirror's row layout (so node
    shards stay zone-balanced across TPU cores); the mirror currently assigns
    rows from a free list and does NOT consume this yet."""

    def __init__(self):
        self._zones: Dict[str, List[str]] = {}
        self._zone_of: Dict[str, str] = {}

    @staticmethod
    def _zone_key(node: Node) -> str:
        from ..api import wellknown
        labels = node.metadata.labels
        region = labels.get(wellknown.LABEL_REGION, "")
        zone = labels.get(wellknown.LABEL_ZONE, "")
        return f"{region}:\x00:{zone}"

    def add(self, node: Node) -> None:
        name = node.metadata.name
        if name in self._zone_of:
            self.remove(node)
        zone = self._zone_key(node)
        self._zones.setdefault(zone, []).append(name)
        self._zone_of[name] = zone

    def remove(self, node: Node) -> None:
        name = node.metadata.name
        zone = self._zone_of.pop(name, None)
        if zone is None:
            return
        lst = self._zones.get(zone, [])
        if name in lst:
            lst.remove(name)
        if not lst:
            self._zones.pop(zone, None)

    def update(self, old: Node, new: Node) -> None:
        if self._zone_key(old) != self._zone_key(new) or \
                old.metadata.name not in self._zone_of:
            self.remove(old)
            self.add(new)

    def ordered_names(self) -> List[str]:
        """Round-robin across zones (zone-strided order)."""
        lists = [list(v) for v in self._zones.values()]
        out: List[str] = []
        i = 0
        while any(i < len(l) for l in lists):
            for l in lists:
                if i < len(l):
                    out.append(l[i])
            i += 1
        return out

    def num_nodes(self) -> int:
        return len(self._zone_of)
