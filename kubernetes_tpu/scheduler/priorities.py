"""Score priorities — python semantic reference.

Ref: pkg/scheduler/algorithm/priorities/ (~1,700 LoC). The default provider
registers 8 (algorithmprovider/defaults/defaults.go:126-137), each weight 1
except NodePreferAvoidPods (weight 10000). Scores are 0-10 per (priority,
node) in Map/Reduce form (priorities/types.go), then weight-summed
(generic_scheduler.go:767-772).

The TPU path computes the same arithmetic as a pods x nodes f32 matrix
(scorer.py + kernels/batch.py); these functions are the parity oracle.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from ..api import helpers, labels as labelsmod, wellknown
from ..api.core import Pod
from .nodeinfo import NodeInfo, pod_resource_nonzero
from .predicates import _term_matches_pod

MAX_PRIORITY = 10  # schedulerapi.MaxPriority

# image locality thresholds (ref: image_locality.go:23-31)
MIN_IMG_SIZE = 23 * 1024 * 1024
MAX_IMG_SIZE = 1000 * 1024 * 1024

#: annotation consulted by NodePreferAvoidPods (ref: v1helper
#: GetAvoidPodsFromNodeAnnotations)
PREFER_AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"

# zone spreading weight (ref: selector_spreading.go zoneWeighting = 2.0/3.0)
ZONE_WEIGHTING = 2.0 / 3.0


class PriorityMetadata:
    """Per-pod precompute (ref: priorities/metadata.go:115 priorityMetadata):
    non-zero request, pod limits, affinity, spread selectors."""

    def __init__(self, pod: Pod, listers: Optional["SpreadListers"] = None):
        self.pod = pod
        self.non_zero_request = pod_resource_nonzero(pod)
        self.pod_selectors = listers.selectors_for_pod(pod) if listers else []
        self.pod_tolerations = [t for t in pod.spec.tolerations
                                if t.effect in ("", "PreferNoSchedule")]
        aff = pod.spec.affinity
        self.preferred_node_affinity = (
            aff.node_affinity.preferred_during_scheduling_ignored_during_execution
            if aff and aff.node_affinity else [])


class SpreadListers:
    """Selector sources for SelectorSpread: services, RCs, RSs, StatefulSets
    (ref: selector_spreading.go getSelectors)."""

    def __init__(self, services=None, rcs=None, rss=None, statefulsets=None):
        self.services = services or (lambda ns: [])
        self.rcs = rcs or (lambda ns: [])
        self.rss = rss or (lambda ns: [])
        self.statefulsets = statefulsets or (lambda ns: [])

    def selectors_for_pod(self, pod: Pod) -> List[Callable[[Dict[str, str]], bool]]:
        ns = pod.metadata.namespace
        out = []
        for svc in self.services(ns):
            sel = svc.spec.selector
            if sel and all(pod.metadata.labels.get(k) == v for k, v in sel.items()):
                out.append(lambda lbls, s=dict(sel): all(
                    lbls.get(k) == v for k, v in s.items()))
        for rc in self.rcs(ns):
            sel = rc.spec.selector
            if sel and all(pod.metadata.labels.get(k) == v for k, v in sel.items()):
                out.append(lambda lbls, s=dict(sel): all(
                    lbls.get(k) == v for k, v in s.items()))
        for rs in self.rss(ns):
            if rs.spec.selector and labelsmod.matches(rs.spec.selector, pod.metadata.labels):
                out.append(lambda lbls, s=rs.spec.selector: labelsmod.matches(s, lbls))
        for ss in self.statefulsets(ns):
            if ss.spec.selector and labelsmod.matches(ss.spec.selector, pod.metadata.labels):
                out.append(lambda lbls, s=ss.spec.selector: labelsmod.matches(s, lbls))
        return out


# ------------------------------------------------------------- map funcs

def least_requested_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: least_requested.go:53 — ((cap-req)*10/cap averaged over cpu+mem),
    integer math."""
    cpu_req, mem_req = meta.non_zero_request
    cpu_score = _unused_score(ni.allocatable.milli_cpu,
                              ni.non_zero_requested.milli_cpu + cpu_req)
    mem_score = _unused_score(ni.allocatable.memory,
                              ni.non_zero_requested.memory + mem_req)
    return (cpu_score + mem_score) // 2


def _unused_score(capacity: int, requested: int) -> int:
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def balanced_allocation_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: balanced_resource_allocation.go:77 — 10 - |cpuFrac - memFrac|*10
    (volume fraction variant gated off in the default build)."""
    cpu_req, mem_req = meta.non_zero_request
    cpu_frac = _fraction(ni.non_zero_requested.milli_cpu + cpu_req,
                         ni.allocatable.milli_cpu)
    mem_frac = _fraction(ni.non_zero_requested.memory + mem_req,
                         ni.allocatable.memory)
    if cpu_frac >= 1 or mem_frac >= 1:
        return 0
    diff = abs(cpu_frac - mem_frac)
    return int((1 - diff) * float(MAX_PRIORITY))


def _fraction(req: int, cap: int) -> float:
    return float(req) / float(cap) if cap > 0 else 1.0


def node_affinity_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: node_affinity.go CalculateNodeAffinityPriorityMap — sum of weights
    of matching preferred terms (normalized by reduce)."""
    score = 0
    for term in meta.preferred_node_affinity:
        if term.weight == 0:
            continue
        if helpers.match_node_selector_terms([term.preference], ni.node):
            score += term.weight
    return score


def taint_toleration_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: taint_toleration.go — count of intolerable PreferNoSchedule taints
    (reduce inverts + normalizes)."""
    count = 0
    for taint in ni.taints:
        if taint.effect != "PreferNoSchedule":
            continue
        if not any(t.tolerates(taint) for t in meta.pod_tolerations):
            count += 1
    return count


def image_locality_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: image_locality.go:109 — scaled sum of present image sizes."""
    total = 0
    for c in pod.spec.containers:
        total += ni.image_sizes.get(c.image, 0)
    return _scale_image_score(total)


def _scale_image_score(size: int) -> int:
    if size < MIN_IMG_SIZE:
        return 0
    if size > MAX_IMG_SIZE:
        return MAX_PRIORITY
    return int(MAX_PRIORITY * (size - MIN_IMG_SIZE) / (MAX_IMG_SIZE - MIN_IMG_SIZE))


def node_prefer_avoid_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: node_prefer_avoid_pods.go — 0 if the node's preferAvoidPods
    annotation targets this pod's controller (RC/RS), else 10."""
    from ..api.meta import controller_ref
    ref = controller_ref(pod.metadata)
    if ref is None or ref.kind not in ("ReplicationController", "ReplicaSet"):
        return MAX_PRIORITY
    if ni.node is None:
        return MAX_PRIORITY
    ann = ni.node.metadata.annotations.get(PREFER_AVOID_PODS_ANNOTATION)
    if not ann:
        return MAX_PRIORITY
    try:
        avoid = json.loads(ann)
    except ValueError:
        return MAX_PRIORITY
    for entry in avoid.get("preferAvoidPods", []):
        sig = entry.get("podSignature", {}).get("podController", {})
        if sig.get("kind") == ref.kind and sig.get("name") == ref.name:
            return 0
    return MAX_PRIORITY


def selector_spread_map(pod: Pod, meta: PriorityMetadata, ni: NodeInfo) -> int:
    """Ref: selector_spreading.go CalculateSpreadPriorityMap — count existing
    pods on the node matched by the pod's controller/service selectors."""
    if not meta.pod_selectors:
        return 0
    count = 0
    for p in ni.pods:
        if p.metadata.namespace != pod.metadata.namespace:
            continue
        if p.metadata.deletion_timestamp is not None:
            continue
        if all(sel(p.metadata.labels) for sel in meta.pod_selectors):
            count += 1
    return count


def selector_spread_reduce(pod: Pod, meta: PriorityMetadata,
                           node_infos: Dict[str, NodeInfo],
                           counts: Dict[str, int]) -> Dict[str, int]:
    """Ref: CalculateSpreadPriorityReduce — invert counts to 0-10, then blend
    zone-level counts with weight 2/3 when zones are present."""
    max_count = max(counts.values()) if counts else 0
    zone_counts: Dict[str, int] = {}
    have_zones = False
    for name, ni in node_infos.items():
        if ni.node is None:
            continue
        zone = ni.node.metadata.labels.get(wellknown.LABEL_ZONE, "")
        if zone:
            have_zones = True
            zone_counts[zone] = zone_counts.get(zone, 0) + counts.get(name, 0)
    max_zone = max(zone_counts.values()) if zone_counts else 0
    out: Dict[str, int] = {}
    for name, ni in node_infos.items():
        score = float(MAX_PRIORITY)
        if max_count > 0:
            score = MAX_PRIORITY * (max_count - counts.get(name, 0)) / max_count
        if have_zones and ni.node is not None:
            zone = ni.node.metadata.labels.get(wellknown.LABEL_ZONE, "")
            # zone-less nodes keep the default MaxPriority zone score
            # (selector_spreading.go: zoneScore only recomputed with a zone id)
            zone_score = float(MAX_PRIORITY)
            if zone and max_zone > 0:
                zone_score = MAX_PRIORITY * (max_zone - zone_counts.get(zone, 0)) / max_zone
            score = score * (1 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zone_score
        out[name] = int(score)
    return out


def interpod_affinity_scores(pod: Pod, hard_pod_affinity_weight: int,
                             node_infos: Dict[str, NodeInfo],
                             score_nodes: Optional[Dict[str, NodeInfo]] = None
                             ) -> Dict[str, float]:
    """Ref: interpod_affinity.go CalculateInterPodAffinityPriority — for every
    existing pod, accumulate onto all nodes in the same topology:
      + weight of the incoming pod's preferred-affinity terms it matches
      - weight of the incoming pod's preferred-anti-affinity terms it matches
      + weight of the existing pod's preferred-affinity terms the incoming
        pod matches (symmetry), and - for its preferred anti-affinity
      + hard_pod_affinity_weight for existing pods whose REQUIRED affinity
        terms the incoming pod matches (symmetric hard-affinity credit)
    """
    aff = pod.spec.affinity
    pref_aff = (aff.pod_affinity.preferred_during_scheduling_ignored_during_execution
                if aff and aff.pod_affinity else [])
    pref_anti = (aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution
                 if aff and aff.pod_anti_affinity else [])
    # topology pair -> accumulated weight
    pair_weights: Dict[Tuple[str, str], float] = {}

    def credit(term_owner: Pod, term, weight: float, node_labels: Dict[str, str]):
        tk = term.topology_key
        if weight == 0 or tk not in node_labels:
            return
        pair = (tk, node_labels[tk])
        pair_weights[pair] = pair_weights.get(pair, 0.0) + weight

    for ni in node_infos.values():
        if ni.node is None:
            continue
        node_labels = ni.node.metadata.labels
        for existing in ni.pods:
            for wt in pref_aff:
                if _term_matches_pod(wt.pod_affinity_term, pod, existing):
                    credit(pod, wt.pod_affinity_term, float(wt.weight), node_labels)
            for wt in pref_anti:
                if _term_matches_pod(wt.pod_affinity_term, pod, existing):
                    credit(pod, wt.pod_affinity_term, -float(wt.weight), node_labels)
            ea = existing.spec.affinity
            if ea and ea.pod_affinity:
                for term in ea.pod_affinity.required_during_scheduling_ignored_during_execution:
                    if hard_pod_affinity_weight > 0 and \
                            _term_matches_pod(term, existing, pod):
                        credit(existing, term, float(hard_pod_affinity_weight), node_labels)
                for wt in ea.pod_affinity.preferred_during_scheduling_ignored_during_execution:
                    if _term_matches_pod(wt.pod_affinity_term, existing, pod):
                        credit(existing, wt.pod_affinity_term, float(wt.weight), node_labels)
            if ea and ea.pod_anti_affinity:
                for wt in ea.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution:
                    if _term_matches_pod(wt.pod_affinity_term, existing, pod):
                        credit(existing, wt.pod_affinity_term, -float(wt.weight), node_labels)

    raw: Dict[str, float] = {}
    for name, ni in (score_nodes if score_nodes is not None else node_infos).items():
        if ni.node is None:
            continue
        total = 0.0
        for (tk, tv), w in pair_weights.items():
            if ni.node.metadata.labels.get(tk) == tv:
                total += w
        raw[name] = total
    return raw


def normalize_reduce(scores: Dict[str, float], reverse: bool = False
                     ) -> Dict[str, int]:
    """Ref: priorities/reduce.go:63 NormalizeReduce(MaxPriority, reverse):
    score = MaxPriority * score / max; reversed: MaxPriority - that.
    max == 0 -> all 0 (all MaxPriority when reversed)."""
    if not scores:
        return {}
    max_v = max(scores.values())
    if max_v == 0:
        fill = MAX_PRIORITY if reverse else 0
        return {n: fill for n in scores}
    out = {}
    for name, v in scores.items():
        norm = int(MAX_PRIORITY * v / max_v)
        if reverse:
            norm = MAX_PRIORITY - norm
        out[name] = norm
    return out


def minmax_normalize(scores: Dict[str, float]) -> Dict[str, int]:
    """InterPodAffinity's in-place normalization (interpod_affinity.go:
    MaxPriority * (count - min) / (max - min); all equal -> 0)."""
    if not scores:
        return {}
    max_v = max(scores.values())
    min_v = min(scores.values())
    if max_v - min_v <= 0:
        return {n: 0 for n in scores}
    return {n: int(MAX_PRIORITY * (v - min_v) / (max_v - min_v))
            for n, v in scores.items()}


# --------------------------------------------------------- whole-cycle API

#: (name, map_fn, weight); reduce behavior is priority-specific
DEFAULT_PRIORITY_WEIGHTS = {
    "SelectorSpreadPriority": 1,
    "InterPodAffinityPriority": 1,
    "LeastRequestedPriority": 1,
    "BalancedResourceAllocation": 1,
    "NodePreferAvoidPodsPriority": 10000,
    "NodeAffinityPriority": 1,
    "TaintTolerationPriority": 1,
    "ImageLocalityPriority": 1,
}

HARD_POD_AFFINITY_WEIGHT = 1  # DefaultHardPodAffinitySymmetricWeight


def prioritize_nodes(pod: Pod, meta: PriorityMetadata,
                     node_infos: Dict[str, NodeInfo],
                     weights: Optional[Dict[str, int]] = None,
                     all_node_infos: Optional[Dict[str, NodeInfo]] = None
                     ) -> Dict[str, int]:
    """Full Map/Reduce + weighted sum for one pod over a node set
    (ref: generic_scheduler.go:672-812 PrioritizeNodes — node_infos is the
    FILTERED set the reduces normalize over; all_node_infos supplies the
    whole cluster's pods for inter-pod topology pair accumulation). Parity
    oracle for the TPU score kernel."""
    w = weights if weights is not None else DEFAULT_PRIORITY_WEIGHTS
    live = {n: ni for n, ni in node_infos.items() if ni.node is not None}
    totals: Dict[str, float] = {n: 0.0 for n in live}

    def acc(per_node: Dict[str, int], weight: int):
        for n, s in per_node.items():
            totals[n] += s * weight

    if w.get("LeastRequestedPriority"):
        acc({n: least_requested_map(pod, meta, ni) for n, ni in live.items()},
            w["LeastRequestedPriority"])
    if w.get("BalancedResourceAllocation"):
        acc({n: balanced_allocation_map(pod, meta, ni) for n, ni in live.items()},
            w["BalancedResourceAllocation"])
    if w.get("NodePreferAvoidPodsPriority"):
        acc({n: node_prefer_avoid_map(pod, meta, ni) for n, ni in live.items()},
            w["NodePreferAvoidPodsPriority"])
    if w.get("ImageLocalityPriority"):
        acc({n: image_locality_map(pod, meta, ni) for n, ni in live.items()},
            w["ImageLocalityPriority"])
    if w.get("NodeAffinityPriority"):
        raw = {n: float(node_affinity_map(pod, meta, ni)) for n, ni in live.items()}
        acc(normalize_reduce(raw), w["NodeAffinityPriority"])
    if w.get("TaintTolerationPriority"):
        raw = {n: float(taint_toleration_map(pod, meta, ni)) for n, ni in live.items()}
        acc(normalize_reduce(raw, reverse=True), w["TaintTolerationPriority"])
    if w.get("SelectorSpreadPriority"):
        counts = {n: selector_spread_map(pod, meta, ni) for n, ni in live.items()}
        acc(selector_spread_reduce(pod, meta, live, counts),
            w["SelectorSpreadPriority"])
    if w.get("InterPodAffinityPriority"):
        raw = interpod_affinity_scores(
            pod, HARD_POD_AFFINITY_WEIGHT,
            all_node_infos if all_node_infos is not None else live,
            score_nodes=live)
        acc(minmax_normalize(raw), w["InterPodAffinityPriority"])
    return {n: int(v) for n, v in totals.items()}
