"""Scheduler configuration — Policy and component config.

Ref: pkg/scheduler/api (schedulerapi.Policy — predicates, priorities with
weights, extenders, hardPodAffinitySymmetricWeight) and
pkg/scheduler/apis/config (KubeSchedulerConfiguration: schedulerName,
algorithmSource, leader election, healthz/metrics binding). Both load from
JSON files or dicts; precedence flags > config file > defaults, applied by
the cmd entry (cmd/kube_scheduler.py).

Capability note (documented deviation): the batch kernel always evaluates
the FULL default predicate set — a Policy listing a predicate subset is
validated against the known names but does not disable the rest; the
result is a conservative superset of the requested filtering. Priority
weights take full effect everywhere: host-side static priorities through
ScoreCompiler, and the two device-resident resource priorities
(LeastRequested/BalancedAllocation) through the batch's resource_weights
vector. Extenders take full effect.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .extender import ExtenderConfig, HTTPExtender
from .predicates import DEFAULT_PREDICATES, ORDERING
from .priorities import DEFAULT_PRIORITY_WEIGHTS, HARD_POD_AFFINITY_WEIGHT

#: every predicate name a Policy may reference (registered + factory-made)
KNOWN_PREDICATES = set(ORDERING) | set(DEFAULT_PREDICATES) | {
    "GeneralPredicates", "CheckNodeUnschedulable", "NoVolumeZoneConflict",
    "CheckVolumeBinding", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "MaxCSIVolumeCountPred", "MatchInterPodAffinity"}

KNOWN_PRIORITIES = set(DEFAULT_PRIORITY_WEIGHTS)


@dataclass
class Policy:
    """Ref: schedulerapi.Policy (pkg/scheduler/api/types.go)."""
    predicates: Optional[List[str]] = None
    priorities: Optional[Dict[str, int]] = None   # name -> weight
    extenders: List[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = HARD_POD_AFFINITY_WEIGHT

    @staticmethod
    def from_dict(data: dict) -> "Policy":
        preds = None
        if "predicates" in data:
            preds = [p["name"] for p in data["predicates"]]
            unknown = [n for n in preds if n not in KNOWN_PREDICATES]
            if unknown:
                raise ValueError(f"unknown predicates in policy: {unknown}")
        prios = None
        if "priorities" in data:
            prios = {p["name"]: int(p.get("weight", 1))
                     for p in data["priorities"]}
            unknown = [n for n in prios if n not in KNOWN_PRIORITIES]
            if unknown:
                raise ValueError(f"unknown priorities in policy: {unknown}")
        extenders = []
        for e in data.get("extenders", []):
            extenders.append(ExtenderConfig(
                url_prefix=e["urlPrefix"],
                filter_verb=e.get("filterVerb", ""),
                prioritize_verb=e.get("prioritizeVerb", ""),
                bind_verb=e.get("bindVerb", ""),
                weight=int(e.get("weight", 1)),
                node_cache_capable=bool(e.get("nodeCacheCapable", False)),
                ignorable=bool(e.get("ignorable", False))))
        return Policy(
            predicates=preds, priorities=prios, extenders=extenders,
            hard_pod_affinity_symmetric_weight=int(
                data.get("hardPodAffinitySymmetricWeight",
                         HARD_POD_AFFINITY_WEIGHT)))

    @staticmethod
    def from_file(path: str) -> "Policy":
        with open(path) as f:
            return Policy.from_dict(json.load(f))

    def weights(self) -> Dict[str, int]:
        """Effective priority weights: the policy's set, or the defaults."""
        if self.priorities is None:
            return dict(DEFAULT_PRIORITY_WEIGHTS)
        w = {name: 0 for name in DEFAULT_PRIORITY_WEIGHTS}
        w.update(self.priorities)
        return w


@dataclass
class LeaderElectionConfig:
    leader_elect: bool = False
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0
    resource_namespace: str = "kube-system"
    resource_name: str = "kube-scheduler"


@dataclass
class KubeSchedulerConfiguration:
    """Ref: pkg/scheduler/apis/config KubeSchedulerConfiguration."""
    scheduler_name: str = "default-scheduler"
    policy: Optional[Policy] = None
    leader_election: LeaderElectionConfig = field(
        default_factory=LeaderElectionConfig)
    healthz_bind_port: int = 0           # 0 = disabled
    disable_preemption: bool = False
    batch_size: int = 1024               # batch extension (no ref analog)
    # accepted for compatibility; the batch kernel evaluates every node,
    # so sampling is unnecessary (generic_scheduler.go:434-453 exists to
    # cut serial per-pod cost the batch design does not pay)
    percentage_of_nodes_to_score: int = 50

    @staticmethod
    def from_dict(data: dict) -> "KubeSchedulerConfiguration":
        cfg = KubeSchedulerConfiguration()
        cfg.scheduler_name = data.get("schedulerName", cfg.scheduler_name)
        cfg.disable_preemption = data.get("disablePreemption",
                                          cfg.disable_preemption)
        cfg.batch_size = int(data.get("batchSize", cfg.batch_size))
        cfg.healthz_bind_port = int(data.get("healthzBindPort", 0))
        cfg.percentage_of_nodes_to_score = int(
            data.get("percentageOfNodesToScore",
                     cfg.percentage_of_nodes_to_score))
        src = data.get("algorithmSource", {})
        pol = src.get("policy")
        if pol:
            if "file" in pol:
                cfg.policy = Policy.from_file(pol["file"]["path"])
            elif "inline" in pol:
                cfg.policy = Policy.from_dict(pol["inline"])
        le = data.get("leaderElection", {})
        if le:
            cfg.leader_election = LeaderElectionConfig(
                leader_elect=bool(le.get("leaderElect", False)),
                lease_duration_seconds=float(le.get("leaseDuration", 15.0)),
                renew_deadline_seconds=float(le.get("renewDeadline", 10.0)),
                retry_period_seconds=float(le.get("retryPeriod", 2.0)),
                resource_namespace=le.get("resourceNamespace", "kube-system"),
                resource_name=le.get("resourceName", "kube-scheduler"))
        return cfg

    @staticmethod
    def from_file(path: str) -> "KubeSchedulerConfiguration":
        with open(path) as f:
            return KubeSchedulerConfiguration.from_dict(json.load(f))


def build_scheduler(client, cfg: KubeSchedulerConfiguration):
    """Configurator: config -> a wired Scheduler (ref: factory.go
    CreateFromConfig/CreateFromProvider)."""
    from .scheduler import Scheduler
    policy = cfg.policy or Policy()
    extenders = [HTTPExtender(e) for e in policy.extenders]
    sched = Scheduler(
        client, batch_size=cfg.batch_size,
        scheduler_name=cfg.scheduler_name,
        disable_preemption=cfg.disable_preemption,
        extenders=extenders)
    # rebuild the algorithm's scorer with policy weights
    if policy.priorities is not None or \
            policy.hard_pod_affinity_symmetric_weight != HARD_POD_AFFINITY_WEIGHT:
        sched.algorithm.scorer.set_weights(
            policy.weights(), policy.hard_pod_affinity_symmetric_weight)
    return sched
