"""Scheduler metrics — the reference's metric families over the batch path.

Ref: pkg/scheduler/metrics/metrics.go:30-180. Same families and labels
where the concept survives batching; the batch-specific additions are
labeled phases of the device pipeline (tensorize/kernel/fetch) that the
reference's per-pod timers have no analog for.
"""

from __future__ import annotations

from ..utils.metrics import Registry

SCHEDULING_LATENCY_BUCKETS = (0.0005, 0.001, 0.002, 0.004, 0.008, 0.016,
                              0.032, 0.064, 0.128, 0.256, 0.512, 1.024,
                              2.048, 4.096, 8.192)


class SchedulerMetrics:
    def __init__(self, registry: Registry = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        # ref: SchedulingLatency histogram labeled by operation
        # {predicate_evaluation, priority_evaluation, binding, ...}; the
        # batch analog is per-phase wall time per cycle
        self.scheduling_duration = r.histogram(
            "scheduler_scheduling_duration_seconds",
            "Scheduling phase latency per batch cycle, by operation",
            buckets=SCHEDULING_LATENCY_BUCKETS)
        # ref: E2eSchedulingLatency — queue pop to bind committed
        self.e2e_scheduling_duration = r.histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "End-to-end batch latency from pop to binds committed",
            buckets=SCHEDULING_LATENCY_BUCKETS)
        self.binding_duration = r.histogram(
            "scheduler_binding_duration_seconds",
            "Bind transaction latency per batch",
            buckets=SCHEDULING_LATENCY_BUCKETS)
        # pipelined drain: wall time the commit stage spent on the commit
        # thread — time the drain thread did NOT serialize on (it was
        # tensorizing/dispatching the next batch); the occupancy lens the
        # device_profile's pipelined section reports per-batch
        self.commit_overlap_duration = r.histogram(
            "scheduler_commit_overlap_duration_seconds",
            "Commit-stage wall time overlapped with the next batch's "
            "launch and device compute (pipelined drain)",
            buckets=SCHEDULING_LATENCY_BUCKETS)
        # ref: scheduleAttempts counter labeled result
        # {scheduled, unschedulable, error}
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Scheduling attempts by result")
        # ref: PreemptionAttempts / PreemptionVictims; family names use
        # the reference's POST-rename spelling (the originals predate
        # its metrics-naming linter — exactly the KTPU004 contract)
        self.preemption_attempts = r.counter(
            "scheduler_preemption_attempts_total",
            "Preemption attempts")
        self.preemption_victims = r.counter(
            "scheduler_preemption_victims_total",
            "Pods evicted by preemption")
        # gang members no longer skip preemption silently: each failed
        # attempt by a gang member routes to WHOLE-GANG preemption
        # (price minMember placements against one ICI domain) and is
        # counted here — the old skip path's disappearance is observable
        self.preemption_gang_routed = r.counter(
            "scheduler_preemption_gang_routed_total",
            "Unschedulable gang members routed to whole-gang preemption "
            "(previously skipped outright)")
        self.pod_scheduling_errors = r.counter(
            "scheduler_pod_scheduling_errors_total",
            "Pods that failed a scheduling cycle with an error")
        # ref: PendingPods gauges {active, backoff, unschedulable}
        self.pending_pods = r.gauge(
            "scheduler_pending_pods",
            "Pending pods by queue")
        self.batch_size = r.histogram(
            "scheduler_batch_size",
            "Pods decided per batch cycle",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                     4096))
        # in-scan (anti-)affinity fallbacks, by reason {term_cap, kmax,
        # soft_terms, soft_kmax, soft_gang}: batches the kernel tables
        # could not cover take the repair-overlay / sub-chunked path
        # instead — a capped code path must be visible, never silent
        self.topo_inscan_fallbacks = r.counter(
            "scheduler_topo_inscan_fallbacks_total",
            "Batches that fell back from the in-scan topology/soft-credit "
            "tables, by reason")
        # serving-mode adaptive drain: the batch cap the sizing policy
        # chose per cycle (grows with queue depth, shrinks under commit/
        # bind backpressure or a priority-lane express batch)
        self.adaptive_batch_cap = r.histogram(
            "scheduler_adaptive_batch_cap",
            "Adaptive drain batch cap chosen per cycle (serving mode)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                     4096, 8192, 16384))
        # express batches popped for the high-priority lane, and cycles
        # shrunk because the hub-side commit/bind stages were backed up
        self.lane_batches = r.counter(
            "scheduler_priority_lane_batches_total",
            "Drain cycles sized to the high-priority lane cohort's "
            "bucket (floored at min_batch, so a tiny lane pops with "
            "bulk pods behind it)")
        self.backpressure_shrinks = r.counter(
            "scheduler_backpressure_shrinks_total",
            "Drain cycles whose batch cap was shrunk by bind/commit "
            "backpressure")
        # unschedulable attribution: one inc per (failed attempt, distinct
        # reason) from the explain() diagnosis, plus the queue's park
        # causes (gang below minMember) — the "why is my pod pending"
        # family /debug/pending reads per-pod detail for
        self.unschedulable_reasons = r.counter(
            "scheduler_unschedulable_reasons_total",
            "Unschedulable scheduling attempts by failure reason "
            "(predicate message or queue park cause)")
        # no silent caps (the PR 5 contract, enforced by KTPU005): every
        # bounded search that truncated its candidate set is visible
        self.capped_scans = r.counter(
            "scheduler_capped_scans_total",
            "Scans truncated at a documented cap, by cap name")
        # ---- sharded drain (mesh execution substrate) ----
        # batches routed through the shard_map kernel (per-shard
        # filter+score, cross-shard argmax) vs the GSPMD/single paths
        self.sharded_batches = r.counter(
            "scheduler_sharded_batches_total",
            "Batches scheduled by the shard-mapped class scan")
        # wall time the fetch spent draining the cross-shard argmax
        # pipeline for a sharded batch (mesh synchronization cost)
        self.shard_sync_seconds = r.histogram(
            "scheduler_shard_sync_seconds",
            "Mesh-synchronization wait fetching a sharded batch's packed "
            "results",
            buckets=SCHEDULING_LATENCY_BUCKETS)
        # mirror rows added purely for shard divisibility (TensorMirror
        # pads the node capacity to a multiple of the mesh's shard count;
        # pad rows are valid=False and excluded from every decision) —
        # padding is visible, never a silent cap
        self.mirror_shard_pad_rows = r.gauge(
            "scheduler_mirror_shard_pad_rows",
            "Node-mirror rows added to make the capacity shard-divisible")
        # ---- speculative cohort assignment (kernels/speculative.py) ----
        # the speculation rate is a first-class observable: cohorts
        # attempted, cohorts that collided (and were serially repaired),
        # pods re-decided by the repair, and oracle-detected divergences
        # from the serial scan (contract: always zero — a nonzero count
        # is a kernel bug, attributed in BatchScheduler.spec_divergence_log)
        self.speculative_cohorts = r.counter(
            "scheduler_speculative_cohorts_total",
            "Speculative cohort assignment attempts")
        self.speculative_collisions = r.counter(
            "scheduler_speculative_collisions_total",
            "Speculative cohorts rejected by collision detection and "
            "replayed serially")
        self.speculative_repaired = r.counter(
            "scheduler_speculative_repaired_pods_total",
            "Pods from the first collision onward whose decisions came "
            "from the serial repair replay")
        self.speculative_divergences = r.counter(
            "scheduler_speculative_divergences_total",
            "Pods whose speculative decision differed from the serial "
            "oracle replay (expected zero; bit-identity contract)")

    def observe_queue(self, queue) -> None:
        """Sample the three sub-queue depths (PendingPods gauges)."""
        with queue._lock:
            active = len(queue._in_active)
            backoff = len(queue._in_backoff)
            unschedulable = len(queue._unschedulable)
        self.pending_pods.set(active, queue="active")
        self.pending_pods.set(backoff, queue="backoff")
        self.pending_pods.set(unschedulable, queue="unschedulable")
