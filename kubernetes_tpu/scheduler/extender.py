"""Scheduler extender — out-of-process Filter/Prioritize/Bind over HTTP.

Ref: pkg/scheduler/core/extender.go (HTTPExtender :42-53, Filter :258,
Prioritize :318, Bind :360, send :387) and the wire types in
pkg/scheduler/api (ExtenderArgs, ExtenderFilterResult, HostPriorityList,
ExtenderBindingArgs). Two halves:

  HTTPExtender        — the client: the scheduler shells out per pod
  ExtenderServer      — the sidecar: exposes THIS framework's predicate
                        oracle over the same protocol, so an unmodified
                        upstream scheduler can delegate Filter/Prioritize
                        (and Bind) to the TPU-backed implementation —
                        the designated M5 integration boundary.

Wire format (exactly the reference's JSON):
  POST {url_prefix}/{filter_verb}     ExtenderArgs{pod, nodes|nodenames}
     -> ExtenderFilterResult{nodes|nodenames, failedNodes, error}
  POST {url_prefix}/{prioritize_verb} ExtenderArgs
     -> [{host, score}, ...]          (HostPriorityList, 0-10 per node)
  POST {url_prefix}/{bind_verb}       ExtenderBindingArgs{podName,
                                      podNamespace, podUID, node}
     -> {error}
"""

from __future__ import annotations

import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest

from ..api import serde
from ..api.core import Node, Pod


class ExtenderConfig:
    """Ref: schedulerapi.ExtenderConfig (pkg/scheduler/api/types.go)."""

    def __init__(self, url_prefix: str, filter_verb: str = "",
                 prioritize_verb: str = "", bind_verb: str = "",
                 weight: int = 1, node_cache_capable: bool = False,
                 ignorable: bool = False):
        self.url_prefix = url_prefix.rstrip("/")
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.bind_verb = bind_verb
        self.weight = weight
        self.node_cache_capable = node_cache_capable
        self.ignorable = ignorable


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """The scheduler-side client (ref: HTTPExtender)."""

    def __init__(self, config: ExtenderConfig, timeout: float = 5.0):
        self.config = config
        self.timeout = timeout

    def _send(self, verb: str, payload: dict) -> dict:
        """Ref: HTTPExtender.send :387. Any transport OR malformed-body
        failure surfaces as ExtenderError so `ignorable` works."""
        url = f"{self.config.url_prefix}/{verb}"
        req = urlrequest.Request(
            url, data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except (urlerror.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"extender {url}: {e}") from e

    def _args(self, pod: Pod, nodes: List[Node],
              encoded_nodes: Optional[list] = None) -> dict:
        args: Dict[str, object] = {"pod": serde.encode(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = [n.metadata.name for n in nodes]
        else:
            # node encoding is batch-invariant: callers fanning one node
            # list across many pods pass it pre-encoded once
            args["nodes"] = {"items": encoded_nodes if encoded_nodes
                             is not None
                             else [serde.encode(n) for n in nodes]}
        return args

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def filter(self, pod: Pod, nodes: List[Node],
               encoded_nodes: Optional[list] = None
               ) -> Tuple[List[str], Dict[str, str]]:
        """Returns (feasible node names, failed {node: reason})
        (ref: Filter :258)."""
        if not self.config.filter_verb:
            return [n.metadata.name for n in nodes], {}
        result = self._send(self.config.filter_verb,
                            self._args(pod, nodes, encoded_nodes))
        try:
            if result.get("error"):
                raise ExtenderError(result["error"])
            if result.get("nodenames") is not None:
                names = [str(n) for n in result["nodenames"]]
            elif result.get("nodes") is not None:
                names = [item["metadata"]["name"]
                         for item in result["nodes"].get("items", [])]
            else:
                names = []
            return names, dict(result.get("failedNodes") or {})
        except (AttributeError, KeyError, TypeError) as e:
            raise ExtenderError(f"malformed filter result: {e}") from e

    def prioritize(self, pod: Pod, nodes: List[Node],
                   encoded_nodes: Optional[list] = None
                   ) -> Dict[str, float]:
        """Node name -> weighted score (ref: Prioritize :318 — the caller
        multiplies by the extender weight; done here)."""
        if not self.config.prioritize_verb:
            return {}
        result = self._send(self.config.prioritize_verb,
                            self._args(pod, nodes, encoded_nodes))
        try:
            return {hp["host"]: float(hp["score"]) * self.config.weight
                    for hp in result or []}
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            raise ExtenderError(f"malformed prioritize result: {e}") from e

    def bind(self, pod: Pod, node_name: str) -> None:
        """Ref: Bind :360."""
        if not self.config.bind_verb:
            raise ExtenderError("extender has no bind verb")
        result = self._send(self.config.bind_verb, {
            "podName": pod.metadata.name,
            "podNamespace": pod.metadata.namespace,
            "podUID": pod.metadata.uid,
            "node": node_name})
        if result and result.get("error"):
            raise ExtenderError(result["error"])

    def supports_bind(self) -> bool:
        return bool(self.config.bind_verb)


class ExtenderServer:
    """Sidecar serving THIS framework's scheduling oracle over the extender
    protocol: an unmodified upstream kube-scheduler configured with an
    ExtenderConfig pointing here delegates Filter/Prioritize (and Bind when
    a client is provided) to the TPU-backed implementation."""

    def __init__(self, client=None, host: str = "127.0.0.1", port: int = 0):
        self.client = client
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    verb = self.path.strip("/").split("/")[-1]
                    try:
                        if verb == "filter":
                            out = outer._filter(payload)
                        elif verb == "prioritize":
                            out = outer._prioritize(payload)
                        elif verb == "bind":
                            out = outer._bind(payload)
                        else:
                            self.send_error(404)
                            return
                    except ValueError as e:
                        # protocol-level rejection (e.g. nodenames-only args
                        # on a clientless sidecar): clean 400, no traceback
                        body = json.dumps({"error": str(e)}).encode()
                        self.send_response(400)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception:
                    traceback.print_exc()
                    self.send_error(500)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ExtenderServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="extender-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # --------------------------------------------------------------- verbs

    def _decode_args(self, payload: dict) -> Tuple[Pod, List[Node]]:
        pod = serde.decode(Pod, payload["pod"])
        nodes = [serde.decode(Node, item)
                 for item in (payload.get("nodes") or {}).get("items", [])]
        if not nodes and payload.get("nodenames") is not None:
            # node_cache_capable caller: names only — resolve from the hub
            # (needs a client); without one this sidecar can't evaluate
            if self.client is None:
                raise ValueError(
                    "nodenames-only args need a client-backed sidecar "
                    "(set nodeCacheCapable: false, or give it a client)")
            for nm in payload["nodenames"]:
                try:
                    nodes.append(self.client.nodes().get(nm))
                except Exception:
                    pass
        return pod, nodes

    def _filter(self, payload: dict) -> dict:
        """Evaluate the full default predicate set on the caller's own
        pod+nodes (stateless: nodes arrive in the args, the non-cache-
        capable mode)."""
        from . import predicates as preds
        from .nodeinfo import NodeInfo
        try:
            pod, nodes = self._decode_args(payload)
        except ValueError as e:
            return {"nodes": {"items": []}, "nodenames": [],
                    "failedNodes": {}, "error": str(e)}
        infos = {n.metadata.name: NodeInfo(n) for n in nodes}
        meta = preds.PredicateMetadata(pod, infos)
        feasible, failed = [], {}
        for name, ni in infos.items():
            ok, reasons = preds.pod_fits_on_node(pod, meta, ni)
            if ok:
                feasible.append(ni.node)
            else:
                failed[name] = "; ".join(reasons) or "unschedulable"
        return {"nodes": {"items": [serde.encode(n) for n in feasible]},
                "nodenames": [n.metadata.name for n in feasible],
                "failedNodes": failed, "error": ""}

    def _prioritize(self, payload: dict) -> list:
        """Default priority scores per node (host oracle Map/Reduce)."""
        from . import priorities as prios
        from .nodeinfo import NodeInfo
        pod, nodes = self._decode_args(payload)
        infos = {n.metadata.name: NodeInfo(n) for n in nodes}
        meta = prios.PriorityMetadata(pod)
        scores = prios.prioritize_nodes(pod, meta, infos)
        return [{"host": name, "score": score}
                for name, score in scores.items()]

    def _bind(self, payload: dict) -> dict:
        if self.client is None:
            return {"error": "binding not enabled on this sidecar"}
        from ..api.core import Binding, ObjectReference
        from ..api.meta import ObjectMeta
        try:
            self.client.pods(payload["podNamespace"]).bind(Binding(
                metadata=ObjectMeta(name=payload["podName"],
                                    namespace=payload["podNamespace"]),
                target=ObjectReference(kind="Node", name=payload["node"])))
        except Exception as e:
            return {"error": str(e)}
        return {"error": ""}
