"""Tensorization: cluster state -> dense device tensors.

This is the layer the reference does not have — it replaces the per-(pod,node)
interface calls of findNodesThatFit/PrioritizeNodes
(pkg/scheduler/core/generic_scheduler.go:518,725) with three artifacts:

  1. TensorMirror — a row-per-node dense mirror of the scheduler cache's
     NodeInfo snapshot (column schema from nodeinfo.Resource, ref:
     pkg/scheduler/nodeinfo/node_info.go:139-148). Updated incrementally from
     the cache's generation-ordered dirty list (ref: cache.go:210-246), so a
     steady-state cycle ships O(delta) rows to HBM, not O(nodes). Device
     state is split into `cfg` (bind-invariant: alloc, flags) and `usage`
     (bind-varying: used, counts) so a queue drain can chain usage on device
     across batches while cfg stays put.

  2. TermCompiler — label selectors, taints/tolerations, host ports and
     hostname constraints compiled into cached per-node boolean vectors.
     String matching never reaches the device: every unique term is evaluated
     once per node-epoch against the snapshot (pods in one Deployment share
     selectors, so the cache hit rate is ~1).

  3. PodBatchTensors — the pod-axis arrays for one scheduling batch:
     requests, non-zero requests, flags, and the DEDUPLICATED static
     feasibility mask: unique_masks [U, N] + mask_idx [P]. Pods sharing
     constraint terms share a row, so per-batch host->device traffic is
     O(P*R + U*N) instead of O(P*N) — critical when the TPU sits behind a
     high-latency tunnel.

Padding: node, pod, and unique-row axes are padded to bucketed sizes (powers
of two) so XLA compiles one kernel per bucket instead of one per cluster size.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import helpers, wellknown
from ..api.core import Pod
from .cache import Snapshot
from .nodeinfo import NodeInfo
from .predicates import _pod_qos, _pressure_taint

# fixed resource columns; extended/scalar resources take columns 3+
COL_CPU = 0      # milliCPU
COL_MEM = 1      # bytes
COL_EPH = 2      # bytes
N_FIXED_COLS = 3

CFG_KEYS = ("alloc", "max_pods", "node_ok", "mem_pressure", "valid")
USAGE_KEYS = ("used", "nonzero_used", "pod_count")


def _bucket(n: int, minimum: int = 128) -> int:
    """Next power-of-two capacity >= n (static shapes for XLA)."""
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _f32_floor(v) -> np.float32:
    """Largest float32 <= v. Applied to allocatable so the f32 tensor can
    only UNDER-state capacity: quantities beyond the 24-bit mantissa (memory
    > 16 GiB at byte granularity) round conservatively instead of allowing
    overcommit (the parity oracle in predicates.py stays exact int64).
    Residual: used-sums accumulate at most n_pods ulps of over-statement,
    also in the safe direction (requests are _f32_ceil'd)."""
    f = np.float32(v)
    if f > v:
        f = np.nextafter(f, np.float32(-np.inf))
    return f


def _f32_ceil(v) -> np.float32:
    """Smallest float32 >= v (pod requests round up — see _f32_floor)."""
    f = np.float32(v)
    if f < v:
        f = np.nextafter(f, np.float32(np.inf))
    return f


class ResourceVocab:
    """Interned scalar-resource names -> tensor columns."""

    def __init__(self, extra_capacity: int = 5):
        self._cols: Dict[str, int] = {}
        self.capacity = N_FIXED_COLS + extra_capacity

    def col(self, name: str) -> int:
        c = self._cols.get(name)
        if c is None:
            c = N_FIXED_COLS + len(self._cols)
            self._cols[name] = c
            if c >= self.capacity:
                self.capacity = _bucket(c + 1, minimum=8)
        return c

    @property
    def n_cols(self) -> int:
        return self.capacity


class NodeTensors:
    """Host-side numpy mirror of per-node state."""

    def __init__(self, capacity: int, n_cols: int):
        self.capacity = capacity
        self.n_cols = n_cols
        self.alloc = np.zeros((capacity, n_cols), np.float32)
        self.used = np.zeros((capacity, n_cols), np.float32)
        self.nonzero_used = np.zeros((capacity, 2), np.float32)  # cpu, mem
        self.pod_count = np.zeros((capacity,), np.float32)
        self.max_pods = np.zeros((capacity,), np.float32)
        self.node_ok = np.zeros((capacity,), bool)        # condition+schedulable
        self.mem_pressure = np.zeros((capacity,), bool)
        self.valid = np.zeros((capacity,), bool)

    def arrays(self) -> Dict[str, np.ndarray]:
        return {"alloc": self.alloc, "used": self.used,
                "nonzero_used": self.nonzero_used,
                "pod_count": self.pod_count, "max_pods": self.max_pods,
                "node_ok": self.node_ok, "mem_pressure": self.mem_pressure,
                "valid": self.valid}

    def cfg_arrays(self) -> Dict[str, np.ndarray]:
        return {k: getattr(self, k) for k in CFG_KEYS}

    def usage_arrays(self) -> Dict[str, np.ndarray]:
        return {k: getattr(self, k) for k in USAGE_KEYS}


class TensorMirror:
    """Name <-> row mapping plus incremental row updates from cache dirties."""

    def __init__(self, vocab: Optional[ResourceVocab] = None,
                 min_capacity: int = 128, mesh=None):
        from . import sharding
        #: jax.sharding.Mesh with a "nodes" axis, or None (single device).
        #: With a mesh, every tensor is placed by the name-keyed partition
        #: rules (sharding.spec_for) so the kernels' node axis rides ICI
        #: (the scaling-book recipe: annotate shardings, let the runtime
        #: insert the collectives); pod batches stay replicated.
        self.mesh = mesh
        #: shards on the node axis; the row capacity is always a multiple
        #: so per-shard slices are equal (shard_map requires it, and a
        #: ragged GSPMD pad would silently skew the argmax row space)
        self._shards = sharding.n_shards(mesh)
        #: rows the current capacity carries ONLY for shard divisibility
        #: (beyond the power-of-two bucket); surfaced as the
        #: scheduler_mirror_shard_pad_rows gauge — padding is visible,
        #: never a silent cap
        self.shard_pad_rows = 0
        self.vocab = vocab or ResourceVocab()
        self.t = NodeTensors(self._capacity_for(1, min_capacity),
                             self.vocab.n_cols)
        self.row_of: Dict[str, int] = {}
        self.name_of: Dict[int, str] = {}
        self._free: List[int] = list(range(self.t.capacity))
        # row-aligned NodeInfo refs for term compilation / host fallbacks
        self.infos: List[Optional[NodeInfo]] = [None] * self.t.capacity
        #: bumped on any node change; TermCompiler cache epoch
        self.epoch = 0
        self._dirty_rows: set = set()
        self._device_cfg: Optional[dict] = None
        self._device_usage: Optional[dict] = None
        #: bumped by invalidate_usage; pending batches launched before an
        #: invalidation must not adopt_usage their (phantom-carrying) output.
        #: _usage_lock makes the epoch check and the adopt/invalidate write
        #: ONE atomic step: the pipelined drain invalidates from the commit
        #: thread while the drain thread adopts, and a lost race would
        #: resurrect phantom usage that invalidation just dropped.
        self.usage_epoch = 0
        self._usage_lock = threading.Lock()

    def _capacity_for(self, need: int, minimum: int = 128) -> int:
        """Row capacity for `need` nodes: the power-of-two bucket, padded
        up to a multiple of the mesh's shard count. Pad rows (valid=False,
        excluded from every kernel decision) are counted in
        shard_pad_rows, not silently absorbed."""
        from .sharding import shard_divisible
        bucket = _bucket(need, minimum)
        cap = shard_divisible(bucket, self._shards)
        self.shard_pad_rows = cap - bucket
        return cap

    # ------------------------------------------------------------ updates

    def apply(self, snapshot: Snapshot, dirty_names: Sequence[str]) -> None:
        """Apply the cache's dirty node list (update_snapshot output)."""
        if not dirty_names:
            return
        self.epoch += 1
        need = len(snapshot.node_infos)
        if need > self.t.capacity:
            self._grow(self._capacity_for(need))
        for name in dirty_names:
            ni = snapshot.node_infos.get(name)
            if ni is None or ni.node is None:
                self._remove_row(name)
            else:
                self._write_row(name, ni)

    def apply_chained(self, snapshot: Snapshot, dirty_names: Sequence[str]) -> None:
        """Host-row updates whose device effect already rides in a chained
        usage handle (the dirt is the pipelined drain's own assumes of
        residual-free pods: usage columns only — no label/taint/port/cfg
        changes, so the term-cache epoch survives). Rows stay queued in
        _dirty_rows: the next non-chained device_cfg_usage scatter rewrites
        them with identical host-truth values (idempotent) or corrects any
        foreign mutation that slipped past the chain_seq guard."""
        for name in dirty_names:
            ni = snapshot.node_infos.get(name)
            if ni is None or ni.node is None:
                self._remove_row(name)
            else:
                self._write_row(name, ni)

    def device_ready(self) -> bool:
        """False after a capacity/column resize or invalidate_usage dropped
        device state (chaining callers must fall back to a full upload)."""
        return self._device_cfg is not None and self._device_usage is not None

    def device_cfg(self) -> dict:
        """The device cfg handle for a chained dispatch (device_ready() must
        be True; usage comes from the chain, not the mirror)."""
        assert self._device_cfg is not None
        return self._device_cfg

    def _grow(self, new_capacity: int) -> None:
        old = self.t
        # the vocab may have grown since the last write (PodBatchTensors
        # interns new extended resources), so copy column-aware
        t = NodeTensors(new_capacity, self.vocab.n_cols)
        n = old.capacity
        for k, arr in t.arrays().items():
            src = getattr(old, k)
            if arr.ndim == 2 and arr.shape[1] != src.shape[1]:
                arr[:n, :src.shape[1]] = src
            else:
                arr[:n] = src
        self.t = t
        self._free.extend(range(n, new_capacity))
        self.infos.extend([None] * (new_capacity - n))
        self._device_cfg = None  # shapes changed; full re-upload
        self._device_usage = None
        self._dirty_rows.clear()

    def ensure_cols(self) -> None:
        """Resize the column axis after the vocab grew (callers: _write_row,
        PodBatchTensors before it sizes its request arrays)."""
        if self.vocab.n_cols > self.t.n_cols:
            t = NodeTensors(self.t.capacity, self.vocab.n_cols)
            for k, arr in t.arrays().items():
                src = getattr(self.t, k)
                if arr.ndim == 2 and arr.shape[1] != src.shape[1]:
                    arr[:, :src.shape[1]] = src
                else:
                    arr[...] = src
            self.t = t
            self._device_cfg = None
            self._device_usage = None
            self._dirty_rows.clear()

    def _write_row(self, name: str, ni: NodeInfo) -> None:
        row = self.row_of.get(name)
        if row is None:
            row = self._free.pop()
            self.row_of[name] = row
            self.name_of[row] = name
        # resource columns
        for scalars in (ni.allocatable.scalar_resources, ni.requested.scalar_resources):
            for rname in scalars:
                self.vocab.col(rname)
        self.ensure_cols()
        t = self.t
        t.alloc[row, :] = 0.0
        t.alloc[row, COL_CPU] = _f32_floor(ni.allocatable.milli_cpu)
        t.alloc[row, COL_MEM] = _f32_floor(ni.allocatable.memory)
        t.alloc[row, COL_EPH] = _f32_floor(ni.allocatable.ephemeral_storage)
        for rname, v in ni.allocatable.scalar_resources.items():
            t.alloc[row, self.vocab.col(rname)] = _f32_floor(v)
        t.used[row, :] = 0.0
        t.used[row, COL_CPU] = _f32_ceil(ni.requested.milli_cpu)
        t.used[row, COL_MEM] = _f32_ceil(ni.requested.memory)
        t.used[row, COL_EPH] = _f32_ceil(ni.requested.ephemeral_storage)
        for rname, v in ni.requested.scalar_resources.items():
            t.used[row, self.vocab.col(rname)] = _f32_ceil(v)
        t.nonzero_used[row, 0] = ni.non_zero_requested.milli_cpu
        t.nonzero_used[row, 1] = ni.non_zero_requested.memory
        t.pod_count[row] = len(ni.pods)
        t.max_pods[row] = ni.allocatable.allowed_pod_number
        node = ni.node
        ok = node is not None and not node.spec.unschedulable \
            and not ni.disk_pressure and not ni.pid_pressure
        if ok:
            for cond in node.status.conditions:
                if cond.type == "Ready" and cond.status != "True":
                    ok = False
                elif cond.type == "NetworkUnavailable" and cond.status == "True":
                    ok = False
        t.node_ok[row] = ok
        t.mem_pressure[row] = ni.memory_pressure
        t.valid[row] = True
        self.infos[row] = ni
        self._dirty_rows.add(row)

    def _remove_row(self, name: str) -> None:
        row = self.row_of.pop(name, None)
        if row is None:
            return
        del self.name_of[row]
        self.infos[row] = None
        t = self.t
        t.valid[row] = False
        t.alloc[row, :] = 0.0
        t.used[row, :] = 0.0
        t.nonzero_used[row, :] = 0.0
        t.pod_count[row] = 0.0
        t.max_pods[row] = 0.0
        t.node_ok[row] = False
        t.mem_pressure[row] = False
        self._free.append(row)
        self._dirty_rows.add(row)

    # ------------------------------------------------------------- device

    def put_named(self, name: str, arr):
        """Host array -> device, placed by the name-keyed partition rules
        (sharding.spec_for) — plain transfer when no mesh is active."""
        from .sharding import put
        return put(self.mesh, name, arr)

    def put_nodes(self, arr):
        """Host array -> device, sharded over the mesh's node axis (or a
        plain transfer single-device). For tensors whose NAME carries the
        rule, prefer put_named."""
        import jax
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P("nodes") if np.ndim(arr) == 1 else P("nodes", None)
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def put_replicated(self, arr):
        import jax
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def device_cfg_usage(self) -> Tuple[dict, dict]:
        """The (node_cfg, usage) pytrees on device. Dirty rows ship as ONE
        packed scatter (kernels.apply_dirty); full upload only after a
        capacity/column resize."""
        import jax.numpy as jnp
        t = self.t
        if self._device_cfg is None or self._device_usage is None:
            # resize or invalidate_usage: both re-uploaded from host truth
            self._device_cfg = {k: self.put_named(k, v)
                                for k, v in t.cfg_arrays().items()}
            self._device_usage = {k: self.put_named(k, v)
                                  for k, v in t.usage_arrays().items()}
        elif self._dirty_rows:
            from .kernels.batch import apply_dirty
            idx = np.fromiter(self._dirty_rows, dtype=np.int32,
                              count=len(self._dirty_rows))
            D = _bucket(len(idx), minimum=8)
            # pad with an out-of-range row; apply_dirty drops it
            pad = np.full((D,), t.capacity, np.int32)
            pad[:len(idx)] = idx
            cfg_rows = {k: self.put_replicated(_padded_rows(v, idx, D))
                        for k, v in t.cfg_arrays().items()}
            usage_rows = {k: self.put_replicated(_padded_rows(v, idx, D))
                          for k, v in t.usage_arrays().items()}
            self._device_cfg, self._device_usage = apply_dirty(
                self._device_cfg, self._device_usage,
                self.put_replicated(pad), cfg_rows, usage_rows)
        self._dirty_rows.clear()
        return self._device_cfg, self._device_usage

    def adopt_usage(self, usage: dict, epoch: Optional[int] = None) -> bool:
        """Adopt the kernel's post-batch usage (device-side chaining). Safe
        whenever every assignment in the batch was committed via assume_pod:
        the cache bumps those nodes' generations, so the next dirty scatter
        rewrites the same rows with identical host-truth values (idempotent);
        rows the host disagrees on (forgotten binds, node churn) are repaired
        by that same scatter. An assignment that never reaches assume_pod
        leaves no dirty row — callers must invalidate_usage() instead.

        `epoch` is the usage_epoch the batch launched at: the adopt is
        REFUSED (returns False) when an invalidation landed in between —
        checked and applied under one lock, so a commit-thread invalidation
        can never lose the race to a concurrent adopt."""
        with self._usage_lock:
            if epoch is not None and epoch != self.usage_epoch:
                return False
            self._device_usage = usage
            return True

    def invalidate_usage(self) -> None:
        """Drop adopted device usage; the next device_cfg_usage() re-uploads
        from host truth. Called when an assumed bind was dropped without a
        cache forget (no dirty row would repair the adopted tensors).
        Bumps usage_epoch so an in-flight PendingBatch whose usage input
        predates the invalidation cannot re-adopt phantom state."""
        with self._usage_lock:
            self._device_usage = None
            self.usage_epoch += 1

    @property
    def n_rows(self) -> int:
        return len(self.row_of)


def _padded_rows(arr: np.ndarray, idx: np.ndarray, D: int) -> np.ndarray:
    out = np.zeros((D,) + arr.shape[1:], arr.dtype)
    out[:len(idx)] = arr[idx]
    return out


# --------------------------------------------------------------- terms

def _canon_tolerations(pod: Pod) -> Tuple:
    return tuple(sorted((t.key, t.operator, t.value, t.effect or "")
                        for t in pod.spec.tolerations))


def _canon_node_selector(pod: Pod) -> Tuple:
    sel = tuple(sorted(pod.spec.node_selector.items()))
    aff = pod.spec.affinity
    terms: Tuple = ()
    if aff and aff.node_affinity and \
            aff.node_affinity.required_during_scheduling_ignored_during_execution is not None:
        ns = aff.node_affinity.required_during_scheduling_ignored_during_execution
        terms = tuple(
            (tuple((r.key, r.operator, tuple(r.values)) for r in t.match_expressions),
             tuple((r.key, r.operator, tuple(r.values)) for r in t.match_fields))
            for t in ns.node_selector_terms)
    return (sel, terms)


def precompute_pod_features(pod: Pod) -> Tuple:
    """Host-side per-pod feature extraction, cached on the pod object.

    Everything here depends only on the pod spec — not on the mirror,
    batch, or cluster state — so the scheduler's event handlers call it
    from the INFORMER thread as pods enter the queue, taking this work off
    the drain thread's critical path (the wire path's drain competes for
    the GIL with watch decode; every microsecond moved off it is wall
    time). PodBatchTensors reuses the signature; pods arriving without one
    (direct queue adds in tests) compute it inline.

    Cached on __dict__ under "_tsig"; a clone made via shallow_bind_clone
    carries the cache but bound clones never re-enter tensorization (the
    signature's node_name component would be stale there).
    """
    sig = pod.__dict__.get("_tsig")
    if sig is not None:
        return sig
    from .nodeinfo import pod_resource, pod_resource_nonzero
    reqs = helpers.pod_requests(pod)
    # warm the per-spec memos consumed by assume/add_pod on the commit path
    pod_resource(pod)
    pod_resource_nonzero(pod)
    helpers.pod_host_ports(pod)
    helpers.pod_requests_nonzero(pod)
    ckey0 = (_canon_tolerations(pod), _canon_node_selector(pod),
             tuple(sorted(helpers.pod_host_ports(pod))),
             pod.spec.node_name or "")
    qos_be = _pod_qos(pod) == "BestEffort"
    blocked = qos_be and not helpers.tolerates_taints(
        pod.spec.tolerations,
        [_pressure_taint(wellknown.TAINT_NODE_MEMORY_PRESSURE)],
        effects=["NoSchedule"])
    sig = (reqs, tuple(sorted(reqs.items())), qos_be, blocked, ckey0)
    pod.__dict__["_tsig"] = sig
    return sig


class TermCompiler:
    """Compiles pod-side constraint terms into cached [capacity] bool vectors
    over the mirror's rows. Cache entries are invalidated by mirror epoch."""

    def __init__(self, mirror: TensorMirror):
        self.mirror = mirror
        self._cache: Dict[Tuple, np.ndarray] = {}
        self._cache_epoch = -1

    def _vector(self, key: Tuple, fn) -> np.ndarray:
        # entries from an older mirror epoch are all stale at once: clear
        # wholesale so the cache stays bounded by live terms per epoch
        if self._cache_epoch != self.mirror.epoch:
            self._cache.clear()
            self._cache_epoch = self.mirror.epoch
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        cap = self.mirror.t.capacity
        vec = np.zeros((cap,), bool)
        for row, ni in enumerate(self.mirror.infos):
            if ni is not None and ni.node is not None:
                vec[row] = fn(ni)
        self._cache[key] = vec
        return vec

    def tolerations_vector(self, pod: Pod) -> np.ndarray:
        """PodToleratesNodeTaints as a node vector."""
        tols = pod.spec.tolerations
        return self._vector(
            ("tol", _canon_tolerations(pod)),
            lambda ni: helpers.tolerates_taints(
                tols, ni.taints, effects=["NoSchedule", "NoExecute"]))

    def node_selector_vector(self, pod: Pod) -> np.ndarray:
        """PodMatchNodeSelector (nodeSelector + required node affinity)."""
        return self._vector(
            ("sel", _canon_node_selector(pod)),
            lambda ni: helpers.pod_matches_node_selector_and_affinity(pod, ni.node))

    def host_ports_vector(self, pod: Pod) -> Optional[np.ndarray]:
        """True where the pod's host ports are free (PodFitsHostPorts).
        None when the pod wants no host ports (no constraint)."""
        wanted = helpers.pod_host_ports(pod)
        if not wanted:
            return None

        def free(ni: NodeInfo) -> bool:
            for proto, ip, port in wanted:
                for uproto, uip, uport in ni.used_ports:
                    if proto == uproto and port == uport and (
                            ip == uip or ip == "0.0.0.0" or uip == "0.0.0.0"):
                        return False
            return True
        return self._vector(("ports", tuple(sorted(wanted))), free)

    def hostname_vector(self, pod: Pod) -> Optional[np.ndarray]:
        """PodFitsHost: spec.nodeName pins the pod to one row."""
        if not pod.spec.node_name:
            return None
        vec = np.zeros((self.mirror.t.capacity,), bool)
        row = self.mirror.row_of.get(pod.spec.node_name)
        if row is not None:
            vec[row] = True
        return vec


# --------------------------------------------------------------- pod batch

class PodBatchTensors:
    """Pod-axis arrays for one batch, padded to a pod bucket.

    The static feasibility mask is deduplicated: `unique_masks [U, N]` holds
    one row per distinct constraint-term set, `mask_idx [P]` points each pod
    at its row. Pods from one controller share every term, so U stays O(few)
    while P is thousands — the device upload shrinks accordingly. Static
    priority scores use the same scheme (`unique_scores [S, N]`, `score_idx
    [P]`, filled by core.BatchScheduler from ScoreCompiler output; default is
    a single all-zeros row meaning "only on-device resource priorities").
    """

    def __init__(self, pods: List[Pod], mirror: TensorMirror,
                 terms: TermCompiler, extra_mask: Optional[np.ndarray] = None,
                 min_bucket: int = 8, seq_base: int = 0,
                 extra_group: Optional[np.ndarray] = None):
        self.pods = pods
        P = _bucket(len(pods), min_bucket)
        vocab = mirror.vocab
        # intern every requested resource FIRST so the mirror's column axis
        # covers the batch (a dropped column would silently zero a request).
        # The per-pod signature (requests, QoS, constraint key, warmed
        # memos) is normally precomputed on the informer thread
        # (precompute_pod_features); computing it here is the fallback.
        sigs = []
        for pod in pods:
            sig = pod.__dict__.get("_tsig")
            if sig is None:
                sig = precompute_pod_features(pod)
            sigs.append(sig)
            for rname in sig[0]:
                if rname not in (wellknown.RESOURCE_CPU, wellknown.RESOURCE_MEMORY,
                                 wellknown.RESOURCE_EPHEMERAL_STORAGE,
                                 wellknown.RESOURCE_PODS):
                    vocab.col(rname)
        mirror.ensure_cols()
        R = mirror.t.n_cols
        N = mirror.t.capacity
        self.req = np.zeros((P, R), np.float32)
        self.nonzero_req = np.zeros((P, 2), np.float32)
        self.mem_pressure_blocked = np.zeros((P,), bool)
        self.active = np.zeros((P,), bool)
        # tie-break rotation, persistent across batches like the reference's
        # lastNodeIndex (generic_scheduler.go:286-296)
        self.seq = (seq_base + np.arange(P, dtype=np.int64)) \
            .astype(np.int32) & 0x7FFFFFFF
        self.mask_idx = np.zeros((P,), np.int32)
        # the pod's own nominated node's row (-1 if none): the kernel
        # subtracts the pod's own reservation there so a preemptor is not
        # blocked by the space reserved for itself. Filled by the caller
        # (core.schedule_launch) from the live NominatedPodMap — the SAME
        # source the reservation tensor is built from; pod.status can lag
        # the map (cleared nominations) and would desync the subtraction.
        self.nom_row = np.full((P,), -1, np.int32)
        self._mirror = mirror

        # Pods stamped from one controller template share requests, QoS,
        # tolerations, and constraint terms; dedupe the per-pod numeric work
        # by template signature and fill rows with one gather per array.
        uniq: Dict[Tuple, int] = {}
        rows: List[np.ndarray] = []
        tmpl: Dict[Tuple, int] = {}
        tmpl_req: List[np.ndarray] = []
        tmpl_nz: List[Tuple[float, float]] = []
        tmpl_blocked: List[bool] = []
        tmpl_mask: List[int] = []
        tmpl_idx = np.zeros((P,), np.int32)
        for i, pod in enumerate(pods):
            reqs, reqs_key, qos_be, blocked_sig, ckey0 = sigs[i]
            if extra_group is not None and extra_mask is not None:
                # the caller's residual group id names the extra row's
                # template: dedupe by id instead of hashing 8K of mask
                # bytes per pod (-1 = no extra row)
                g = int(extra_group[i])
                has_extra = g != -1  # >= 0: template row; -2: all-False
                ckey = ckey0 + (("eg", g) if has_extra else None,)
            else:
                has_extra = extra_mask is not None \
                    and not extra_mask[i].all()
                ckey = ckey0 + (extra_mask[i].tobytes()
                                if has_extra else None,)
            # the QoS class itself is a template key component (aggregate
            # request maps can't distinguish init-container-only
            # BestEffort pods)
            tkey = (reqs_key, qos_be, ckey)
            t_i = tmpl.get(tkey)
            if t_i is None:
                req_row = np.zeros((R,), np.float32)
                for rname, v in reqs.items():
                    if rname == wellknown.RESOURCE_CPU:
                        req_row[COL_CPU] = _f32_ceil(v)
                    elif rname == wellknown.RESOURCE_MEMORY:
                        req_row[COL_MEM] = _f32_ceil(v)
                    elif rname == wellknown.RESOURCE_EPHEMERAL_STORAGE:
                        req_row[COL_EPH] = _f32_ceil(v)
                    elif rname == wellknown.RESOURCE_PODS:
                        pass
                    else:
                        req_row[vocab.col(rname)] = _f32_ceil(v)
                nz = helpers.pod_requests_nonzero(pod)
                blocked = blocked_sig
                u = uniq.get(ckey)
                if u is None:
                    mask = terms.tolerations_vector(pod) & \
                        terms.node_selector_vector(pod)
                    pv = terms.host_ports_vector(pod)
                    if pv is not None:
                        mask = mask & pv
                    hv = terms.hostname_vector(pod)
                    if hv is not None:
                        mask = mask & hv
                    if has_extra:
                        mask = mask & extra_mask[i]
                    u = len(rows)
                    uniq[ckey] = u
                    rows.append(mask)
                t_i = len(tmpl_req)
                tmpl[tkey] = t_i
                tmpl_req.append(req_row)
                tmpl_nz.append((nz.get(wellknown.RESOURCE_CPU, 0),
                                nz.get(wellknown.RESOURCE_MEMORY, 0)))
                tmpl_blocked.append(blocked)
                tmpl_mask.append(u)
            tmpl_idx[i] = t_i
        n = len(pods)
        if tmpl_req:
            idx = tmpl_idx[:n]
            self.req[:n] = np.stack(tmpl_req)[idx]
            self.nonzero_req[:n] = np.asarray(tmpl_nz, np.float32)[idx]
            self.mem_pressure_blocked[:n] = \
                np.asarray(tmpl_blocked, bool)[idx]
            self.mask_idx[:n] = np.asarray(tmpl_mask, np.int32)[idx]
        self.active[:n] = True
        # template tables retained for the class-indexed incremental scan
        # (enable_class_scan): pods sharing a template share every
        # batch-varying row the scan would otherwise recompute per pod
        self.tmpl_idx = tmpl_idx                       # [P] (pads -> 0)
        self._tmpl_req = tmpl_req
        self._tmpl_nz = tmpl_nz
        self._tmpl_blocked = tmpl_blocked
        self._tmpl_mask = tmpl_mask
        self._class_tables: Optional[Dict[str, np.ndarray]] = None
        U = _bucket(len(rows), minimum=1)
        self.unique_masks = np.zeros((U, N), bool)
        if rows:
            self.unique_masks[:len(rows)] = np.stack(rows)
        self.n_unique_masks = len(rows)
        # score dedupe table; default single zero row (resource-only scoring)
        self.score_idx = np.zeros((P,), np.int32)
        self.unique_scores = np.zeros((1, N), np.float32)
        # [LeastRequested, BalancedAllocation] weights for the device scan
        # (Policy-configurable; defaults.go:126-137 defaults both to 1)
        self.resource_weights = np.ones((2,), np.float32)
        # in-scan SelectorSpread groups (core._assign_spread_groups): pods
        # sharing (namespace, selector set) share a group whose per-node
        # match counts update inside the kernel scan
        self.spread_gidx = np.full((P,), -1, np.int32)
        self.spread_base: Optional[np.ndarray] = None   # [G, N] f32
        self.spread_zone: Optional[np.ndarray] = None   # [N] int32 (0=no zone)
        self.spread_zinit: Optional[np.ndarray] = None  # [Z] f32 zeros
        self.spread_match: Optional[np.ndarray] = None  # [P, G] f32
        self.spread_weight = 0.0

        # in-scan required (anti-)affinity term tables
        # (core._assign_topology_terms)
        self.anti_dom: Optional[np.ndarray] = None      # [T, N] int32
        #: epoch-cached DEVICE copy of the padded anti_dom table (sharded
        #: by the name rules) — set under a mesh so repeat batches skip
        #: the [T, N] upload entirely
        self.anti_dom_dev = None
        self.anti_cnt0: Optional[np.ndarray] = None     # [T, D] f32 zeros
        self.anti_tids: Optional[np.ndarray] = None     # [P, K] int32 (-1 pad)
        self.aff_tids: Optional[np.ndarray] = None      # [P, K] int32
        self.match_tids: Optional[np.ndarray] = None    # [P, K] int32
        self.cmatch_tids: Optional[np.ndarray] = None   # [P, K] int32
        self.canti_tids: Optional[np.ndarray] = None    # [P, K] int32

        # in-scan preferred (anti-)affinity credit tables
        # (core._assign_soft_terms)
        self.soft_dom: Optional[np.ndarray] = None       # [Ts, N] int32
        self.soft_cnt0: Optional[np.ndarray] = None      # [Ts, Ds] f32 zeros
        self.soft_base: Optional[np.ndarray] = None      # [Sb, N] f32
        self.soft_base_idx: Optional[np.ndarray] = None  # [P] int32 (-1 off)
        self.soft_read_tids: Optional[np.ndarray] = None   # [P, Ks] int32
        self.soft_read_w: Optional[np.ndarray] = None      # [P, Ks] f32
        self.soft_write_tids: Optional[np.ndarray] = None  # [P, Ks] int32
        self.soft_write_w: Optional[np.ndarray] = None     # [P, Ks] f32
        self.soft_weight = 0.0

        # speculative cohort vectors (set_speculative — only when the
        # batch routes to kernels/speculative.py)
        self.spec_plain: Optional[np.ndarray] = None     # [P] bool
        self.cohort_id: Optional[np.ndarray] = None      # [P] int32

    def set_topology_terms(self, dom: np.ndarray, n_domains: int,
                           anti_tids: np.ndarray, aff_tids: np.ndarray,
                           match_tids: np.ndarray,
                           cmatch_tids: Optional[np.ndarray] = None,
                           canti_tids: Optional[np.ndarray] = None,
                           dom_dev=None) -> None:
        """Install in-scan term tables; T, D, and the per-pod K axis all
        bucketed to powers of two (padded term rows carry dom=-1
        everywhere: never conflict, never bump) so consecutive batches
        with drifting term fan-outs share one compiled kernel instead of
        recompiling per batch. The per-pod [K]-term lists keep the scan
        O(K*N) per step. `dom_dev` is an already-padded, already-sharded
        DEVICE copy of the same table (TopologyIndex.term_table_device's
        epoch cache); its T bucketing matches this method's."""
        T = _bucket(dom.shape[0], minimum=8)
        P = self.req.shape[0]
        dom_p = np.full((T, dom.shape[1]), -1, np.int32)
        dom_p[:dom.shape[0]] = dom
        self.anti_dom = dom_p
        assert dom_dev is None or tuple(dom_dev.shape) == dom_p.shape, \
            "device dom table bucketing diverged from the host table"
        self.anti_dom_dev = dom_dev
        self.anti_cnt0 = np.zeros((T, _bucket(max(n_domains, 1),
                                              minimum=64)), np.float32)
        K = _bucket(max(anti_tids.shape[1], aff_tids.shape[1],
                        match_tids.shape[1], 1), minimum=1)

        def pad(m):
            out = np.full((P, K), -1, np.int32)
            out[:m.shape[0], :m.shape[1]] = m
            return out
        self.anti_tids = pad(anti_tids)
        self.aff_tids = pad(aff_tids)
        self.match_tids = pad(match_tids)
        # direction-2 lists (winner carries / pod matches), present only
        # when some pure matcher in the batch needs them — their absence
        # drops the whole carry-counter table from the kernel trace
        self.cmatch_tids = pad(cmatch_tids) if cmatch_tids is not None \
            else None
        self.canti_tids = pad(canti_tids) if canti_tids is not None \
            else None

    def set_soft_terms(self, dom: np.ndarray, n_domains: int,
                       base: np.ndarray, base_idx: np.ndarray,
                       read_tids: np.ndarray, read_w: np.ndarray,
                       write_tids: np.ndarray, write_w: np.ndarray,
                       weight: float) -> None:
        """Install in-scan preferred inter-pod (anti-)affinity credit
        tables (core._assign_soft_terms): per-(term slot, domain) weight
        accumulators start at zero (pre-batch credits live in the per-class
        `base` raw rows); each pod reads its slot list at its nodes'
        domains (signed weights) and a winner writes its slot list at the
        chosen node's domain. Ts/Ds/Ks/Sb bucketed like the required-term
        tables."""
        Ts = _bucket(dom.shape[0], minimum=8)
        P = self.req.shape[0]
        dom_p = np.full((Ts, dom.shape[1]), -1, np.int32)
        dom_p[:dom.shape[0]] = dom
        self.soft_dom = dom_p
        self.soft_cnt0 = np.zeros((Ts, _bucket(max(n_domains, 1),
                                               minimum=64)), np.float32)
        Sb = _bucket(base.shape[0], minimum=1)
        base_p = np.zeros((Sb, base.shape[1]), np.float32)
        base_p[:base.shape[0]] = base
        self.soft_base = base_p
        self.soft_base_idx = np.full((P,), -1, np.int32)
        self.soft_base_idx[:len(base_idx)] = base_idx
        Ks = _bucket(max(read_tids.shape[1], write_tids.shape[1], 1),
                     minimum=1)

        def pad_i(m):
            out = np.full((P, Ks), -1, np.int32)
            out[:m.shape[0], :m.shape[1]] = m
            return out

        def pad_f(m):
            out = np.zeros((P, Ks), np.float32)
            out[:m.shape[0], :m.shape[1]] = m
            return out
        self.soft_read_tids = pad_i(read_tids)
        self.soft_read_w = pad_f(read_w)
        self.soft_write_tids = pad_i(write_tids)
        self.soft_write_w = pad_f(write_w)
        self.soft_weight = float(weight)

    def enable_class_scan(self) -> None:
        """Build the (template, score-row) class tables for the kernel's
        incremental class-indexed scan (kernels/batch.py
        _schedule_batch_classes). Called AFTER static scores are set —
        score_idx is part of the class key. Spread groups, soft credits,
        and nominated reservations ride the class scan as per-pod
        carried/overlaid state, so every non-gang batch builds these."""
        if not self._tmpl_req:
            return
        P = self.req.shape[0]
        S = max(1, self.unique_scores.shape[0])
        pair = self.tmpl_idx.astype(np.int64) * S \
            + self.score_idx.astype(np.int64)
        uniq, class_idx = np.unique(pair, return_inverse=True)
        C = _bucket(len(uniq), minimum=1)
        t_of = (uniq // S).astype(np.int64)
        s_of = (uniq % S).astype(np.int64)
        req = np.zeros((C, self.req.shape[1]), np.float32)
        nz = np.zeros((C, 2), np.float32)
        blocked = np.zeros((C,), bool)
        mask_idx = np.zeros((C,), np.int32)
        score_idx = np.zeros((C,), np.int32)
        req[:len(uniq)] = np.stack(self._tmpl_req)[t_of]
        nz[:len(uniq)] = np.asarray(self._tmpl_nz, np.float32)[t_of]
        blocked[:len(uniq)] = np.asarray(self._tmpl_blocked, bool)[t_of]
        mask_idx[:len(uniq)] = np.asarray(self._tmpl_mask, np.int32)[t_of]
        score_idx[:len(uniq)] = s_of
        self._class_tables = {
            "class_req": req, "class_nz": nz, "class_blocked": blocked,
            "class_mask_idx": mask_idx, "class_score_idx": score_idx,
            "class_idx": class_idx.astype(np.int32)[:P]}

    def set_speculative(self, width: int) -> None:
        """Mark pods eligible for speculative cohort assignment
        (kernels/speculative.py) and stamp the cohort-id vector. A pod
        is PLAIN — safe to speculate on — iff it READS no carry-
        dependent term: no required/waived (anti-)affinity term lists,
        no spread group membership, no soft credit read channel, no
        nominated self-exemption row. Carry WRITERS stay plain (the
        kernel applies their counter writes with the shared serial
        helpers); DRF ordering is host-side and never reaches the
        kernel. Pads are plain: inactive pods never write, so they are
        trivially serial-equivalent. Must run AFTER every term table and
        nom_row is installed — the flags are derived from them.

        `cohort_id[i]` is the contiguous cohort the pod speculates in
        (pod index // width, the kernel's chunking) or -1 where the pod
        is pinned serial — the divergence oracle's attribution key."""
        P = self.req.shape[0]
        plain = self.nom_row < 0
        if self.anti_dom is not None:
            plain = plain & (self.anti_tids < 0).all(axis=1)
            plain = plain & (self.aff_tids < 0).all(axis=1)
            if self.cmatch_tids is not None:
                plain = plain & (self.cmatch_tids < 0).all(axis=1)
        if self.spread_base is not None:
            plain = plain & (self.spread_gidx < 0)
        if self.soft_dom is not None:
            plain = plain & (self.soft_base_idx < 0)
        self.spec_plain = plain
        cid = np.arange(P, dtype=np.int32) // np.int32(max(width, 1))
        self.cohort_id = np.where(plain, cid, np.int32(-1))

    def set_spread(self, base: np.ndarray, zone_of: np.ndarray,
                   n_zones: int, weight: float,
                   match: Optional[np.ndarray] = None) -> None:
        """Install spread group tables (G and Z bucketed to bound XLA
        recompiles across batches). `match` [P, G0] marks which groups'
        selectors match each pod — a winner bumps EVERY matching group's
        running count (overlapping selector groups see each other's
        in-batch placements, like the serial re-count would)."""
        G = _bucket(base.shape[0], minimum=1)
        P = self.req.shape[0]
        padded = np.zeros((G, base.shape[1]), np.float32)
        padded[:base.shape[0]] = base
        self.spread_base = padded
        self.spread_zone = zone_of.astype(np.int32)
        self.spread_zinit = np.zeros((_bucket(n_zones, minimum=8),),
                                     np.float32)
        self.spread_match = np.zeros((P, G), np.float32)
        if match is not None:
            self.spread_match[:match.shape[0], :match.shape[1]] = match
        else:
            for i, g in enumerate(self.spread_gidx):
                if g >= 0:
                    self.spread_match[i, g] = 1.0
        self.spread_weight = float(weight)

    def set_static_scores(self, score_idx: np.ndarray,
                          unique_scores: np.ndarray) -> None:
        """Install ScoreCompiler output (S-bucketed unique score rows)."""
        S = _bucket(unique_scores.shape[0], minimum=1)
        padded = np.zeros((S, self.unique_scores.shape[1]), np.float32)
        padded[:unique_scores.shape[0]] = unique_scores
        self.unique_scores = padded
        self.score_idx[:len(score_idx)] = score_idx

    def _base_ok(self) -> np.ndarray:
        t = self._mirror.t
        return t.node_ok & t.valid & (t.pod_count + 1.0 <= t.max_pods)

    def fits_row(self, i: int) -> np.ndarray:
        """One pod's batch-start feasibility [N] on host numpy."""
        t = self._mirror.t
        fits = self.unique_masks[self.mask_idx[i]] & self._base_ok()
        if self.mem_pressure_blocked[i]:
            fits = fits & ~t.mem_pressure
        free = t.alloc - t.used
        fits = fits & (self.req[i][None, :] <= free).all(axis=1)
        return fits

    def device(self, mesh=None) -> dict:
        import jax.numpy as jnp
        from . import sharding
        if mesh is None:
            put = jnp.asarray

            def mask_put(name, a):
                return jnp.asarray(a)
        else:
            # pod axes replicate; the mask/score tables' NODE axis shards
            # with the mirror (each core sees every pod, owns a node
            # shard) — both resolved by the name-keyed rule table
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(mesh, P())

            def put(a):
                return jax.device_put(np.asarray(a), repl)

            def mask_put(name, a):
                return sharding.put(mesh, name, a)
        out = {"req": put(self.req),
               "nonzero_req": put(self.nonzero_req),
               "mem_pressure_blocked": put(self.mem_pressure_blocked),
               "active": put(self.active),
               "seq": put(self.seq),
               "mask_idx": put(self.mask_idx),
               "score_idx": put(self.score_idx),
               "nom_row": put(self.nom_row),
               "unique_masks": mask_put("unique_masks", self.unique_masks),
               "unique_scores": mask_put("unique_scores",
                                         self.unique_scores),
               "resource_weights": put(self.resource_weights)}
        if self.spread_base is not None:
            import jax.numpy as jnp
            out["spread_gidx"] = put(self.spread_gidx)
            out["spread_match"] = put(self.spread_match)
            out["spread_base"] = mask_put("spread_base", self.spread_base)
            # the zone-id vector is node-axis data: it shards with the
            # mirror rows so the shard_map kernel's local slice aligns
            out["spread_zone"] = mask_put("spread_zone", self.spread_zone)
            out["spread_zinit"] = put(self.spread_zinit)
            out["spread_weight"] = jnp.float32(self.spread_weight)
        if self.anti_dom is not None:
            # the dom table may already sit on device, epoch-cached and
            # sharded by the topology index (set_topology_terms dom_dev)
            out["anti_dom"] = self.anti_dom_dev \
                if self.anti_dom_dev is not None \
                else mask_put("anti_dom", self.anti_dom)
            out["anti_cnt0"] = put(self.anti_cnt0)
            out["anti_tids"] = put(self.anti_tids)
            out["aff_tids"] = put(self.aff_tids)
            out["match_tids"] = put(self.match_tids)
            if self.cmatch_tids is not None:
                out["cmatch_tids"] = put(self.cmatch_tids)
                out["canti_tids"] = put(self.canti_tids)
        if self.soft_dom is not None:
            import jax.numpy as jnp
            out["soft_dom"] = mask_put("soft_dom", self.soft_dom)
            out["soft_cnt0"] = put(self.soft_cnt0)
            out["soft_base"] = mask_put("soft_base", self.soft_base)
            out["soft_base_idx"] = put(self.soft_base_idx)
            out["soft_read_tids"] = put(self.soft_read_tids)
            out["soft_read_w"] = put(self.soft_read_w)
            out["soft_write_tids"] = put(self.soft_write_tids)
            out["soft_write_w"] = put(self.soft_write_w)
            out["soft_weight"] = jnp.float32(self.soft_weight)
        if self._class_tables is not None:
            ct = self._class_tables
            for k in ("class_req", "class_nz", "class_blocked",
                      "class_mask_idx", "class_score_idx"):
                out[k] = put(ct[k])
            out["class_idx"] = put(ct["class_idx"])
        if self.spec_plain is not None:
            # pod-axis cohort vector; replicates by the named rule
            # (sharding._COHORT_REPLICATED)
            out["spec_plain"] = mask_put("spec_plain", self.spec_plain)
        return out
