"""Preemption — host-side victim search over tensor-screened candidates.

Ref: pkg/scheduler/core/generic_scheduler.go Preempt (:310-369),
selectNodesForPreemption (:996), selectVictimsOnNode (:1054-1128),
pickOneNodeForPreemption (:837-962, six tie-break criteria), and
pkg/scheduler/scheduler.go preempt (:292-380).

The reference fans the per-node victim search over 16 goroutines; here the
candidate set is cut first by the SAME cached per-node boolean vectors the
kernel uses (TermCompiler): only nodes whose pod-independent constraints
(taints, selectors, conditions, hostname) pass are examined, because those
failures are exactly the ones evicting other pods cannot fix
(ref: nodesWherePreemptionMightHelp's unresolvable-reason list). A second
O(pods-on-node) resource screen — could evicting every lower-priority pod
even free enough? — runs before any NodeInfo clone, so the expensive
clone + full-predicate reprieve loop touches only plausible nodes.

Victim selection is inherently serial per node (the reprieve loop's fit
checks depend on prior re-adds), so it stays on host, consuming the python
predicate oracle (predicates.py) — the same functions the kernel is
parity-tested against.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api import helpers, labels as labelsmod
from ..api.core import Pod
from ..api.policy import PodDisruptionBudget
from . import predicates as preds
from .nodeinfo import NodeInfo, pod_resource


@dataclass
class PreemptionPlan:
    node_name: str
    victims: List[Pod]
    num_pdb_violations: int
    # nominated pods on node_name with lower priority whose nomination the
    # shell must clear (ref: getLowerPriorityNominatedPods, :371-388)
    nominated_to_clear: List[Pod] = field(default_factory=list)


@dataclass
class GangPreemptionPlan:
    """Whole-gang preemption (kernels/preempt.py price_domains): evict
    `victims` (whole PodGroups expanded) and nominate each member to its
    node inside the winning ICI domain — the freed space is shielded by
    the nominated-reservation overlay until the gang binds."""
    domain: str
    victims: List[Pod]
    nominations: List[Tuple[Pod, str]]   # (member, node name)
    num_pdb_violations: int


def pod_eligible_to_preempt_others(pod: Pod,
                                   node_infos: Dict[str, NodeInfo]) -> bool:
    """Ref: podEligibleToPreemptOthers (:1130-1150) — a pod that already
    preempted (nominated node set) must wait while its victims terminate."""
    nn = pod.status.nominated_node_name
    if not nn:
        return True
    ni = node_infos.get(nn)
    if ni is None:
        return True
    prio = helpers.pod_priority(pod)
    for p in ni.pods:
        if p.metadata.deletion_timestamp is not None and \
                helpers.pod_priority(p) < prio:
            return False
    return True


def _more_important(p: Pod) -> Tuple[int, str]:
    """Sort key: higher priority first, then earlier start
    (ref: pkg/scheduler/util.MoreImportantPod)."""
    return (-helpers.pod_priority(p), p.status.start_time or "")


def filter_pods_with_pdb_violation(pods: Sequence[Pod],
                                   pdbs: Sequence[PodDisruptionBudget]
                                   ) -> Tuple[List[Pod], List[Pod]]:
    """Split would-be victims into (violating, non_violating) with cumulative
    per-PDB accounting (ref: filterPodsWithPDBViolation :964-994): each
    non-violating eviction consumes one disruptionsAllowed."""
    allowed = {id(pdb): pdb.status.disruptions_allowed for pdb in pdbs}
    violating: List[Pod] = []
    ok: List[Pod] = []
    for pod in pods:
        matched = []
        for pdb in pdbs:
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            sel = pdb.spec.selector
            if sel is None or not labelsmod.matches(sel, pod.metadata.labels):
                continue
            matched.append(pdb)
        if any(allowed[id(p)] <= 0 for p in matched):
            violating.append(pod)
        else:
            for p in matched:
                allowed[id(p)] -= 1
            ok.append(pod)
    return violating, ok


def select_victims_on_node(pod: Pod, ni: NodeInfo,
                           node_infos: Dict[str, NodeInfo],
                           fits: Callable[[Pod, preds.PredicateMetadata,
                                           NodeInfo], bool],
                           pdbs: Sequence[PodDisruptionBudget],
                           base_meta: Optional[preds.PredicateMetadata] = None
                           ) -> Optional[Tuple[List[Pod], int]]:
    """Ref: selectVictimsOnNode (:1054-1128). Remove every lower-priority
    pod; if the preemptor still doesn't fit, the node is hopeless. Otherwise
    reprieve pods one at a time — most important first, PDB-violating pods
    first so as many of them as possible are spared — keeping each one that
    doesn't break the fit. Returns (victims, numPDBViolations) or None.

    `base_meta` is the preemptor's cluster-wide metadata, built ONCE by the
    caller and cloned here per candidate node (ref: selectNodesForPreemption
    metaCopy) — rebuilding it per node would rescan every pod in the
    cluster for each candidate."""
    prio = helpers.pod_priority(pod)
    potential = [p for p in ni.pods if helpers.pod_priority(p) < prio]
    if not potential:
        return None
    ni = ni.clone()
    meta = base_meta.clone() if base_meta is not None \
        else preds.PredicateMetadata(pod, node_infos)
    for v in potential:
        ni.remove_pod(v)
        meta.remove_pod(v, ni)
    if not fits(pod, meta, ni):
        return None
    potential.sort(key=_more_important)
    violating, non_violating = filter_pods_with_pdb_violation(potential, pdbs)
    victims: List[Pod] = []

    def reprieve(p: Pod) -> bool:
        ni.add_pod(p)
        meta.add_pod(p, ni)
        if fits(pod, meta, ni):
            return True
        ni.remove_pod(p)
        meta.remove_pod(p, ni)
        victims.append(p)
        return False

    num_violations = sum(0 if reprieve(p) else 1 for p in violating)
    for p in non_violating:
        reprieve(p)
    if not victims:
        # everything was reprieved: the preemptor fit all along; scheduling
        # (not preemption) should have placed it — treat as no-op candidate
        return None
    return victims, num_violations


def pick_one_node_for_preemption(
        nodes_to_victims: Dict[str, Tuple[List[Pod], int]]) -> Optional[str]:
    """Ref: pickOneNodeForPreemption (:837-962) — six criteria applied in
    order, each narrowing the candidate list:
      1. fewest PDB violations
      2. lowest highest-victim priority
      3. smallest sum of victim priorities
      4. fewest victims
      5. latest start time among each node's highest-priority victims
      6. first remaining
    """
    if not nodes_to_victims:
        return None
    candidates = list(nodes_to_victims.keys())

    def narrow(key_fn, minimize=True):
        nonlocal candidates
        if len(candidates) == 1:
            return
        vals = {n: key_fn(*nodes_to_victims[n]) for n in candidates}
        best = min(vals.values()) if minimize else max(vals.values())
        candidates = [n for n in candidates if vals[n] == best]

    narrow(lambda v, nviol: nviol)
    narrow(lambda v, _: max(helpers.pod_priority(p) for p in v))
    narrow(lambda v, _: sum(helpers.pod_priority(p) for p in v))
    narrow(lambda v, _: len(v))

    def latest_high_priority_start(v: List[Pod], _) -> str:
        hi = max(helpers.pod_priority(p) for p in v)
        return max((p.status.start_time or "")
                   for p in v if helpers.pod_priority(p) == hi)
    narrow(latest_high_priority_start, minimize=False)
    return candidates[0]


def nominated_pods_to_clear(pod: Pod, node_name: str,
                            nominated_on_node: Sequence[Pod]) -> List[Pod]:
    """Lower-priority pods nominated to the chosen node lose their
    nomination — their space estimate is invalidated by the eviction
    (ref: getLowerPriorityNominatedPods :371-388)."""
    prio = helpers.pod_priority(pod)
    return [p for p in nominated_on_node
            if helpers.pod_priority(p) < prio]


def node_could_ever_fit(pod: Pod, ni: NodeInfo) -> bool:
    """Could the pod fit on this node with NOTHING else running? Used to
    decide whether a standing nomination is still worth waiting on."""
    req = pod_resource(pod)
    alloc = ni.allocatable
    return (req.milli_cpu <= alloc.milli_cpu
            and req.memory <= alloc.memory
            and alloc.allowed_pod_number >= 1)


def resource_screen(pod: Pod, ni: NodeInfo) -> bool:
    """Cheap pre-clone check: with EVERY lower-priority pod evicted, could
    the preemptor's resources fit? O(pods-on-node), no clones."""
    prio = helpers.pod_priority(pod)
    freed_cpu = freed_mem = 0
    freed_count = 0
    for p in ni.pods:
        if helpers.pod_priority(p) < prio:
            r = pod_resource(p)
            freed_cpu += r.milli_cpu
            freed_mem += r.memory
            freed_count += 1
    if freed_count == 0:
        return False
    req = pod_resource(pod)
    alloc = ni.allocatable
    used = ni.requested
    if req.milli_cpu > alloc.milli_cpu - used.milli_cpu + freed_cpu:
        return False
    if req.memory > alloc.memory - used.memory + freed_mem:
        return False
    if len(ni.pods) - freed_count + 1 > alloc.allowed_pod_number:
        return False
    return True
