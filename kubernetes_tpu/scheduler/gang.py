"""Gang scheduling — PodGroup grouping, queue admission, and the permit gate.

The coscheduling subsystem (ref: the Kueue/JobSet lineage in PAPERS.md;
mechanism modeled on sigs.k8s.io/scheduler-plugins' coscheduling plugin,
adapted to the batch kernel). Three gates keep a multi-host TPU slice's
workers ALL-OR-NOTHING:

  1. Queue admission (pop_gate): a gang member popped before `minMember`
     members are pending is PARKED — removed from the active heap but kept
     pending — so a starved gang can never head-of-line-block singletons.
     The arrival that completes the gang releases every parked member in
     the same queue-lock critical section (pod_pending), so one batch pop
     sees the whole gang.

  2. All-or-nothing placement: gang-carrying batches route through
     kernels/gang.py, which places each gang atomically against running
     usage (every member lands, on one ICI topology domain) or rejects the
     whole gang — no partial gang ever reaches the bind path from a single
     batch.

  3. Permit gate (permit/expire): when a gang still straddles batches
     (gang larger than a batch, retry races), winners RESERVE their nodes
     — assumed into the scheduler cache so the space is held — but bind
     only once `minMember` members hold reservations. A reservation older
     than the PodGroup's scheduleTimeoutSeconds rolls the WHOLE gang back
     (cache.forget_pods, one atomic sweep) and requeues the members.

Pods labeled into a PodGroup that does not exist yet are parked until it
appears (group_changed releases them) — scheduling them as singletons
would wedge the slice the moment the PodGroup arrives.

Lock order: callers holding the SchedulingQueue lock may call into the
manager (pop/add hooks); the manager never calls back into the queue, so
queue-lock -> manager-lock is the only ordering.

Pipelined-drain interplay: permit-gate reservations are TRACKED assumes
(scheduler._tracked_assume), so a gang straddling batches keeps the
device-usage chain account balanced; every rollback path here (reject,
timeout expire, node_gone, bind_failed) forgets reservations UNtracked —
by design, that breaks the chain equality so the drain flushes and
relaunches from host truth, and the scheduler shell phantom-marks
in-flight chained batches whose usage counted the rolled-back members.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..api.core import Pod
from ..api.scheduling import (DEFAULT_SCHEDULE_TIMEOUT, PodGroup,
                              pod_group_key)
from ..utils.clock import Clock, REAL_CLOCK

#: seconds a parked (below-minMember) member waits before it is handed to
#: the queue's unschedulable backoff machinery — the slow-path retry that
#: re-evaluates a PodGroup whose spec changed under a parked gang
PARK_TIMEOUT = 60.0

#: pop_gate verdicts
ADMIT = "admit"
PARK = "park"
#: parked for an exhausted per-namespace active-gang quota — its own
#: verdict so the queue can attribute it as QuotaExhausted, not as a
#: gang that merely has not formed yet
PARK_QUOTA = "park-quota"


class _Gang:
    """Per-PodGroup member bookkeeping. States are disjoint key sets:
    pending (in the queue, parked subset marked separately), inflight
    (popped, being decided), waiting (node reserved at the permit gate),
    bound (bind committed). Admissibility counts them all — a gang is
    schedulable when enough members EXIST to complete it, not only when
    all of them happen to sit in the queue at once."""

    __slots__ = ("key", "pending", "parked", "inflight", "waiting", "bound",
                 "first_wait", "dom_pin")

    def __init__(self, key: str):
        self.key = key
        self.pending: Dict[str, Pod] = {}
        self.parked: Dict[str, float] = {}          # pod key -> parked at
        self.inflight: Dict[str, float] = {}        # pod key -> popped at
        # pod key -> (queue pod, assumed clone, node name, reserved at)
        self.waiting: Dict[str, Tuple[Pod, Pod, str, float]] = {}
        self.bound: set = set()
        self.first_wait: Optional[float] = None
        #: topology-label VALUE the gang's reservations agree on — the
        #: kernel pins a domain only within one batch; this is the
        #: cross-batch pin (None until the first constrained reservation)
        self.dom_pin: Optional[str] = None

    def member_count(self) -> int:
        return (len(self.pending) + len(self.inflight)
                + len(self.waiting) + len(self.bound))

    def reserved_count(self) -> int:
        return len(self.waiting) + len(self.bound)

    def empty(self) -> bool:
        return self.member_count() == 0


class GangManager:
    """Groups pending pods by PodGroup and drives all three gang gates.

    `group_lookup(namespace, name) -> Optional[PodGroup]` is consulted on
    every decision (an informer indexer get), so spec changes — minMember
    lowered, timeout raised — take effect without replumbing.
    """

    def __init__(self, group_lookup: Callable[[str, str], Optional[PodGroup]],
                 clock: Clock = REAL_CLOCK, metrics=None,
                 node_label: Optional[Callable[[str, str],
                                              Optional[str]]] = None,
                 quota_gate=None):
        self._lookup = group_lookup
        self._clock = clock
        self.metrics = metrics
        #: node_label(node_name, label_key) -> value | None; the permit
        #: gate's cross-batch ICI-domain check (None disables it)
        self._node_label = node_label
        #: tenancy.GangQuotaGate (optional): per-namespace active-gang
        #: slots claimed at pop admission, returned when the gang's last
        #: member leaves the books (_gc)
        self.quota_gate = quota_gate
        self._lock = threading.RLock()
        self._gangs: Dict[str, _Gang] = {}
        #: gang key -> the QuotaBlock that last parked it (attribution)
        self._quota_blocks: Dict[str, object] = {}
        #: a slot was returned since the last quota_released() sweep
        self._quota_freed = False
        #: reservations invalidated outside the permit flow (their pod was
        #: deleted while waiting); drained by expire() for cache rollback
        self._orphaned: List[Tuple[Pod, Pod]] = []

    # ----------------------------------------------------------- lookup

    def _spec(self, gkey: str) -> Optional[PodGroup]:
        ns, _, name = gkey.partition("/")
        return self._lookup(ns, name)

    def _min_member(self, gkey: str) -> Optional[int]:
        """None while the PodGroup object does not exist (members park)."""
        pg = self._spec(gkey)
        return None if pg is None else max(1, pg.spec.min_member)

    def _timeout(self, gkey: str) -> float:
        pg = self._spec(gkey)
        if pg is None:
            return float(DEFAULT_SCHEDULE_TIMEOUT)
        return float(pg.spec.schedule_timeout_seconds)

    def topology_key(self, gkey: str) -> str:
        pg = self._spec(gkey)
        return pg.spec.topology_key if pg is not None else ""

    def _gang(self, gkey: str) -> _Gang:
        g = self._gangs.get(gkey)
        if g is None:
            g = _Gang(gkey)
            self._gangs[gkey] = g
        return g

    def _admissible(self, g: _Gang) -> bool:
        mm = self._min_member(g.key)
        return mm is not None and g.member_count() >= mm

    def _gc(self, g: _Gang) -> None:
        if not g.waiting and not g.bound:
            # no reservation left to agree with: the next generation of
            # reservations picks its own domain
            g.dom_pin = None
        if g.empty():
            self._gangs.pop(g.key, None)
            self._quota_blocks.pop(g.key, None)
            if self.quota_gate is not None \
                    and self.quota_gate.release(g.key):
                self._quota_freed = True

    def _observe_pending(self) -> None:
        if self.metrics is not None:
            self.metrics.gangs_pending.set(
                sum(1 for g in self._gangs.values() if g.parked),
                stage="queue")
            self.metrics.gangs_pending.set(
                sum(1 for g in self._gangs.values() if g.waiting),
                stage="permit")

    def min_member(self, gkey: str) -> Optional[int]:
        """Public minMember lookup (None while the PodGroup is absent)."""
        return self._min_member(gkey)

    def pending_members(self, gkey: str) -> List[Pod]:
        """The gang's pending (incl. parked) member pods in sorted-key
        order — whole-gang preemption's placement list."""
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return []
            return [g.pending[k] for k in sorted(g.pending)]

    def demand_shapes(self) -> List[dict]:
        """Every stuck gang as a capacity-demand SHAPE: minMember x the
        representative member request x one ICI domain (topology key).
        The autoscaler's scale-up signal and /debug/pending's parked-gang
        report both read this — a parked gang is not just a queue state,
        it is a slice the cluster does not have."""
        from .nodeinfo import pod_resource
        out: List[dict] = []
        with self._lock:
            for gkey in sorted(self._gangs):
                g = self._gangs[gkey]
                if not g.pending:
                    continue
                pg = self._spec(gkey)
                if pg is None:
                    continue
                rep = g.pending[sorted(g.pending)[0]]
                r = pod_resource(rep)
                out.append({
                    "gang": gkey,
                    "min_member": max(1, pg.spec.min_member),
                    "pending": len(g.pending),
                    "parked": len(g.parked),
                    "reserved": g.reserved_count(),
                    "members": sorted(g.pending),
                    "topology_key": pg.spec.topology_key,
                    "cpu_m": r.milli_cpu,
                    "memory": r.memory,
                    "scalars": dict(r.scalar_resources)})
        return out

    # ------------------------------------------------------ queue hooks

    def pod_pending(self, pod: Pod) -> List[str]:
        """A gang member (re)entered the pending set. Returns the parked
        member keys to reactivate when this arrival makes the gang
        admissible — the caller (queue, under its lock) pushes them back
        onto the active heap so one batch pop sees the whole gang."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return []
        with self._lock:
            g = self._gang(gkey)
            key = pod.metadata.key()
            g.inflight.pop(key, None)
            if key not in g.waiting and key not in g.bound:
                g.pending[key] = pod
            released: List[str] = []
            if g.parked and self._admissible(g):
                released = list(g.parked)
                g.parked.clear()
            self._observe_pending()
            return released

    def pod_gone(self, pod: Pod) -> None:
        """Queue delete: the pod was removed while unbound. A waiting
        member's reservation is orphaned for the next expire() sweep to
        roll back; bound members are kept — they still count toward the
        gang until the controller takes over."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return
            key = pod.metadata.key()
            if key in g.bound:
                return
            g.pending.pop(key, None)
            g.parked.pop(key, None)
            g.inflight.pop(key, None)
            entry = g.waiting.pop(key, None)
            if entry is not None:
                self._orphaned.append((entry[0], entry[1]))
            if not g.waiting:
                g.first_wait = None
            self._gc(g)
            self._observe_pending()

    def pop_gate(self, pod: Pod) -> str:
        """Pop-time admission (called under the queue lock, pod still in
        the queue's pending map). ADMIT marks the member in flight; PARK
        tells the queue to hold the pod out of the active heap;
        PARK_QUOTA is the same hold but because the namespace's
        active-gang quota is exhausted (the block is retrievable via
        quota_block_for until a slot frees up)."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return ADMIT
        with self._lock:
            g = self._gang(gkey)
            key = pod.metadata.key()
            if key not in g.pending:
                g.pending[key] = pod
            if self._admissible(g):
                # an admissible gang additionally needs an active-gang
                # slot — unless it already holds reservations or bound
                # members (a started gang must be allowed to finish;
                # try_admit is idempotent while the slot is held)
                if self.quota_gate is not None and g.reserved_count() == 0:
                    block = self.quota_gate.try_admit(gkey)
                    if block is not None:
                        self._quota_blocks[gkey] = block
                        g.parked.setdefault(key, self._clock.now())
                        self._observe_pending()
                        return PARK_QUOTA
                self._quota_blocks.pop(gkey, None)
                g.pending.pop(key, None)
                g.parked.pop(key, None)
                g.inflight[key] = self._clock.now()
                return ADMIT
            g.parked.setdefault(key, self._clock.now())
            self._observe_pending()
            return PARK

    def quota_block_for(self, pod: Pod):
        """The QuotaBlock that parked this member's gang (None when the
        gang is not quota-parked) — the queue's attribution source."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return None
        with self._lock:
            return self._quota_blocks.get(gkey)

    def quota_changed(self) -> None:
        """A ResourceQuota was raised/deleted: treat it like a freed
        slot so the next quota_released() sweep re-evaluates parked
        gangs against the new limit."""
        with self._lock:
            self._quota_freed = True

    def quota_released(self) -> List[str]:
        """Reactivation sweep after an active-gang slot was returned:
        every parked member of an admissible gang goes back to the
        active heap (optimistic — pop_gate re-checks the quota, so a
        gang that still cannot get a slot simply re-parks). Returns
        nothing when no slot was freed since the last sweep."""
        with self._lock:
            if not self._quota_freed:
                return []
            self._quota_freed = False
            out: List[str] = []
            for g in self._gangs.values():
                if g.parked and self._admissible(g):
                    out.extend(g.parked)
                    g.parked.clear()
            self._observe_pending()
            return out

    def group_changed(self, gkey: str) -> List[str]:
        """A PodGroup was created/updated: parked members may now clear
        the (possibly lowered) minMember bar."""
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None or not g.parked or not self._admissible(g):
                return []
            released = list(g.parked)
            g.parked.clear()
            self._observe_pending()
            return released

    def expired_parked(self, now: float) -> List[str]:
        """Parked members older than PARK_TIMEOUT, handed to the queue's
        unschedulable backoff machinery (the gang's slow-path retry). The
        park marks are cleared; the pods stay pending members."""
        with self._lock:
            out: List[str] = []
            for g in self._gangs.values():
                for key, ts in list(g.parked.items()):
                    if now - ts >= PARK_TIMEOUT:
                        del g.parked[key]
                        out.append(key)
            return out

    # ------------------------------------------------------ permit gate

    def is_member(self, pod: Pod) -> bool:
        return pod_group_key(pod) is not None

    def permit(self, pod: Pod, clone: Pod, node_name: str
               ) -> Tuple[str, List[Tuple[Pod, Pod, str]]]:
        """A gang member won a node and its reservation (`clone`) is
        assumed in the cache. Returns ("allow", released) with EVERY
        waiting reservation (this one included) when the gang reached
        minMember — the caller binds them as one transaction —
        ("wait", []) while the gang is still short, or ("reject", [])
        when this node breaks the gang's cross-batch ICI-domain pin (the
        caller must drop the reservation and requeue the pod: the kernel
        pins a domain only within one batch, so a gang split across
        batches could otherwise reserve on two slices and bind straddled)."""
        gkey = pod_group_key(pod)
        assert gkey is not None
        now = self._clock.now()
        with self._lock:
            g = self._gang(gkey)
            key = pod.metadata.key()
            tk = self.topology_key(gkey)
            if tk and self._node_label is not None:
                val = self._node_label(node_name, tk)
                if val is None or (g.dom_pin is not None
                                   and val != g.dom_pin):
                    g.pending.pop(key, None)
                    g.inflight.pop(key, None)
                    return "reject", []
                if g.dom_pin is None:
                    g.dom_pin = val
            g.pending.pop(key, None)
            g.inflight.pop(key, None)
            g.waiting[key] = (pod, clone, node_name, now)
            if g.first_wait is None:
                g.first_wait = now
            mm = self._min_member(gkey)
            if mm is not None and g.reserved_count() >= mm:
                released = [(p, c, n) for p, c, n, _ in g.waiting.values()]
                if self.metrics is not None:
                    for _, _, _, since in g.waiting.values():
                        self.metrics.gang_permit_wait.observe(now - since)
                    self.metrics.gangs_admitted.inc()
                g.bound.update(g.waiting)
                g.waiting.clear()
                g.first_wait = None
                self._observe_pending()
                return "allow", released
            self._observe_pending()
            return "wait", []

    def bind_failed(self, pod: Pod) -> Optional[Pod]:
        """A released member's bind failed: hand back its assumed clone so
        the caller can roll the reservation off the cache. The member
        leaves the bound set; requeueing (or dropping) it is the bind
        path's decision, and its re-add flows through pod_pending."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return None
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return None
            g.bound.discard(pod.metadata.key())
            self._gc(g)
            return None  # clone already handed out with the release

    def pod_bound(self, pod: Pod) -> None:
        """A member's bind committed (also reached via the normal
        singleton path when a whole gang bound in one batch)."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return
            key = pod.metadata.key()
            g.pending.pop(key, None)
            g.inflight.pop(key, None)
            g.waiting.pop(key, None)
            g.bound.add(key)
            if not g.waiting:
                g.first_wait = None

    def bind_regressed(self, pod: Pod
                       ) -> Tuple[List[Tuple[Pod, Pod]], List[Pod]]:
        """The store REGRESSED this member's bind (torn-WAL recovery:
        the journal lost the bind transaction's tail and the pod is
        Pending again). The member leaves the bound set — its re-add
        flows through pod_pending like any requeue — and, per the PR 2
        whole-group convention, every reservation the gang still holds
        at the permit gate rolls back NOW: the group's placement
        integrity is in doubt (sibling binds may be torn too, the
        dom_pin may reference a placement the store no longer records),
        and waiting out scheduleTimeoutSeconds just delays the retry.
        Returns (rollbacks, requeue) in node_gone's shape."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return [], []
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return [], []
            g.bound.discard(pod.metadata.key())
            rollbacks: List[Tuple[Pod, Pod]] = []
            requeue: List[Pod] = []
            now = self._clock.now()
            for p, clone, _node, since in g.waiting.values():
                rollbacks.append((p, clone))
                requeue.append(p)
                if self.metrics is not None:
                    self.metrics.gang_permit_wait.observe(now - since)
            g.waiting.clear()
            g.first_wait = None
            self._gc(g)  # clears dom_pin with the last reservation
            self._observe_pending()
            return rollbacks, requeue

    def pod_dropped(self, pod: Pod) -> None:
        """A member left the system for good: deleted in flight, deleted or
        terminal after binding, duplicate bind. Unlike pod_gone (queue
        deletes, where bound members must keep counting toward the gang),
        this removes the key from EVERY state including bound — a deleted
        worker must not inflate reserved_count forever, or a re-created
        gang would release partially against stale counts."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                return
            key = pod.metadata.key()
            g.pending.pop(key, None)
            g.parked.pop(key, None)
            g.inflight.pop(key, None)
            g.waiting.pop(key, None)
            g.bound.discard(key)
            self._gc(g)

    def node_gone(self, node_name: str
                  ) -> Tuple[List[Tuple[Pod, Pod]], List[Pod]]:
        """A node vanished (deleted, or NoExecute-tainted dead): every
        permit-gate reservation on it is pinned to a broken slice.
        Unlike pod_gone — where only the deleted pod's reservation is
        orphaned — the WHOLE affected gang rolls back NOW: the surviving
        members' reservations hold space the gang can no longer use
        (the dom_pin may point at the dead slice), and waiting out
        scheduleTimeoutSeconds just delays the retry. Returns
        (rollbacks, requeue) in expire()'s shape: (pod, assumed clone)
        pairs to forget from the cache, and the surviving member pods to
        requeue — all of them still exist (the node died, not the pods),
        so all of them go back to the queue."""
        with self._lock:
            rollbacks: List[Tuple[Pod, Pod]] = []
            requeue: List[Pod] = []
            for g in list(self._gangs.values()):
                if not any(n == node_name
                           for _, _, n, _ in g.waiting.values()):
                    continue
                now = self._clock.now()
                for pod, clone, _, since in g.waiting.values():
                    rollbacks.append((pod, clone))
                    requeue.append(pod)
                    if self.metrics is not None:
                        self.metrics.gang_permit_wait.observe(now - since)
                g.waiting.clear()
                g.first_wait = None
                if self.metrics is not None:
                    self.metrics.gangs_node_lost.inc()
                self._gc(g)  # clears dom_pin with the last reservation
            self._observe_pending()
            return rollbacks, requeue

    def reservations(self) -> List[Tuple[str, str, str]]:
        """(gang key, pod key, node name) for every live permit-gate
        reservation — the invariant checker sweeps these against the set
        of live, untainted nodes."""
        with self._lock:
            return [(g.key, key, node)
                    for g in self._gangs.values()
                    for key, (_p, _c, node, _t) in g.waiting.items()]

    def expire(self, now: float
               ) -> Tuple[List[Tuple[Pod, Pod]], List[Pod]]:
        """The permit-timeout sweep. Returns (rollbacks, requeue):
        `rollbacks` are (pod, assumed clone) reservations to forget from
        the cache — a timed-out gang's ENTIRE waiting set plus any orphaned
        reservations — and `requeue` the pods to put back in the queue.
        Also drops stale in-flight marks (a pod the commit path lost track
        of must not inflate the gang's member count forever)."""
        with self._lock:
            rollbacks = list(self._orphaned)
            self._orphaned = []
            requeue: List[Pod] = []
            for g in list(self._gangs.values()):
                for key, ts in list(g.inflight.items()):
                    if now - ts >= PARK_TIMEOUT:
                        del g.inflight[key]
                if g.first_wait is None or not g.waiting:
                    self._gc(g)
                    continue
                if now - g.first_wait < self._timeout(g.key):
                    continue
                for pod, clone, _, since in g.waiting.values():
                    rollbacks.append((pod, clone))
                    requeue.append(pod)
                    if self.metrics is not None:
                        self.metrics.gang_permit_wait.observe(now - since)
                g.waiting.clear()
                g.first_wait = None
                if self.metrics is not None:
                    self.metrics.gangs_timed_out.inc()
                self._gc(g)
            self._observe_pending()
            return rollbacks, requeue

    # ----------------------------------------------------- batch groups

    def batch_groups(self, pods: List[Pod]
                     ) -> Optional[List[Tuple[List[int], str, bool,
                                              Optional[str]]]]:
        """Partition one batch into placement units for the all-or-nothing
        kernel: each unit is (member indices, topology key, is_gang,
        pinned domain value), gangs in first-appearance order and every
        non-member a singleton unit. The pin is the label VALUE earlier
        batches' reservations already agreed on (None when free) — the
        kernel seeds its domain carry with it, so stragglers of a split
        gang can only place inside the slice the rest reserved. Returns
        None when the batch carries no gang members — the caller keeps
        the plain schedule_batch path."""
        units: List[Tuple[List[int], str, bool, Optional[str]]] = []
        by_group: Dict[str, int] = {}
        any_gang = False
        with self._lock:
            for i, pod in enumerate(pods):
                gkey = pod_group_key(pod)
                if gkey is None or self._spec(gkey) is None:
                    units.append(([i], "", False, None))
                    continue
                any_gang = True
                u = by_group.get(gkey)
                if u is None:
                    by_group[gkey] = len(units)
                    g = self._gangs.get(gkey)
                    units.append(([i], self.topology_key(gkey), True,
                                  g.dom_pin if g is not None else None))
                else:
                    units[u][0].append(i)
        return units if any_gang else None
