"""Static score compilation: the non-resource priorities as deduplicated
per-node score rows.

Ref: pkg/scheduler/algorithm/priorities/ and PrioritizeNodes
(generic_scheduler.go:672-812). The reference runs Map per (priority, node)
then Reduce per priority over the FILTERED node list. Here:

  - raw per-node vectors are compiled on the host through the same term
    cache as the filter terms (pods sharing tolerations/affinity/images hit
    the cache),
  - Reduce (NormalizeReduce / reversed / min-max / spread's zone blend) is
    vectorized numpy over the pod's statically-feasible node set,
  - the weighted sum is computed ONCE per unique score key (pods of one
    controller share terms, labels, and requests) and ships to the kernel as
    pod_batch["unique_scores"] [S, N] + ["score_idx"] [P], added on device to
    the resource scores (LeastRequested/Balanced, which the scan recomputes
    per step because they vary with in-batch usage).

Priorities whose contribution is CONSTANT over a pod's feasible nodes (e.g.
TaintToleration when no node has PreferNoSchedule taints: all 10) are
selection-invariant and dropped — ScheduleResult.score is therefore the
selection score, not the reference's absolute weighted sum.

In-batch drift: SelectorSpread counts and InterPodAffinity terms are frozen
at batch start (the reference re-runs them after every one-pod bind). Hard
(anti-)affinity stays exact via core._repair_batch; soft scores may lag by
one batch — the documented batching tradeoff.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import helpers, labels as labelsmod, wellknown
from ..api.core import Pod
from ..api.meta import controller_ref
from . import priorities as prios
from .nodeinfo import NodeInfo
from .tensorize import TensorMirror, TermCompiler, _canon_tolerations

MAXP = float(prios.MAX_PRIORITY)


def _canon_preferred_node_affinity(pod: Pod) -> Tuple:
    aff = pod.spec.affinity
    if not aff or not aff.node_affinity:
        return ()
    return tuple(
        (t.weight,
         tuple((r.key, r.operator, tuple(r.values))
               for r in t.preference.match_expressions),
         tuple((r.key, r.operator, tuple(r.values))
               for r in t.preference.match_fields))
        for t in aff.node_affinity.preferred_during_scheduling_ignored_during_execution)


def _canon_pod_affinity(pod: Pod) -> Tuple:
    """Canonical form of the pod's preferred (anti-)affinity terms — part of
    the static-score dedupe key (scorer rows are shared across pods whose
    affinity terms, labels, and namespace coincide)."""
    aff = pod.spec.affinity
    if not aff:
        return ()

    def canon_weighted(terms):
        out = []
        for wt in terms or []:
            t = wt.pod_affinity_term
            sel = labelsmod.canonical_selector(t.label_selector) \
                if t.label_selector is not None else None
            out.append((wt.weight, sel, t.topology_key,
                        tuple(sorted(t.namespaces))))
        return tuple(out)

    pa = canon_weighted(
        aff.pod_affinity.preferred_during_scheduling_ignored_during_execution
        if aff.pod_affinity else None)
    paa = canon_weighted(
        aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution
        if aff.pod_anti_affinity else None)
    return (pa, paa)


def _has_preferred_pod_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return bool(aff and (
        (aff.pod_affinity and
         aff.pod_affinity.preferred_during_scheduling_ignored_during_execution) or
        (aff.pod_anti_affinity and
         aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution)))


class ScoreCompiler:
    """Builds the static [P, N] score matrix for a batch."""

    def __init__(self, mirror: TensorMirror, terms: TermCompiler,
                 listers: Optional[prios.SpreadListers] = None,
                 weights: Optional[Dict[str, int]] = None,
                 hard_pod_affinity_weight: int = prios.HARD_POD_AFFINITY_WEIGHT,
                 topology=None):
        self.mirror = mirror
        self.terms = terms
        #: scheduler/topology.py TopologyIndex — when present, inter-pod
        #: affinity scoring is count-matrix gathers instead of the
        #: O(existing pods × terms) python scan per template
        self.topology = topology
        self.listers = listers
        self.weights = dict(weights if weights is not None
                            else prios.DEFAULT_PRIORITY_WEIGHTS)
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self._epoch = -1
        self._vec_cache: Dict[Tuple, np.ndarray] = {}
        self._zone_ids: Optional[np.ndarray] = None
        self._any_prefer_taints = False
        self._any_avoid_annotations = False
        self._cluster_has_affinity_pods = False
        #: bumped by invalidate_spread_selectors (Service/RC/RS/SS
        #: events): part of the spread chain signature, so a selector
        #: source changing mid-chain refuses the chained spread carry
        self.spread_sel_gen = 0

    def set_weights(self, weights: Dict[str, int],
                    hard_pod_affinity_weight: Optional[int] = None) -> None:
        """Install Policy weights (ref: CreateFromConfig applying
        policy.Priorities); invalidates the static-vector cache."""
        self.weights = dict(weights)
        if hard_pod_affinity_weight is not None:
            self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self._epoch = -1
        self._vec_cache.clear()

    # ------------------------------------------------------- cached vectors

    def _refresh_epoch(self) -> None:
        if self._epoch == self.mirror.epoch:
            return
        self._epoch = self.mirror.epoch
        self._vec_cache.clear()
        self._spread_sel_memo: Dict[Tuple, bool] = {}
        cap = self.mirror.t.capacity
        zone_ids = np.zeros((cap,), np.int32)
        zones: Dict[str, int] = {"": 0}
        any_taints = False
        any_avoid = False
        any_images = False
        for row, ni in enumerate(self.mirror.infos):
            if ni is None or ni.node is None:
                continue
            z = ni.node.metadata.labels.get(wellknown.LABEL_ZONE, "")
            zid = zones.get(z)
            if zid is None:
                zid = len(zones)
                zones[z] = zid
            zone_ids[row] = zid
            if any(t.effect == "PreferNoSchedule" for t in ni.taints):
                any_taints = True
            if prios.PREFER_AVOID_PODS_ANNOTATION in ni.node.metadata.annotations:
                any_avoid = True
            if ni.image_sizes:
                any_images = True
        self._zone_ids = zone_ids
        self._n_zones = len(zones)
        self._any_prefer_taints = any_taints
        self._any_avoid_annotations = any_avoid
        self._any_images = any_images

    def _vec(self, key: Tuple, fn) -> np.ndarray:
        hit = self._vec_cache.get(key)
        if hit is not None:
            return hit
        cap = self.mirror.t.capacity
        vec = np.zeros((cap,), np.float32)
        for row, ni in enumerate(self.mirror.infos):
            if ni is not None and ni.node is not None:
                vec[row] = fn(ni)
        self._vec_cache[key] = vec
        return vec

    def _node_affinity_raw(self, pod: Pod, meta: prios.PriorityMetadata
                           ) -> Optional[np.ndarray]:
        key = ("nodeaff", _canon_preferred_node_affinity(pod))
        if not key[1]:
            return None
        return self._vec(key, lambda ni: prios.node_affinity_map(pod, meta, ni))

    def _taint_raw(self, pod: Pod, meta: prios.PriorityMetadata
                   ) -> Optional[np.ndarray]:
        if not self._any_prefer_taints:
            return None  # all counts 0 -> reversed reduce gives constant 10
        key = ("tainttol", _canon_tolerations(pod))
        return self._vec(key, lambda ni: prios.taint_toleration_map(pod, meta, ni))

    def _image_raw(self, pod: Pod, meta: prios.PriorityMetadata
                   ) -> Optional[np.ndarray]:
        if not self._any_images:
            return None  # no node reports images -> all zeros
        images = tuple(sorted({c.image for c in pod.spec.containers if c.image}))
        if not images:
            return None
        key = ("img", images)
        return self._vec(key, lambda ni: prios.image_locality_map(pod, meta, ni))

    def _avoid_raw(self, pod: Pod, meta: prios.PriorityMetadata
                   ) -> Optional[np.ndarray]:
        if not self._any_avoid_annotations:
            return None  # constant 10 everywhere
        ref = controller_ref(pod.metadata)
        if ref is None or ref.kind not in ("ReplicationController", "ReplicaSet"):
            return None
        key = ("avoid", ref.kind, ref.name)
        return self._vec(key, lambda ni: prios.node_prefer_avoid_map(pod, meta, ni))

    def _spread_counts(self, pod: Pod, meta: prios.PriorityMetadata
                       ) -> Optional[np.ndarray]:
        if not meta.pod_selectors:
            return None
        # selectors derive from the pod's owning service/controller; key by
        # namespace + its labels (pods of one controller share both)
        key = ("spread", pod.metadata.namespace,
               tuple(sorted(pod.metadata.labels.items())))
        return self._vec(key, lambda ni: prios.selector_spread_map(pod, meta, ni))

    # ------------------------------------------------------------- compile

    def _pod_score_key(self, pod: Pod) -> Optional[Tuple]:
        """Canonical key of everything that can make this pod's static score
        row differ from another pod's — None when no priority can contribute
        (the common resource-only case). Pods from one controller share the
        key, so rows are computed once per controller, not once per pod."""
        w = self.weights
        parts = []
        contributes = False
        if w.get("NodeAffinityPriority"):
            k = _canon_preferred_node_affinity(pod)
            parts.append(k)
            contributes = contributes or bool(k)
        if w.get("TaintTolerationPriority") and self._any_prefer_taints:
            parts.append(_canon_tolerations(pod))
            contributes = True
        if w.get("ImageLocalityPriority") and self._any_images:
            images = tuple(sorted({c.image for c in pod.spec.containers
                                   if c.image}))
            parts.append(images)
            contributes = contributes or bool(images)
        if w.get("NodePreferAvoidPodsPriority") and self._any_avoid_annotations:
            ref = controller_ref(pod.metadata)
            if ref is not None and ref.kind in ("ReplicationController",
                                                "ReplicaSet"):
                parts.append((ref.kind, ref.name))
                contributes = True
            else:
                parts.append(None)
        spread_or_interpod = False
        if w.get("SelectorSpreadPriority") and self.listers is not None \
                and self._pod_has_spread_selectors(pod):
            spread_or_interpod = True
        if w.get("InterPodAffinityPriority") and (
                _has_preferred_pod_affinity(pod) or
                self._cluster_has_affinity_pods):
            spread_or_interpod = True
        if spread_or_interpod:
            parts.append((pod.metadata.namespace,
                          tuple(sorted(pod.metadata.labels.items())),
                          _canon_pod_affinity(pod)))
            contributes = True
        if not contributes:
            return None
        return tuple(parts)

    def invalidate_spread_selectors(self) -> None:
        """Drop the per-template spread-selector memo. The scheduler shell
        calls this on Service/RC/RS/StatefulSet informer events (the same
        events that move parked pods back to active): mirror.epoch only
        moves on node changes, so without this a Service created mid-run
        on a node-quiet cluster would leave its templates memoized as
        selector-less and silently skip spread scoring."""
        self._spread_sel_memo = {}
        self.spread_sel_gen += 1

    def _pod_has_spread_selectors(self, pod: Pod) -> bool:
        """SelectorSpread contributes only when some service/controller
        selector matches the pod; without one, the whole (ns, labels)
        score-key component — and its per-template fits_row +
        PriorityMetadata work — is dead weight. Memoized per template,
        invalidated by node epoch AND selector-source events
        (invalidate_spread_selectors), so a selector-less 16k-pod burst
        skips static scoring entirely."""
        memo = getattr(self, "_spread_sel_memo", None)
        if memo is None:
            memo = self._spread_sel_memo = {}
        key = (pod.metadata.namespace,
               tuple(sorted(pod.metadata.labels.items())))
        hit = memo.get(key)
        if hit is None:
            hit = bool(self.listers.selectors_for_pod(pod))
            memo[key] = hit
        return hit

    def static_scores(self, pods: List[Pod], batch
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Deduplicated static scores: (score_idx [P], unique_rows [S, N]),
        or None when no priority contributes for any pod (resource-only
        batch — the device kernel needs no static term at all).

        Each unique (score key, feasibility row) computes ONE weighted row;
        reduces normalize over the representative pod's batch-start feasible
        set (the reference normalizes over filtered nodes,
        generic_scheduler.go PrioritizeNodes). `batch` is the
        PodBatchTensors (for mask_idx/req identity and fits_row)."""
        self._refresh_epoch()
        P = len(pods)
        score_idx = np.zeros((P,), np.int32)
        rows: List[np.ndarray] = [np.zeros((self.mirror.t.capacity,),
                                           np.float32)]
        row_of: Dict[Tuple, int] = {}
        any_contrib = False
        for i, pod in enumerate(pods):
            skey = self._pod_score_key(pod)
            if skey is None:
                continue
            # pods in an in-scan spread group get their spread component
            # from the kernel's running counts — the static row must not
            # double-count it; same for inter-pod affinity when the batch
            # carries in-scan soft credit tables (core._assign_soft_terms)
            kernel_spread = bool(batch.spread_gidx[i] >= 0)
            kernel_interpod = getattr(batch, "soft_dom", None) is not None
            # the feasible set (normalization domain) depends on the mask
            # row, the request columns, and the pressure flag
            key = (skey, int(batch.mask_idx[i]), batch.req[i].tobytes(),
                   bool(batch.mem_pressure_blocked[i]), kernel_spread,
                   kernel_interpod)
            u = row_of.get(key)
            if u is None:
                row = self._compute_row(pod, batch.fits_row(i),
                                        skip_spread=kernel_spread,
                                        skip_interpod=kernel_interpod)
                if row is None:
                    u = 0
                else:
                    rows.append(row)
                    u = len(rows) - 1
                row_of[key] = u
            if u:
                any_contrib = True
            score_idx[i] = u
        if not any_contrib:
            return None
        return score_idx, np.stack(rows)

    def _compute_row(self, pod: Pod, fits: np.ndarray,
                     skip_spread: bool = False,
                     skip_interpod: bool = False) -> Optional[np.ndarray]:
        """One pod's weighted static score row [N] (None = all-constant)."""
        w = self.weights
        meta = prios.PriorityMetadata(pod, self.listers)
        total: Optional[np.ndarray] = None

        def acc(vec: np.ndarray, weight: float):
            nonlocal total
            if total is None:
                total = np.zeros((self.mirror.t.capacity,), np.float32)
            total += weight * vec

        def feas_max(raw: np.ndarray) -> float:
            vals = raw[fits]
            return float(vals.max()) if vals.size else 0.0

        if w.get("NodeAffinityPriority"):
            raw = self._node_affinity_raw(pod, meta)
            if raw is not None:
                mx = feas_max(raw)
                if mx > 0:
                    acc(np.floor(MAXP * raw / mx), w["NodeAffinityPriority"])
        if w.get("TaintTolerationPriority"):
            raw = self._taint_raw(pod, meta)
            if raw is not None:
                mx = feas_max(raw)
                if mx > 0:  # reversed NormalizeReduce
                    acc(MAXP - np.floor(MAXP * raw / mx),
                        w["TaintTolerationPriority"])
        if w.get("ImageLocalityPriority"):
            raw = self._image_raw(pod, meta)
            if raw is not None and raw.any():
                acc(raw, w["ImageLocalityPriority"])  # no reduce
        if w.get("NodePreferAvoidPodsPriority"):
            raw = self._avoid_raw(pod, meta)
            if raw is not None:
                acc(raw, w["NodePreferAvoidPodsPriority"])
        if w.get("SelectorSpreadPriority") and not skip_spread:
            counts = self._spread_counts(pod, meta)
            if counts is not None and counts.any():
                acc(self._spread_reduce(counts, fits),
                    w["SelectorSpreadPriority"])
        if w.get("InterPodAffinityPriority") and not skip_interpod:
            raw = self._interpod_raw(pod)
            if raw is not None:
                mn = float(raw[fits].min()) if fits.any() else 0.0
                mx = float(raw[fits].max()) if fits.any() else 0.0
                if mx > mn:
                    acc(np.floor(MAXP * (raw - mn) / (mx - mn)),
                        w["InterPodAffinityPriority"])
        return total

    def _spread_reduce(self, counts: np.ndarray, feas: np.ndarray
                       ) -> np.ndarray:
        """CalculateSpreadPriorityReduce with zone blending
        (selector_spreading.go zoneWeighting=2/3)."""
        max_count = float(counts[feas].max()) if feas.any() else 0.0
        if max_count > 0:
            node_score = MAXP * (max_count - counts) / max_count
        else:
            node_score = np.full_like(counts, MAXP)
        zid = self._zone_ids
        have_zones = (zid[feas] > 0).any() if feas.any() else False
        if not have_zones:
            return np.floor(node_score)
        zcounts = np.bincount(zid, weights=counts * feas,
                              minlength=self._n_zones)
        max_zone = float(zcounts[1:].max()) if self._n_zones > 1 else 0.0
        zone_of_node = zcounts[zid]
        # zone-less nodes keep the default MaxPriority zone score
        # (selector_spreading.go: zoneScore initialized to MaxPriority and
        # only recomputed for nodes with a zone id)
        zone_score = np.where((zid > 0) & (max_zone > 0),
                              MAXP * (max_zone - zone_of_node) /
                              max(max_zone, 1.0),
                              MAXP)
        blended = node_score * (1 - prios.ZONE_WEIGHTING) + \
            prios.ZONE_WEIGHTING * zone_score
        return np.floor(blended)

    def _interpod_raw(self, pod: Pod) -> Optional[np.ndarray]:
        """Preferred inter-pod (anti-)affinity + symmetric hard credit.
        Through the topology index when available (count-matrix gathers);
        the O(existing pods × terms) python scan over the snapshot is the
        fallback and the parity oracle. Only runs when the pod or the
        cluster carries (anti-)affinity terms."""
        if not _has_preferred_pod_affinity(pod) and \
                not self._cluster_has_affinity_pods:
            return None
        if self.topology is not None:
            return self.topology.score_vector(
                pod, self.hard_pod_affinity_weight)
        node_infos = {name: self.mirror.infos[row]
                      for name, row in self.mirror.row_of.items()
                      if self.mirror.infos[row] is not None}
        raw_by_name = prios.interpod_affinity_scores(
            pod, self.hard_pod_affinity_weight, node_infos)
        if not any(raw_by_name.values()):
            return None
        raw = np.zeros((self.mirror.t.capacity,), np.float32)
        for name, v in raw_by_name.items():
            raw[self.mirror.row_of[name]] = v
        return raw

    def set_cluster_has_affinity_pods(self, flag: bool) -> None:
        self._cluster_has_affinity_pods = flag
