"""Scheduling framework — plugin points and registry.

Ref: pkg/scheduler/framework/v1alpha1/{interface.go:89-142, framework.go:
30-114, registry.go, context.go}. The v1.15 snapshot exposes exactly two
extension points — Reserve (after a host is chosen, before assume) and
Prebind (before the bind is issued) — which is what this implements, plus
the same supporting pieces: a name->factory Registry, a per-scheduling-
cycle PluginContext K/V store, and a Framework runner that calls every
registered plugin in registration order and stops on the first failure.

Gang extension: a Permit point between Reserve and Prebind (the later
framework versions' WaitOnPermit, interface.go in >= v1.17; coscheduling
builds on it). A permit plugin may return Status.wait(), which parks the
winner with its node RESERVED (assumed in the cache) but unbound; the
shell binds it when a later cycle's permit allows it, or rolls the
reservation back on timeout (scheduler/gang.py drives both edges).

Batch adaptation: the reference runs plugins inside scheduleOne, once per
pod; here the shell calls run_reserve_plugins per winner before its assume
and run_prebind_plugins per winner before the bulk bind — same per-pod
semantics, same ordering guarantees relative to assume/bind
(scheduler.go:507,533).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..api.core import Pod


class PluginContext:
    """Per-cycle scratch shared across plugins (ref: context.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, object] = {}

    def read(self, key: str):
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def write(self, key: str, value) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)


class Status:
    """Ref: interface.go Status — Success, an error message, or Wait
    (the Permit point's third verdict: hold the reservation, bind later)."""

    WAIT = 2

    def __init__(self, code: int = 0, message: str = ""):
        self.code = code
        self.message = message

    @property
    def success(self) -> bool:
        return self.code == 0

    @property
    def is_wait(self) -> bool:
        return self.code == Status.WAIT

    @staticmethod
    def ok() -> "Status":
        return Status()

    @staticmethod
    def error(message: str) -> "Status":
        return Status(1, message)

    @staticmethod
    def wait(message: str = "") -> "Status":
        return Status(Status.WAIT, message)


class Plugin:
    """Base plugin; subclasses implement reserve, permit and/or prebind
    (ref: ReservePlugin/PrebindPlugin interfaces + the later PermitPlugin)."""

    name = "plugin"

    def reserve(self, ctx: PluginContext, pod: Pod,
                node_name: str) -> Status:
        return Status.ok()

    def permit(self, ctx: PluginContext, pod: Pod,
               node_name: str) -> Status:
        return Status.ok()

    def prebind(self, ctx: PluginContext, pod: Pod,
                node_name: str) -> Status:
        return Status.ok()


class Registry:
    """name -> factory (ref: registry.go)."""

    def __init__(self):
        self._factories: Dict[str, Callable[..., Plugin]] = {}

    def register(self, name: str, factory: Callable[..., Plugin]) -> None:
        if name in self._factories:
            raise ValueError(f"plugin {name} already registered")
        self._factories[name] = factory

    def unregister(self, name: str) -> None:
        self._factories.pop(name, None)

    def build_all(self, *args, **kwargs) -> List[Plugin]:
        # the reference's NewFramework instantiates every registry entry
        # unconditionally (framework.go:58-70)
        return [f(*args, **kwargs) for f in self._factories.values()]


class Framework:
    """Runs the plugin set at each extension point
    (ref: framework.go RunReservePlugins :79, RunPrebindPlugins :96)."""

    def __init__(self, registry: Optional[Registry] = None,
                 plugins: Optional[List[Plugin]] = None):
        self.plugins: List[Plugin] = list(plugins or [])
        if registry is not None:
            self.plugins.extend(registry.build_all())

    def run_reserve_plugins(self, ctx: PluginContext, pod: Pod,
                            node_name: str) -> Status:
        for p in self.plugins:
            st = p.reserve(ctx, pod, node_name)
            if not st.success:
                return Status.error(
                    f"error while running {p.name} reserve plugin for pod "
                    f"{pod.metadata.name}: {st.message}")
        return Status.ok()

    def run_permit_plugins(self, ctx: PluginContext, pod: Pod,
                           node_name: str) -> Status:
        """First error wins; otherwise a single Wait verdict makes the
        whole point Wait (ref: RunPermitPlugins — max of the statuses)."""
        wait: Optional[Status] = None
        for p in self.plugins:
            st = p.permit(ctx, pod, node_name)
            if st.is_wait:
                wait = st
            elif not st.success:
                return Status.error(
                    f"error while running {p.name} permit plugin for pod "
                    f"{pod.metadata.name}: {st.message}")
        return wait if wait is not None else Status.ok()

    def run_prebind_plugins(self, ctx: PluginContext, pod: Pod,
                            node_name: str) -> Status:
        for p in self.plugins:
            st = p.prebind(ctx, pod, node_name)
            if not st.success:
                return Status.error(
                    f"error while running {p.name} prebind plugin for pod "
                    f"{pod.metadata.name}: {st.message}")
        return Status.ok()
