"""Per-node scheduling aggregate.

Ref: pkg/scheduler/nodeinfo/node_info.go — NodeInfo (:47-86), Resource
(:139-148), AddPod/RemovePod/Clone, and host_ports.go HostPortInfo.

Resource carries exactly the columns the tensor mirror exports per node:
milli_cpu, memory, ephemeral_storage, allowed_pod_number, plus a scalar map
for extended resources — the reference's column schema.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import helpers, wellknown
from ..api.core import Node, Pod


@dataclass
class Resource:
    """Ref: node_info.go:139-148."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar_resources: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_request_map(cls, req: Dict[str, int]) -> "Resource":
        r = cls()
        for name, v in req.items():
            r.add(name, v)
        return r

    def add(self, name: str, v: int) -> None:
        if name == wellknown.RESOURCE_CPU:
            self.milli_cpu += v
        elif name == wellknown.RESOURCE_MEMORY:
            self.memory += v
        elif name == wellknown.RESOURCE_EPHEMERAL_STORAGE:
            self.ephemeral_storage += v
        elif name == wellknown.RESOURCE_PODS:
            self.allowed_pod_number += v
        else:
            self.scalar_resources[name] = self.scalar_resources.get(name, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) - v

    def add_resource(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) + v

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.ephemeral_storage,
                        self.allowed_pod_number, dict(self.scalar_resources))


def pod_resource(pod: Pod) -> Resource:
    """Memoized per PodSpec — callers treat the Resource as read-only."""
    spec = pod.spec
    cached = spec.__dict__.get("_res_cache")
    if cached is None:
        cached = Resource.from_request_map(helpers.pod_requests(pod))
        spec.__dict__["_res_cache"] = cached
    return cached


def pod_resource_nonzero(pod: Pod) -> Tuple[int, int]:
    """(milliCPU, memory) with non-zero defaults (ref: non_zero.go)."""
    spec = pod.spec
    cached = spec.__dict__.get("_nz_cache")
    if cached is None:
        r = helpers.pod_requests_nonzero(pod)
        cached = (r.get(wellknown.RESOURCE_CPU, 0),
                  r.get(wellknown.RESOURCE_MEMORY, 0))
        spec.__dict__["_nz_cache"] = cached
    return cached


def pod_has_affinity_constraints(pod: Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)


class NodeInfo:
    """Dense per-node aggregate; `generation` is bumped on every mutation so
    snapshots copy only changed nodes (ref: node_info.go:83-99)."""

    __slots__ = ("node", "pods", "pods_with_affinity", "requested",
                 "non_zero_requested", "allocatable", "used_ports",
                 "taints", "memory_pressure", "disk_pressure", "pid_pressure",
                 "image_sizes", "generation")

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = None
        self.pods: List[Pod] = []
        self.pods_with_affinity: List[Pod] = []
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        # {(protocol, ip, port)} (ref: host_ports.go; wildcard-IP overlap is
        # resolved in predicates/tensorize, storage keeps the raw triples)
        self.used_ports: Set[Tuple[str, str, int]] = set()
        self.taints = []
        self.memory_pressure = False
        self.disk_pressure = False
        self.pid_pressure = False
        self.image_sizes: Dict[str, int] = {}
        self.generation = 0
        if node is not None:
            self.set_node(node)

    @property
    def name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = Resource.from_request_map(helpers.node_allocatable(node))
        self.taints = list(node.spec.taints)
        self.memory_pressure = _cond(node, "MemoryPressure")
        self.disk_pressure = _cond(node, "DiskPressure")
        self.pid_pressure = _cond(node, "PIDPressure")
        self.image_sizes = {name: img.size_bytes
                            for img in node.status.images for name in img.names}

    def add_pod(self, pod: Pod) -> None:
        res = pod_resource(pod)
        self.requested.add_resource(res)
        cpu0, mem0 = pod_resource_nonzero(pod)
        self.non_zero_requested.milli_cpu += cpu0
        self.non_zero_requested.memory += mem0
        self.pods.append(pod)
        if pod_has_affinity_constraints(pod):
            self.pods_with_affinity.append(pod)
        for hp in helpers.pod_host_ports(pod):
            self.used_ports.add(hp)

    def remove_pod(self, pod: Pod) -> bool:
        """Returns False if the pod was not present (ref: RemovePod error)."""
        key = pod.metadata.key()
        for i, p in enumerate(self.pods):
            if p.metadata.key() == key:
                del self.pods[i]
                break
        else:
            return False
        self.pods_with_affinity = [p for p in self.pods_with_affinity
                                   if p.metadata.key() != key]
        res = pod_resource(pod)
        self.requested.sub(res)
        cpu0, mem0 = pod_resource_nonzero(pod)
        self.non_zero_requested.milli_cpu -= cpu0
        self.non_zero_requested.memory -= mem0
        for hp in helpers.pod_host_ports(pod):
            self.used_ports.discard(hp)
        return True

    def clone(self) -> "NodeInfo":
        c = NodeInfo()
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.requested = self.requested.clone()
        c.non_zero_requested = self.non_zero_requested.clone()
        c.allocatable = self.allocatable.clone()
        c.used_ports = set(self.used_ports)
        c.taints = list(self.taints)
        c.memory_pressure = self.memory_pressure
        c.disk_pressure = self.disk_pressure
        c.pid_pressure = self.pid_pressure
        c.image_sizes = dict(self.image_sizes)
        c.generation = self.generation
        return c


def _cond(node: Node, ctype: str) -> bool:
    for c in node.status.conditions:
        if c.type == ctype:
            return c.status == "True"
    return False
