"""Filter predicates — python semantic reference.

Ref: pkg/scheduler/algorithm/predicates/{predicates.go (1,706 LoC),
metadata.go, csi_volume_predicate.go}. The default provider registers 14
(algorithmprovider/defaults/defaults.go:40-56); evaluation order is
predicates.Ordering() (predicates.go:143-149).

On TPU the same semantics run as a pods x nodes mask kernel
(tensorize.py + kernels/batch.py); these functions are the parity oracle
and the host path
for preemption's AddPod/RemovePod incremental re-evaluation.

Each predicate: (pod, meta, node_info) -> (fits: bool, reasons: list[str]).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api import helpers, labels as labelsmod, wellknown
from ..api.core import Pod, PodAffinityTerm
from .nodeinfo import NodeInfo, Resource, pod_resource

# failure reasons (ref: predicates/error.go)
ERR_INSUFFICIENT = "Insufficient {}"
ERR_POD_COUNT = "Too many pods"
ERR_NODE_SELECTOR = "node(s) didn't match node selector"
ERR_HOST = "node(s) didn't match the requested hostname"
ERR_PORTS = "node(s) didn't have free ports for the requested pod ports"
ERR_TAINTS = "node(s) had taints that the pod didn't tolerate"
ERR_MEMORY_PRESSURE = "node(s) had memory pressure"
ERR_DISK_PRESSURE = "node(s) had disk pressure"
ERR_PID_PRESSURE = "node(s) had pid pressure"
ERR_NODE_CONDITION = "node(s) had condition"
ERR_UNSCHEDULABLE = "node(s) were unschedulable"
ERR_AFFINITY = "node(s) didn't match pod affinity rules"
ERR_ANTI_AFFINITY = "node(s) didn't satisfy existing pods anti-affinity rules"
ERR_DISK_CONFLICT = "node(s) had no available disk"
ERR_VOLUME_ZONE = "node(s) had no available volume zone"
ERR_VOLUME_BIND = "node(s) had volume node affinity conflict"


class PredicateMetadata:
    """Per-pod precompute shared across all nodes in one cycle
    (ref: metadata.go:71-94 predicateMetadata)."""

    def __init__(self, pod: Pod, all_node_infos: Dict[str, NodeInfo]):
        self.pod = pod
        self.pod_request = pod_resource(pod)
        self.pod_ports = helpers.pod_host_ports(pod)
        # scratch for predicates to stash pod-invariant precomputes that are
        # reused across the node loop (e.g. Max*VolumeCount wanted-sets,
        # which resolve PVC->PV through listers once per pod, not per node)
        self.memo: Dict[object, object] = {}
        # topology pair -> set of existing pod keys whose anti-affinity terms
        # match this (incoming) pod, i.e. pairs forbidden for the pod
        # (ref: topologyPairsAntiAffinityPodsMap)
        self.anti_affinity_pairs: Set[Tuple[str, str]] = set()
        # for the pod's own (anti)affinity terms: per term, the set of
        # topology pairs where matching pods exist
        self.affinity_term_pairs: List[Tuple[PodAffinityTerm, Set[Tuple[str, str]]]] = []
        self.anti_term_pairs: List[Tuple[PodAffinityTerm, Set[Tuple[str, str]]]] = []
        self._compute_topology_maps(all_node_infos)

    def _compute_topology_maps(self, all_node_infos: Dict[str, NodeInfo]) -> None:
        pod = self.pod
        aff = pod.spec.affinity
        own_aff_terms = _required_terms(
            aff.pod_affinity.required_during_scheduling_ignored_during_execution
            if aff and aff.pod_affinity else [])
        own_anti_terms = _required_terms(
            aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution
            if aff and aff.pod_anti_affinity else [])
        aff_pairs: List[Set[Tuple[str, str]]] = [set() for _ in own_aff_terms]
        anti_pairs: List[Set[Tuple[str, str]]] = [set() for _ in own_anti_terms]
        for ni in all_node_infos.values():
            if ni.node is None:
                continue
            node_labels = ni.node.metadata.labels
            for existing in ni.pods:
                # existing pods' anti-affinity vs the incoming pod
                ea = existing.spec.affinity
                if ea and ea.pod_anti_affinity:
                    for term in _required_terms(
                            ea.pod_anti_affinity.required_during_scheduling_ignored_during_execution):
                        if _term_matches_pod(term, existing, pod) and \
                                term.topology_key in node_labels:
                            self.anti_affinity_pairs.add(
                                (term.topology_key, node_labels[term.topology_key]))
                # incoming pod's terms vs existing pods
                for i, term in enumerate(own_aff_terms):
                    if _term_matches_pod(term, pod, existing) and \
                            term.topology_key in node_labels:
                        aff_pairs[i].add((term.topology_key, node_labels[term.topology_key]))
                for i, term in enumerate(own_anti_terms):
                    if _term_matches_pod(term, pod, existing) and \
                            term.topology_key in node_labels:
                        anti_pairs[i].add((term.topology_key, node_labels[term.topology_key]))
        self.affinity_term_pairs = list(zip(own_aff_terms, aff_pairs))
        self.anti_term_pairs = list(zip(own_anti_terms, anti_pairs))

    def clone(self) -> "PredicateMetadata":
        """Ref: metadata.go ShallowCopy — preemption's per-candidate-node
        what-if mutations need an isolated copy without re-scanning the
        cluster's topology maps."""
        c = object.__new__(PredicateMetadata)
        c.pod = self.pod
        c.pod_request = self.pod_request
        c.pod_ports = self.pod_ports
        c.memo = dict(self.memo)
        c.anti_affinity_pairs = set(self.anti_affinity_pairs)
        c.affinity_term_pairs = [(t, set(p)) for t, p in self.affinity_term_pairs]
        c.anti_term_pairs = [(t, set(p)) for t, p in self.anti_term_pairs]
        return c

    # incremental update for preemption what-if evaluation (ref: metadata.go
    # AddPod/RemovePod)
    def remove_pod(self, deleted: Pod, node_info: NodeInfo) -> None:
        self._adjust(deleted, node_info, add=False)

    def add_pod(self, added: Pod, node_info: NodeInfo) -> None:
        self._adjust(added, node_info, add=True)

    def _adjust(self, other: Pod, node_info: NodeInfo, add: bool) -> None:
        if node_info.node is None:
            return
        node_labels = node_info.node.metadata.labels
        oa = other.spec.affinity
        if oa and oa.pod_anti_affinity:
            for term in _required_terms(
                    oa.pod_anti_affinity.required_during_scheduling_ignored_during_execution):
                if _term_matches_pod(term, other, self.pod) and \
                        term.topology_key in node_labels:
                    pair = (term.topology_key, node_labels[term.topology_key])
                    if add:
                        self.anti_affinity_pairs.add(pair)
                    else:
                        # conservative: a full recompute would check whether
                        # another pod still pins this pair; preemption removes
                        # victims from one node only, where this is exact if
                        # no other pod on the node matches
                        still = any(
                            _term_matches_pod(t, p, self.pod) and
                            t.topology_key == pair[0] and
                            node_labels.get(t.topology_key) == pair[1]
                            for p in node_info.pods
                            if p.metadata.key() != other.metadata.key()
                            and p.spec.affinity and p.spec.affinity.pod_anti_affinity
                            for t in _required_terms(
                                p.spec.affinity.pod_anti_affinity.required_during_scheduling_ignored_during_execution))
                        if not still:
                            self.anti_affinity_pairs.discard(pair)
        for term, pairs in self.affinity_term_pairs + self.anti_term_pairs:
            if _term_matches_pod(term, self.pod, other) and \
                    term.topology_key in node_labels:
                pair = (term.topology_key, node_labels[term.topology_key])
                if add:
                    pairs.add(pair)
                # removal from term pairs is handled conservatively the same way


def _required_terms(terms: List[PodAffinityTerm]) -> List[PodAffinityTerm]:
    return [t for t in terms if t is not None]


def _term_matches_pod(term: PodAffinityTerm, term_owner: Pod, candidate: Pod) -> bool:
    """Does `candidate` match `term` of `term_owner`? Namespace semantics:
    empty namespaces list means the term-owner's namespace
    (ref: priorityutil.PodMatchesTermsNamespaceAndSelector)."""
    namespaces = term.namespaces or [term_owner.metadata.namespace]
    if candidate.metadata.namespace not in namespaces:
        return False
    return labelsmod.matches(term.label_selector, candidate.metadata.labels)


# ------------------------------------------------------------ predicates

def pod_fits_resources(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                       ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go:769-840 PodFitsResources."""
    reasons: List[str] = []
    alloc = ni.allocatable
    if len(ni.pods) + 1 > alloc.allowed_pod_number:
        reasons.append(ERR_POD_COUNT)
    req = meta.pod_request if meta is not None else pod_resource(pod)
    if req.milli_cpu == 0 and req.memory == 0 and req.ephemeral_storage == 0 \
            and not req.scalar_resources:
        return len(reasons) == 0, reasons
    if req.milli_cpu > alloc.milli_cpu - ni.requested.milli_cpu:
        reasons.append(ERR_INSUFFICIENT.format("cpu"))
    if req.memory > alloc.memory - ni.requested.memory:
        reasons.append(ERR_INSUFFICIENT.format("memory"))
    if req.ephemeral_storage > alloc.ephemeral_storage - ni.requested.ephemeral_storage:
        reasons.append(ERR_INSUFFICIENT.format("ephemeral-storage"))
    for name, v in req.scalar_resources.items():
        if v > alloc.scalar_resources.get(name, 0) - ni.requested.scalar_resources.get(name, 0):
            reasons.append(ERR_INSUFFICIENT.format(name))
    return len(reasons) == 0, reasons


def pod_fits_host(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                  ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go PodFitsHost."""
    if not pod.spec.node_name:
        return True, []
    if ni.node is not None and pod.spec.node_name == ni.node.metadata.name:
        return True, []
    return False, [ERR_HOST]


def pod_fits_host_ports(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                        ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go PodFitsHostPorts + host_ports.go CheckConflict
    (wildcard 0.0.0.0 conflicts with any IP on same proto/port)."""
    wanted = meta.pod_ports if meta is not None else helpers.pod_host_ports(pod)
    if not wanted:
        return True, []
    for proto, ip, port in wanted:
        for uproto, uip, uport in ni.used_ports:
            if proto != uproto or port != uport:
                continue
            if ip == uip or ip == "0.0.0.0" or uip == "0.0.0.0":
                return False, [ERR_PORTS]
    return True, []


def pod_match_node_selector(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                            ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go PodMatchNodeSelector."""
    if ni.node is None:
        return False, [ERR_NODE_SELECTOR]
    if helpers.pod_matches_node_selector_and_affinity(pod, ni.node):
        return True, []
    return False, [ERR_NODE_SELECTOR]


def pod_tolerates_node_taints(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                              ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go PodToleratesNodeTaints — only NoSchedule/NoExecute
    matter for scheduling."""
    if helpers.tolerates_taints(pod.spec.tolerations, ni.taints,
                                effects=["NoSchedule", "NoExecute"]):
        return True, []
    return False, [ERR_TAINTS]


def check_node_unschedulable(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                             ) -> Tuple[bool, List[str]]:
    """Ref: CheckNodeConditionPredicate's unschedulable spec field part."""
    if ni.node is not None and ni.node.spec.unschedulable:
        return False, [ERR_UNSCHEDULABLE]
    return True, []


def check_node_condition(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                         ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go CheckNodeConditionPredicate — NotReady,
    NetworkUnavailable, or unschedulable fail."""
    if ni.node is None:
        return False, [ERR_NODE_CONDITION]
    reasons = []
    for cond in ni.node.status.conditions:
        if cond.type == "Ready" and cond.status != "True":
            reasons.append(ERR_NODE_CONDITION)
        elif cond.type == "NetworkUnavailable" and cond.status == "True":
            reasons.append(ERR_NODE_CONDITION)
    if ni.node.spec.unschedulable:
        reasons.append(ERR_UNSCHEDULABLE)
    return len(reasons) == 0, reasons


def check_node_memory_pressure(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                               ) -> Tuple[bool, List[str]]:
    """Ref: CheckNodeMemoryPressurePredicate — only BestEffort pods blocked,
    unless they tolerate the memory-pressure taint."""
    if not ni.memory_pressure:
        return True, []
    if _pod_qos(pod) != "BestEffort":
        return True, []
    if helpers.tolerates_taints(
            pod.spec.tolerations,
            [_pressure_taint(wellknown.TAINT_NODE_MEMORY_PRESSURE)],
            effects=["NoSchedule"]):
        return True, []
    return False, [ERR_MEMORY_PRESSURE]


def check_node_disk_pressure(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                             ) -> Tuple[bool, List[str]]:
    if not ni.disk_pressure:
        return True, []
    return False, [ERR_DISK_PRESSURE]


def check_node_pid_pressure(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                            ) -> Tuple[bool, List[str]]:
    if not ni.pid_pressure:
        return True, []
    return False, [ERR_PID_PRESSURE]


def no_disk_conflict(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                     ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go NoDiskConflict — GCE PD / EBS / RBD / ISCSI volumes
    may not be mounted read-write by two pods on one node."""
    for vol in pod.spec.volumes:
        for existing in ni.pods:
            for evol in existing.spec.volumes:
                if _disks_conflict(vol, evol):
                    return False, [ERR_DISK_CONFLICT]
    return True, []


def _disks_conflict(v1, v2) -> bool:
    if v1.gce_persistent_disk and v2.gce_persistent_disk:
        if v1.gce_persistent_disk.get("pdName") == v2.gce_persistent_disk.get("pdName"):
            if not (v1.gce_persistent_disk.get("readOnly") and
                    v2.gce_persistent_disk.get("readOnly")):
                return True
    if v1.aws_elastic_block_store and v2.aws_elastic_block_store:
        if v1.aws_elastic_block_store.get("volumeID") == \
                v2.aws_elastic_block_store.get("volumeID"):
            return True
    if v1.rbd and v2.rbd:
        if (v1.rbd.get("monitors"), v1.rbd.get("image"), v1.rbd.get("pool")) == \
                (v2.rbd.get("monitors"), v2.rbd.get("image"), v2.rbd.get("pool")):
            return True
    if v1.iscsi and v2.iscsi:
        if (v1.iscsi.get("targetPortal"), v1.iscsi.get("iqn")) == \
                (v2.iscsi.get("targetPortal"), v2.iscsi.get("iqn")):
            return True
    return False


def match_inter_pod_affinity(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                             ) -> Tuple[bool, List[str]]:
    """Ref: predicates.go InterPodAffinityMatches via topologyPairsMaps:
    1. no existing pod's anti-affinity forbids this node's topology pairs
    2. every required affinity term of the pod has a matching pod in this
       node's topology (or, per the reference's special case, the term matches
       the incoming pod itself and no pod anywhere matches it yet)
    3. the pod's own anti-affinity terms have no match in this topology
    """
    if ni.node is None:
        return False, [ERR_AFFINITY]
    node_labels = ni.node.metadata.labels
    for tk, tv in meta.anti_affinity_pairs:
        if node_labels.get(tk) == tv:
            return False, [ERR_ANTI_AFFINITY]
    for term, pairs in meta.affinity_term_pairs:
        tk = term.topology_key
        if tk not in node_labels:
            return False, [ERR_AFFINITY]
        if (tk, node_labels[tk]) not in pairs:
            # special case (predicates.go:1476-1497): the term matches the
            # incoming pod itself and matches no existing pod anywhere
            if not pairs and _term_matches_pod(term, pod, pod):
                continue
            return False, [ERR_AFFINITY]
    for term, pairs in meta.anti_term_pairs:
        tk = term.topology_key
        if tk in node_labels and (tk, node_labels[tk]) in pairs:
            return False, [ERR_ANTI_AFFINITY]
    return True, []


def no_volume_zone_conflict_factory(pvc_lister, pv_lister, sc_lister=None):
    """Ref: predicates.go NewVolumeZonePredicate — a bound PV's zone/region
    labels must match the node's."""
    zone_labels = (wellknown.LABEL_ZONE, wellknown.LABEL_REGION)

    def predicate(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                  ) -> Tuple[bool, List[str]]:
        if ni.node is None:
            return False, [ERR_VOLUME_ZONE]
        node_labels = ni.node.metadata.labels
        for vol in pod.spec.volumes:
            if not vol.persistent_volume_claim:
                continue
            pvc = pvc_lister(pod.metadata.namespace, vol.persistent_volume_claim.claim_name)
            if pvc is None or not pvc.spec.volume_name:
                continue
            pv = pv_lister(pvc.spec.volume_name)
            if pv is None:
                continue
            for lk in zone_labels:
                lv = pv.metadata.labels.get(lk)
                if lv is None:
                    continue
                # PV zone labels may hold __ -separated sets (volume helpers)
                allowed = set(lv.split("__"))
                if node_labels.get(lk) not in allowed:
                    return False, [ERR_VOLUME_ZONE]
        return True, []

    return predicate


def check_volume_binding_factory(volume_binder):
    """Ref: predicates.go NewVolumeBindingPredicate → FindPodVolumes."""
    def predicate(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                  ) -> Tuple[bool, List[str]]:
        if ni.node is None:
            return False, [ERR_VOLUME_BIND]
        ok = volume_binder.find_pod_volumes(pod, ni.node)
        return (True, []) if ok else (False, [ERR_VOLUME_BIND])
    return predicate


def max_volume_count_factory(filter_fn: Callable, max_volumes: int,
                             pvc_lister=None):
    """Ref: predicates.go MaxPDVolumeCountChecker — EBS/GCEPD/AzureDisk and
    csi_volume_predicate.go. filter_fn(volume, pod_namespace) returns a unique
    volume id or None."""
    memo_key = object()  # unique per factory instance

    def predicate(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                  ) -> Tuple[bool, List[str]]:
        memo = getattr(meta, "memo", None) if meta is not None else None
        wanted: Optional[Set[str]] = \
            memo.get(memo_key) if memo is not None else None
        if wanted is None:
            wanted = set()
            for vol in pod.spec.volumes:
                vid = filter_fn(vol, pod.metadata.namespace)
                if vid is not None:
                    wanted.add(vid)
            if memo is not None:
                memo[memo_key] = wanted
        if not wanted:
            return True, []
        existing: Set[str] = set()
        for p in ni.pods:
            for vol in p.spec.volumes:
                vid = filter_fn(vol, p.metadata.namespace)
                if vid is not None:
                    existing.add(vid)
        if len(existing | wanted) > max_volumes:
            return False, ["node(s) exceed max volume count"]
        return True, []
    return predicate


# Ref: predicates.go DefaultMaxEBSVolumes / DefaultMaxGCEPDVolumes /
# getMaxAzureDiskVolumes (KUBE_MAX_PD_VOLS env override not carried over)
DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16


def pd_volume_filter_factory(attr: str, id_keys: Tuple[str, ...],
                             pvc_lister=None, pv_lister=None) -> Callable:
    """A max_volume_count_factory filter for one PD flavor: matches direct
    volume sources and PVC-referenced PVs of that flavor (ref: predicates.go
    EBSVolumeFilter/GCEPDVolumeFilter/AzureDiskVolumeFilter — FilterVolume +
    FilterPersistentVolume)."""
    def _vid(src: Optional[dict]) -> Optional[str]:
        if not src:
            return None
        for k in id_keys:
            v = src.get(k)
            if v:
                return f"{attr}:{v}"
        return None

    def filter_fn(vol, ns: str) -> Optional[str]:
        vid = _vid(getattr(vol, attr, None))
        if vid is not None:
            return vid
        ref = vol.persistent_volume_claim
        if ref and pvc_lister is not None and pv_lister is not None:
            pvc = pvc_lister(ns, ref.claim_name)
            if pvc is not None and pvc.spec.volume_name:
                pv = pv_lister(pvc.spec.volume_name)
                if pv is not None:
                    return _vid(getattr(pv.spec, attr, None))
        return None
    return filter_fn


def csi_max_volume_count_factory(pvc_lister=None, pv_lister=None) -> Callable:
    """Ref: csi_volume_predicate.go CSIMaxVolumeLimitChecker — per-driver
    attach limits read from node allocatable `attachable-volumes-csi-<driver>`
    scalars; CSI volumes reach pods only through PVCs."""
    def _driver_handle(vol, ns: str) -> Optional[Tuple[str, str]]:
        ref = vol.persistent_volume_claim
        if not ref or pvc_lister is None or pv_lister is None:
            return None
        pvc = pvc_lister(ns, ref.claim_name)
        if pvc is None or not pvc.spec.volume_name:
            return None
        pv = pv_lister(pvc.spec.volume_name)
        if pv is None or not pv.spec.csi:
            return None
        drv = pv.spec.csi.get("driver")
        if not drv:
            return None
        return drv, pv.spec.csi.get("volumeHandle", pvc.spec.volume_name)

    memo_key = object()

    def predicate(pod: Pod, meta: PredicateMetadata, ni: NodeInfo
                  ) -> Tuple[bool, List[str]]:
        memo = getattr(meta, "memo", None) if meta is not None else None
        wanted: Optional[Dict[str, Set[str]]] = \
            memo.get(memo_key) if memo is not None else None
        if wanted is None:
            wanted = {}
            for vol in pod.spec.volumes:
                dh = _driver_handle(vol, pod.metadata.namespace)
                if dh is not None:
                    wanted.setdefault(dh[0], set()).add(dh[1])
            if memo is not None:
                memo[memo_key] = wanted
        if not wanted:
            return True, []
        existing: Dict[str, Set[str]] = {}
        for p in ni.pods:
            for vol in p.spec.volumes:
                dh = _driver_handle(vol, p.metadata.namespace)
                if dh is not None:
                    existing.setdefault(dh[0], set()).add(dh[1])
        for drv, handles in wanted.items():
            limit = ni.allocatable.scalar_resources.get(
                f"attachable-volumes-csi-{drv}")
            if limit is None:
                continue  # node exposes no limit for this driver
            if len(handles | existing.get(drv, set())) > limit:
                return False, ["node(s) exceed max volume count"]
        return True, []
    return predicate


def default_max_volume_count_predicates(pvc_lister=None, pv_lister=None
                                        ) -> Dict[str, Callable]:
    """The four attach-limit members of the default predicate set
    (ref: algorithmprovider/defaults/defaults.go:40-56)."""
    return {
        "MaxEBSVolumeCount": max_volume_count_factory(
            pd_volume_filter_factory("aws_elastic_block_store", ("volumeID",),
                                     pvc_lister, pv_lister),
            DEFAULT_MAX_EBS_VOLUMES),
        "MaxGCEPDVolumeCount": max_volume_count_factory(
            pd_volume_filter_factory("gce_persistent_disk", ("pdName",),
                                     pvc_lister, pv_lister),
            DEFAULT_MAX_GCE_PD_VOLUMES),
        "MaxAzureDiskVolumeCount": max_volume_count_factory(
            pd_volume_filter_factory("azure_disk", ("diskURI", "diskName"),
                                     pvc_lister, pv_lister),
            DEFAULT_MAX_AZURE_DISK_VOLUMES),
        "MaxCSIVolumeCountPred": csi_max_volume_count_factory(
            pvc_lister, pv_lister),
    }


#: canonical GetPodQOS lives in api/helpers (shared with admission and
#: kubelet eviction); the old name stays for in-package callers
_pod_qos = helpers.pod_qos


def _pressure_taint(key: str):
    from ..api.core import Taint
    return Taint(key=key, effect="NoSchedule")


#: evaluation order (ref: predicates.go:143-149 Ordering()); short-circuit on
#: first failure is the host path; the TPU kernel computes all and ANDs
#: (the reference's alwaysCheckAllPredicates mode, generic_scheduler.go:652)
ORDERING = [
    "CheckNodeCondition",
    "CheckNodeUnschedulable",
    "GeneralPredicates",
    "HostName",
    "PodFitsHostPorts",
    "MatchNodeSelector",
    "PodFitsResources",
    "NoDiskConflict",
    "PodToleratesNodeTaints",
    "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount",
    "MaxCSIVolumeCountPred",
    "MaxAzureDiskVolumeCount",
    "CheckNodeMemoryPressure",
    "CheckNodePIDPressure",
    "CheckNodeDiskPressure",
    "MatchInterPodAffinity",
]

DEFAULT_PREDICATES: Dict[str, Callable] = {
    "CheckNodeCondition": check_node_condition,
    "HostName": pod_fits_host,
    "PodFitsHostPorts": pod_fits_host_ports,
    "MatchNodeSelector": pod_match_node_selector,
    "PodFitsResources": pod_fits_resources,
    "NoDiskConflict": no_disk_conflict,
    "PodToleratesNodeTaints": pod_tolerates_node_taints,
    "CheckNodeMemoryPressure": check_node_memory_pressure,
    "CheckNodePIDPressure": check_node_pid_pressure,
    "CheckNodeDiskPressure": check_node_disk_pressure,
    "MatchInterPodAffinity": match_inter_pod_affinity,
}


def pod_fits_on_node(pod: Pod, meta: PredicateMetadata, ni: NodeInfo,
                     predicates: Optional[Dict[str, Callable]] = None
                     ) -> Tuple[bool, List[str]]:
    """Run predicates in Ordering() with short-circuit
    (ref: generic_scheduler.go:598-664 podFitsOnNode single-pass)."""
    preds = predicates if predicates is not None else DEFAULT_PREDICATES
    for name in ORDERING:
        fn = preds.get(name)
        if fn is None:
            continue
        ok, reasons = fn(pod, meta, ni)
        if not ok:
            return False, reasons
    for name, fn in preds.items():
        if name not in ORDERING:
            ok, reasons = fn(pod, meta, ni)
            if not ok:
                return False, reasons
    return True, []
