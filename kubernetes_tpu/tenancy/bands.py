"""PriorityClass bands — named priority ranges replacing the single lane
threshold.

The scheduler's express lane has been one integer (`lane_priority`): at
or above it you ride the express drain, below it you batch. PriorityClass
objects (scheduling/v1) already carry richer intent — a name, a value,
preemption policy — so the band catalog derives the lane structure FROM
them: each PriorityClass opens a band at its value, a pod belongs to the
highest band whose value it reaches, and pods under every band fall into
the implicit ``best-effort`` band at value 0. Per-band SLO targets ride a
PriorityClass annotation (``serving.ktpu/slo-p99-bind-seconds``) so the
SLOTracker can judge each band against ITS promise instead of one global
gate; ``serving.ktpu/express`` marks which bands drain on the express
lane, and the catalog's ``lane_threshold()`` is the lowest express value
— the same integer the scheduler always took, now derived instead of
hard-coded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..api import helpers
from ..api.policy import PriorityClass

#: PriorityClass annotation: this band's p99 bind-latency target, seconds
SLO_ANNOTATION = "serving.ktpu/slo-p99-bind-seconds"
#: PriorityClass annotation ("true"): this band drains on the express lane
EXPRESS_ANNOTATION = "serving.ktpu/express"
#: the implicit bottom band pods under every PriorityClass fall into
BEST_EFFORT = "best-effort"


@dataclass(frozen=True)
class Band:
    name: str
    value: int                      # band floor (PriorityClass.value)
    express: bool = False
    slo_p99_bind_s: Optional[float] = None
    description: str = ""


class BandCatalog:
    """Bands sorted by floor, descending; ``band_of(priority)`` is the
    first whose floor the priority reaches."""

    def __init__(self, bands: Sequence[Band]):
        named = {b.name: b for b in bands}
        if BEST_EFFORT not in named:
            named[BEST_EFFORT] = Band(BEST_EFFORT, 0)
        self.bands: List[Band] = sorted(
            named.values(), key=lambda b: (-b.value, b.name))

    @classmethod
    def from_priority_classes(cls, pcs: Sequence[PriorityClass],
                              ) -> "BandCatalog":
        bands = []
        for pc in sorted(pcs, key=lambda p: p.metadata.key()):
            ann = pc.metadata.annotations
            slo = ann.get(SLO_ANNOTATION)
            bands.append(Band(
                name=pc.metadata.name,
                value=pc.value,
                express=ann.get(EXPRESS_ANNOTATION) == "true",
                slo_p99_bind_s=float(slo) if slo is not None else None,
                description=pc.description))
        return cls(bands)

    @classmethod
    def default(cls, lane_priority: int = 1000) -> "BandCatalog":
        """The legacy two-lane split expressed as bands — what a cluster
        without PriorityClass objects behaves like."""
        return cls([
            Band("express", lane_priority, express=True,
                 description="the express drain lane"),
            Band(BEST_EFFORT, 0,
                 description="batch: everything under the lane"),
        ])

    # ---------------------------------------------------------- lookups

    def band_of(self, priority: int) -> Band:
        for b in self.bands:
            if priority >= b.value:
                return b
        return self.bands[-1]  # negative priority: the bottom band

    def band_of_pod(self, pod) -> Band:
        return self.band_of(helpers.pod_priority(pod))

    def lane_threshold(self, default: int = 1000) -> int:
        """The express-lane integer the scheduler consumes: the lowest
        express band's floor (the legacy single threshold when no band
        is marked express)."""
        express = [b.value for b in self.bands if b.express]
        return min(express) if express else default

    def names(self) -> List[str]:
        return [b.name for b in self.bands]

    def targets(self) -> Dict[str, float]:
        """band name -> p99 bind SLO target (bands without one absent)."""
        return {b.name: b.slo_p99_bind_s for b in self.bands
                if b.slo_p99_bind_s is not None}
