"""Deterministic ResourceQuota reconciliation — the tenancy subsystem's
step-based twin of controllers/resourcequota.py.

The threaded controller reconciles through a workqueue plus a 30s resync
thread; under the FakeClock harnesses that timing is invisible and
unreproducible. This controller is the same semantic contract — the
reference's admission/registry split, where admission only charges
forward and the controller is the source of truth that also RELEASES —
expressed as a synchronous ``sync_all()`` the harness driver steps:
quotas visited in sorted-key order, usage recomputed from a settled
client listing with the SAME evaluators admission charges with
(``evaluate_usage`` + ``scope_matches``), status written only on drift.
Two same-seed runs therefore produce the identical sequence of quota
status writes.

Hard-cap coverage is whatever the hard keys name: compute
(``cpu``/``memory``/``requests.*``/``limits.*`` — TPU devices ride
``requests.google.com/tpu`` like any extended scalar), ``pods``, and
object counts (``count/podgroups`` for gang quota at the API surface).
"""

from __future__ import annotations

from typing import Dict, List

from ..api.core import ResourceQuota
from ..api.quantity import Quantity
from ..apiserver.admission import evaluate_usage, scope_matches
from ..controllers.resourcequota import ResourceQuotaController
from ..utils.errlog import SwallowedErrors


class TenantQuotaController:
    """Synchronous, informer-free ResourceQuota reconciler.

    ``sync_all()`` is one deterministic reconcile pass; call it from the
    harness tick (after settling) the way the serving harness steps its
    workload controllers. Key-resolution rules are SHARED with the
    threaded controller (``_resource_of_key``) so the two can never
    disagree about which resource a hard key counts.
    """

    def __init__(self, client, metrics=None):
        self.client = client
        self.metrics = metrics
        self._swallowed = SwallowedErrors("tenantquota")
        #: quota keys whose last sync wrote a status (observability)
        self.last_drift: List[str] = []

    # ------------------------------------------------------------- sync

    def sync_all(self) -> int:
        """Reconcile every quota, sorted by key. Returns the number of
        status writes (0 on a converged pass)."""
        quotas = sorted(
            self.client.resource_quotas().list(namespace=None),
            key=lambda q: q.metadata.key())
        writes = 0
        self.last_drift = []
        for quota in quotas:
            if self.sync_one(quota):
                writes += 1
                self.last_drift.append(quota.metadata.key())
        return writes

    def sync_one(self, quota: ResourceQuota) -> bool:
        """Recount one quota's used totals from live objects; write
        status only when it drifted. Returns True on a write."""
        ns = quota.metadata.namespace
        used: Dict[str, Quantity] = {}
        recounted = set()
        resources = sorted({
            ResourceQuotaController._resource_of_key(k)
            for k in quota.spec.hard})
        for resource in resources:
            objs = self._list(resource, ns)
            if objs is None:
                continue  # can't recount -> keep admission's charge
            recounted.add(resource)
            for obj in sorted(objs, key=lambda o: o.metadata.key()):
                if quota.spec.scopes and resource == "pods":
                    if not all(scope_matches(s, obj)
                               for s in quota.spec.scopes):
                        continue
                for k, v in evaluate_usage(resource, obj).items():
                    if k in quota.spec.hard:
                        used[k] = used.get(k, Quantity(0)) + v
        # every hard key reports a used total, even when zero; a key
        # whose resource could not be recounted keeps its current value
        # (zeroing it would wipe admission's charges)
        for k in quota.spec.hard:
            if k in used:
                continue
            if ResourceQuotaController._resource_of_key(k) in recounted:
                used[k] = Quantity(0)
            else:
                used[k] = quota.status.used.get(k, Quantity(0))
        if dict(quota.status.used) == used and \
                dict(quota.status.hard) == dict(quota.spec.hard):
            return False

        def mutate(live):
            live.status.hard = dict(live.spec.hard)
            live.status.used = used
            return live
        self.client.resource_quotas().patch(
            quota.metadata.name, mutate, namespace=ns)
        if self.metrics is not None:
            self.metrics.reconcile_writes.inc(namespace=ns)
        return True

    def _list(self, resource: str, ns: str):
        """Objects of `resource` in `ns` via the client (None when the
        kind is unknown or the listing fails — keep-charge semantics)."""
        from ..runtime.scheme import SCHEME
        cls = SCHEME.type_for_resource(resource)
        if cls is None:
            return None
        try:
            out = self.client.resource(cls).list(namespace=ns)
            self._swallowed.ok("list_usage")
            return out
        except Exception as e:
            self._swallowed.swallow("list_usage", e)
            return None


def quota_headroom(quotas: List[ResourceQuota]) -> Dict[str, dict]:
    """Per-namespace headroom (hard - used per key) — the
    /debug/pending answer to 'which quota is blocking me'. Quantities
    render through str() so the report is JSON-serializable as-is."""
    out: Dict[str, dict] = {}
    tightest: Dict[tuple, Quantity] = {}
    for q in sorted(quotas, key=lambda q: q.metadata.key()):
        ns = q.metadata.namespace
        entry = out.setdefault(ns, {})
        for k in sorted(q.spec.hard):
            hard = q.spec.hard[k]
            used = q.status.used.get(k, Quantity(0))
            left = hard - used
            if left < Quantity(0):
                left = Quantity(0)
            prev = tightest.get((ns, k))
            # several quotas capping one key: report the tightest
            if prev is None or left < prev:
                tightest[(ns, k)] = left
                entry[k] = {"quota": q.metadata.name, "hard": str(hard),
                            "used": str(used), "free": str(left)}
    return out
