"""Multi-tenancy: ResourceQuota reconciliation, gang quota at the queue
gate, DRF fair share on the device scan, and PriorityClass bands.

Layer map (ISSUE 16):

  - quota.py     deterministic ResourceQuota reconciler + headroom report
                 (admission charges forward in apiserver/admission.py;
                 this controller is the source of truth that releases)
  - gangquota.py per-namespace active-gang slots enforced at the gang
                 manager's pop gate — whole PodGroups admitted or parked
                 as units, with the blocking quota named
  - drf.py       per-tenant usage carry + dominant-share kernel and its
                 numpy parity oracle; drain ordering and preemption
                 pricing terms (KTPU_DRF=0 is the measured control)
  - bands.py     PriorityClass-derived named bands replacing the single
                 lane threshold, with per-band SLO targets
  - metrics.py   QuotaMetrics / TenancyMetrics families
"""

from .bands import Band, BandCatalog, BEST_EFFORT, EXPRESS_ANNOTATION, \
    SLO_ANNOTATION
from .drf import DRFAccount, RESOURCES, TENANT_LABEL, \
    dominant_shares_reference, drf_enabled, drf_order_reference, tenant_of
from .gangquota import ACTIVE_GANGS_KEY, GangQuotaGate, QuotaBlock
from .metrics import QuotaMetrics, TenancyMetrics
from .quota import TenantQuotaController, quota_headroom

__all__ = [
    "ACTIVE_GANGS_KEY", "BEST_EFFORT", "Band", "BandCatalog",
    "DRFAccount", "EXPRESS_ANNOTATION", "GangQuotaGate", "QuotaBlock",
    "QuotaMetrics", "RESOURCES", "SLO_ANNOTATION", "TENANT_LABEL",
    "TenancyMetrics", "TenantQuotaController",
    "dominant_shares_reference", "drf_enabled", "drf_order_reference",
    "quota_headroom", "tenant_of",
]
