"""Per-namespace gang quota — whole PodGroups admitted or parked as units.

ResourceQuota's ``count/podgroups`` caps how many PodGroup OBJECTS a
namespace may create; it says nothing about how many gangs may be
in flight at once, and a tenant that creates its gangs early can still
interleave-starve everyone else at the scheduling queue. This gate
enforces the hard key ``scheduling.ktpu/active-gangs`` at the queue's
pop gate instead: an admissible gang (minMember reached) additionally
needs an active-gang slot in its namespace before its members may leave
the parked state. A gang denied a slot parks with its OWN attribution
reason (``QuotaExhausted``) so it never reads as a scheduler failure,
and the slot is returned when the gang's last member leaves the
manager's books (bound members terminal, pods deleted, or the gang
rolled back).

The gate is consulted UNDER the gang manager's lock (queue-lock ->
manager-lock is the documented order; the gate takes no lock of its
own beyond its internal one and never calls back into either).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set

from ..utils.errlog import SwallowedErrors

#: ResourceQuota hard key the gate enforces
ACTIVE_GANGS_KEY = "scheduling.ktpu/active-gangs"


@dataclass
class QuotaBlock:
    """Why a gang is parked: the blocking quota, named."""
    namespace: str
    resource: str
    quota: str
    used: int
    hard: int

    def reason(self) -> str:
        return "QuotaExhausted"

    def message(self, gkey: str) -> str:
        return (f"gang {gkey} parked: namespace '{self.namespace}' "
                f"{self.resource} quota exhausted "
                f"({self.used}/{self.hard} via quota "
                f"'{self.quota}')")


class GangQuotaGate:
    """Tracks active (admitted, not yet finished) gangs per namespace
    against the tightest ``scheduling.ktpu/active-gangs`` hard cap.

    ``quota_lister()`` returns the live ResourceQuota objects (an
    informer indexer list or a client list); namespaces carrying no
    such hard key are unlimited — the gate no-ops for them, the same
    contract the admission plugins keep for quota-less namespaces.
    """

    def __init__(self, quota_lister: Callable[[], list],
                 metrics=None):
        self._lister = quota_lister
        self.metrics = metrics
        self._swallowed = SwallowedErrors("gangquota")
        self._lock = threading.Lock()
        #: namespace -> active gang keys holding a slot
        self._active: Dict[str, Set[str]] = {}
        #: gang key -> namespace (release without re-parsing)
        self._held: Dict[str, str] = {}

    # ----------------------------------------------------------- limits

    def _limit(self, ns: str) -> Optional[tuple]:
        """(limit, quota name) — the tightest active-gangs cap in `ns`,
        or None when unlimited."""
        best = None
        try:
            quotas = self._lister()
            self._swallowed.ok("list_quotas")
        except Exception as e:
            # listing failed: fail open, admission still caps
            self._swallowed.swallow("list_quotas", e)
            return None
        for q in quotas:
            if q.metadata.namespace != ns:
                continue
            cap = q.spec.hard.get(ACTIVE_GANGS_KEY)
            if cap is None:
                continue
            val = int(float(str(cap)))
            if best is None or val < best[0]:
                best = (val, q.metadata.name)
        return best

    # ------------------------------------------------------ gate verbs

    def try_admit(self, gkey: str) -> Optional[QuotaBlock]:
        """Claim an active-gang slot for `gkey` (idempotent while
        held). None = admitted; a QuotaBlock = parked, with the
        blocking quota named."""
        ns, _, _ = gkey.partition("/")
        with self._lock:
            if gkey in self._held:
                return None
            lim = self._limit(ns)
            if lim is None:
                self._active.setdefault(ns, set()).add(gkey)
                self._held[gkey] = ns
                return None
            limit, qname = lim
            active = self._active.setdefault(ns, set())
            if len(active) >= limit:
                if self.metrics is not None:
                    self.metrics.gang_quota_parked.inc(namespace=ns)
                return QuotaBlock(namespace=ns,
                                  resource=ACTIVE_GANGS_KEY,
                                  quota=qname, used=len(active),
                                  hard=limit)
            active.add(gkey)
            self._held[gkey] = ns
            if self.metrics is not None:
                self.metrics.gang_quota_admitted.inc(namespace=ns)
            return None

    def release(self, gkey: str) -> bool:
        """Return `gkey`'s slot (no-op when it holds none). True when a
        slot was actually freed — the caller's cue to re-evaluate
        quota-parked gangs."""
        with self._lock:
            ns = self._held.pop(gkey, None)
            if ns is None:
                return False
            active = self._active.get(ns)
            if active is not None:
                active.discard(gkey)
                if not active:
                    del self._active[ns]
            return True

    def holds(self, gkey: str) -> bool:
        with self._lock:
            return gkey in self._held

    # -------------------------------------------------------- reporting

    def report(self) -> Dict[str, dict]:
        """Per-namespace active counts + the cap (for /debug/pending's
        quota headroom section)."""
        with self._lock:
            namespaces = sorted(set(self._active) | {
                q.metadata.namespace
                for q in self._safe_list()
                if ACTIVE_GANGS_KEY in q.spec.hard})
            out: Dict[str, dict] = {}
            for ns in namespaces:
                lim = self._limit(ns)
                active = sorted(self._active.get(ns, ()))
                out[ns] = {
                    "active": len(active),
                    "gangs": active,
                    "limit": lim[0] if lim is not None else None,
                    "quota": lim[1] if lim is not None else None,
                }
            return out

    def _safe_list(self) -> List:
        try:
            out = list(self._lister())
            self._swallowed.ok("list_quotas")
            return out
        except Exception as e:
            self._swallowed.swallow("list_quotas", e)
            return []
