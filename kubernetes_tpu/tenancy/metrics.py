"""Tenancy metric families: quota enforcement and DRF fair share.

Same contract as the families in utils/metrics.py — constructed over the
caller's registry so they ride the owning component's /metrics
exposition, with a private-registry fallback for standalone use; names
follow the prometheus conventions ktpulint enforces (counters end in
``_total``). Both classes are part of the registry-completeness gate
(tests/test_observability.py), so a family declared here but never
exposed fails CI.
"""

from __future__ import annotations

from typing import Optional

from ..utils.metrics import Registry


class QuotaMetrics:
    """ResourceQuota enforcement: admission rejections (the apiserver's
    view) and reconcile writes (the controller's view)."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: CREATEs denied by the quota validator, named by the namespace
        #: and the hard key that was exhausted
        self.admission_rejections = r.counter(
            "quota_admission_rejections_total",
            "Object creations denied by ResourceQuota admission, by "
            "namespace and exhausted resource")
        #: status.used writes the reconciler made (0 on a converged pass)
        self.reconcile_writes = r.counter(
            "quota_reconcile_writes_total",
            "ResourceQuota status writes by the reconciler, by namespace")


class TenancyMetrics:
    """DRF fair share and the gang-quota gate."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: each tenant's current dominant share (max over resources of
        #: usage/capacity), sampled at scheduler commit points
        self.dominant_share = r.gauge(
            "tenancy_dominant_share",
            "Dominant resource share per tenant (DRF)")
        #: gangs parked at the queue gate because their namespace's
        #: active-gang quota was exhausted
        self.gang_quota_parked = r.counter(
            "tenancy_gang_quota_parked_total",
            "Gangs parked for an exhausted active-gang quota, "
            "by namespace")
        self.gang_quota_admitted = r.counter(
            "tenancy_gang_quota_admitted_total",
            "Gangs granted an active-gang quota slot, by namespace")

    def sample_shares(self, account) -> None:
        """Refresh the per-tenant dominant-share gauge from a
        DRFAccount (called at scheduler commit points)."""
        rep = account.report()
        for tenant, rec in rep["tenants"].items():
            self.dominant_share.set(rec["dominant_share"], tenant=tenant)
