"""Dominant-resource fair share (DRF) across tenants, on the device scan.

Ghodsi et al.'s DRF assigns each tenant a *dominant share* — the maximum,
over resource kinds, of the tenant's usage divided by cluster capacity —
and a work-conserving fair scheduler serves the tenant with the LOWEST
dominant share first. This module carries that computation the way the
repo carries every scheduling decision: a per-tenant usage tensor
``[T, R]`` updated at each winner commit (one more carried tensor, like
the spread group counts that ride the class carry), a jitted kernel that
turns it into dominant shares and a drain ordering, and a serial numpy
mirror (``dominant_shares_reference`` / ``drf_order_reference``) in the
same parity-oracle role ``price_nodes_reference`` plays for preemption.

The account feeds two consumers:

  - **drain batch ordering** (``order_batch``): a popped batch is
    reordered (priority desc, dominant share asc, pop position) so
    pods of tenants furthest BELOW fair share tensorize first and win
    in-batch contention — priority still dominates (the express-lane
    contract is untouched), DRF only arbitrates within a band. The
    permutation is computed on device and is bit-identical to the
    numpy mirror (f32 arithmetic, same op order, position as the
    unique final sort key).
  - **preemption pricing** (``overshare_ranks``): tenants above fair
    share (1/T of every resource) get a quantized over-share rank; the
    victim tables sort those tenants' pods into a cheaper band, so a
    gang storm's own pods are priced first when capacity must be
    reclaimed.

``KTPU_DRF=0`` disables both consumers — today's priority-then-FIFO
drain and tenant-blind pricing stay byte-identical as the measured
control (the flag pattern of KTPU_CLASS_SCAN / KTPU_PREEMPT_KERNEL).

Charging is idempotent by pod key (charge at assume/bind, release at
terminal/delete/bind-failure), so replays and informer echoes can never
double-count a tenant.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import helpers
from ..api.core import Pod

#: the label a workload generator stamps tenants with; pods without it
#: fall back to their namespace (the reference's tenancy boundary)
TENANT_LABEL = "serving.ktpu/tenant"

#: resource columns of the usage tensor: cpu (milli), memory (bytes),
#: TPU devices (summed over tpu-suffixed extended resources)
RESOURCES: Tuple[str, ...] = ("cpu", "memory", "tpu")


def drf_enabled() -> bool:
    """KTPU_DRF=0 pins the drain to priority-then-FIFO and preemption
    to tenant-blind pricing — the measured control."""
    return os.environ.get("KTPU_DRF", "1") != "0"


def tenant_of(pod: Pod) -> str:
    """The pod's tenant: the explicit label, else its namespace."""
    return pod.metadata.labels.get(TENANT_LABEL) \
        or pod.metadata.namespace or "default"


def _pod_vec(pod: Pod) -> np.ndarray:
    """[R] f32 usage row for one pod (requests; max with init
    containers is immaterial at this granularity — the scan's own
    nodeinfo accounting stays the placement truth)."""
    from ..scheduler.nodeinfo import pod_resource
    r = pod_resource(pod)
    tpu = sum(v for k, v in r.scalar_resources.items()
              if k.endswith("tpu") or "/tpu" in k)
    return np.array([r.milli_cpu, r.memory, tpu], np.float32)


# ------------------------------------------------------------- kernels

#: jitted wrappers, cached per underlying function — a fresh jax.jit()
#: per call would recompile every invocation
_JITTED: dict = {}


def _jit(fn):
    j = _JITTED.get(fn)
    if j is None:
        import jax
        j = jax.jit(fn)
        _JITTED[fn] = j
    return j


def _dominant_kernel(usage, cap):
    """[T, R] usage + [R] capacity -> [T] dominant shares (jitted on
    first use; f32 divide then max, the reference mirror's op order)."""
    import jax.numpy as jnp
    shares = usage / jnp.maximum(cap, jnp.float32(1.0))
    return jnp.max(shares, axis=1)


def dominant_shares_reference(usage: np.ndarray,
                              cap: np.ndarray) -> np.ndarray:
    """Numpy mirror of the dominant-share kernel — same op order, f32
    throughout (the parity oracle)."""
    shares = usage.astype(np.float32) \
        / np.maximum(cap.astype(np.float32), np.float32(1.0))
    return np.max(shares, axis=1)


def _order_kernel(prio, share, pos):
    """[P] priorities + [P] per-pod dominant shares + [P] pop positions
    -> permutation: priority desc, share asc, position asc. Position is
    the unique final key, so the permutation never depends on sort
    stability."""
    import jax.numpy as jnp
    return jnp.lexsort((pos, share, -prio))


def drf_order_reference(prio: np.ndarray, share: np.ndarray,
                        pos: np.ndarray) -> np.ndarray:
    """Numpy mirror of the drain-order kernel (np.lexsort: last key is
    primary, identical key tuple)."""
    return np.lexsort((pos, share.astype(np.float32), -prio))


class DRFAccount:
    """The per-tenant usage ledger and its device-resident carry.

    Tenants are registered on first sight (index order is first-charge
    order, which is deterministic under the harnesses' sorted-key
    stepping); the usage tensor grows by doubling so the jitted kernels
    recompile O(log T) times. All mutation is under one lock — charges
    come from the commit path, releases from informer event handlers.
    """

    def __init__(self, mesh=None):
        self._lock = threading.Lock()
        self.mesh = mesh
        self._idx: Dict[str, int] = {}
        self._names: List[str] = []
        self._usage = np.zeros((4, len(RESOURCES)), np.float32)
        #: pod key -> (tenant index, charged [R] vector): idempotence
        #: and exact-release bookkeeping in one map
        self._charged: Dict[str, Tuple[int, np.ndarray]] = {}
        self._capacity = np.ones((len(RESOURCES),), np.float32)
        self._cap_nodes = -1  # node-count fingerprint of _capacity

    # ------------------------------------------------------- registry

    def tenant_index(self, tenant: str) -> int:
        i = self._idx.get(tenant)
        if i is None:
            i = len(self._names)
            self._idx[tenant] = i
            self._names.append(tenant)
            if i >= self._usage.shape[0]:
                grown = np.zeros((self._usage.shape[0] * 2,
                                  len(RESOURCES)), np.float32)
                grown[:self._usage.shape[0]] = self._usage
                self._usage = grown
        return i

    @property
    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._names)

    # ------------------------------------------------------- capacity

    def set_capacity(self, cap: Sequence[float]) -> None:
        with self._lock:
            self._capacity = np.asarray(cap, np.float32)
            self._cap_nodes = -2  # pinned: ensure_capacity won't overwrite

    def ensure_capacity(self, node_infos: Dict[str, object]) -> None:
        """Refresh cluster capacity from the snapshot's node set. Cheap
        re-entry guard: recompute only when the node COUNT changed
        (allocatable churn without add/remove is rare and self-corrects
        on the next topology change)."""
        with self._lock:
            if self._cap_nodes == -2 or len(node_infos) == self._cap_nodes:
                return
            cap = np.zeros((len(RESOURCES),), np.float32)
            for ni in node_infos.values():
                alloc = ni.allocatable
                cap[0] += alloc.milli_cpu
                cap[1] += alloc.memory
                cap[2] += sum(
                    v for k, v in alloc.scalar_resources.items()
                    if k.endswith("tpu") or "/tpu" in k)
            self._capacity = np.maximum(cap, np.float32(1.0))
            self._cap_nodes = len(node_infos)

    # ------------------------------------------------------ the ledger

    def charge(self, pod: Pod) -> None:
        """Winner commit: add the pod's vector to its tenant's row
        (no-op when this key is already charged)."""
        key = pod.metadata.key()
        with self._lock:
            if key in self._charged:
                return
            vec = _pod_vec(pod)
            t = self.tenant_index(tenant_of(pod))
            self._usage[t] += vec
            self._charged[key] = (t, vec)

    def release(self, pod: Pod) -> None:
        self.release_key(pod.metadata.key())

    def release_key(self, key: str) -> None:
        """Terminal phase / delete / failed bind: return the charged
        vector (exact — the vector that was charged, not a recompute)."""
        with self._lock:
            rec = self._charged.pop(key, None)
            if rec is None:
                return
            t, vec = rec
            self._usage[t] = np.maximum(
                self._usage[t] - vec, np.float32(0.0))

    # ------------------------------------------------------- consumers

    def _snapshot(self) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
        with self._lock:
            T = max(1, len(self._names))
            return (self._usage[:T].copy(), self._capacity.copy(),
                    dict(self._idx))

    def dominant_shares(self) -> np.ndarray:
        """[T] dominant shares via the device kernel (the usage carry is
        shipped under the 'tenant_usage' partition rule — replicated,
        tenant-leading; see scheduler/sharding.py)."""
        usage, cap, _ = self._snapshot()
        from ..scheduler import sharding
        u = sharding.put(self.mesh, "tenant_usage", usage)
        c = sharding.put(self.mesh, "tenant_capacity", cap)
        return np.asarray(_jit(_dominant_kernel)(u, c))

    def share_of(self, tenant: str) -> float:
        usage, cap, idx = self._snapshot()
        i = idx.get(tenant)
        if i is None or i >= usage.shape[0]:
            return 0.0
        return float(dominant_shares_reference(usage, cap)[i])

    #: below this batch size the numpy mirror runs instead of the device
    #: kernel — the permutation is identical (the parity contract), and
    #: a device round-trip per tiny batch costs more than it parallelizes
    DEVICE_FLOOR = 64

    def order_batch(self, pods: List[Pod]) -> List[Pod]:
        """Reorder a popped batch: priority desc (the express-lane
        contract), dominant share asc (tenants furthest below fair
        share first), pop position as the unique tie-break. Bit-
        identical to order_batch_reference over the same inputs."""
        if len(pods) < 2:
            return list(pods)
        if len(pods) < self.DEVICE_FLOOR:
            return self.order_batch_reference(pods)
        with self._lock:
            tidx = np.array([self.tenant_index(tenant_of(p))
                             for p in pods], np.int32)
            T = max(1, len(self._names))
            usage = self._usage[:T].copy()
            cap = self._capacity.copy()
        import jax.numpy as jnp
        from ..scheduler import sharding
        u = sharding.put(self.mesh, "tenant_usage", usage)
        c = sharding.put(self.mesh, "tenant_capacity", cap)
        shares = _jit(_dominant_kernel)(u, c)
        prio = np.array([helpers.pod_priority(p) for p in pods], np.int32)
        pos = np.arange(len(pods), dtype=np.int32)
        perm = np.asarray(_jit(_order_kernel)(
            jnp.asarray(prio), shares[tidx], jnp.asarray(pos)))
        return [pods[int(i)] for i in perm]

    def order_batch_reference(self, pods: List[Pod]) -> List[Pod]:
        """The serial numpy mirror of order_batch (parity surface)."""
        if len(pods) < 2:
            return list(pods)
        with self._lock:
            tidx = np.array([self.tenant_index(tenant_of(p))
                             for p in pods], np.int32)
            T = max(1, len(self._names))
            usage = self._usage[:T].copy()
            cap = self._capacity.copy()
        shares = dominant_shares_reference(usage, cap)[tidx]
        prio = np.array([helpers.pod_priority(p) for p in pods], np.int32)
        pos = np.arange(len(pods), dtype=np.int32)
        perm = drf_order_reference(prio, shares, pos)
        return [pods[int(i)] for i in perm]

    def overshare_ranks(self) -> Dict[str, int]:
        """tenant -> quantized rank ABOVE the equal fair share (1/T per
        resource); tenants at/below fair share are absent. The victim
        tables fold this into the eviction band order — integer
        quantization (1e6 steps) keeps the host sort exact."""
        usage, cap, idx = self._snapshot()
        if not idx:
            return {}
        shares = dominant_shares_reference(usage, cap)
        fair = np.float32(1.0) / np.float32(max(1, len(idx)))
        out: Dict[str, int] = {}
        for name, i in idx.items():
            q = int(round(float(shares[i] - fair) * 1_000_000))
            if q > 0:
                out[name] = q
        return out

    def report(self) -> dict:
        """Per-tenant usage/share snapshot for /debug/pending and the
        bench's isolation section."""
        usage, cap, idx = self._snapshot()
        shares = dominant_shares_reference(usage, cap)
        return {
            "capacity": {r: float(cap[i])
                         for i, r in enumerate(RESOURCES)},
            "tenants": {
                name: {
                    "dominant_share": round(float(shares[i]), 6),
                    "usage": {r: float(usage[i, j])
                              for j, r in enumerate(RESOURCES)},
                } for name, i in sorted(idx.items())},
        }
