"""Serving mode — open-loop churn with latency SLOs over the live
control plane.

    LoadGen        loadgen.py  — seeded Poisson arrivals of mixed
                                 workload classes (deployments scaling,
                                 jobs, cronjob firings, gangs, singletons)
    SLOTracker     slo.py      — created→bound→running stamps, exact
                                 per-class p50/p95/p99 + sustained pods/s
    ServingHarness harness.py  — the FakeClock-deterministic (or chaotic)
                                 control-plane driver tying them together

The scheduler-side half of serving mode lives in scheduler/scheduler.py
(adaptive drain batch sizing, priority lanes, hub backpressure —
`adaptive_batch=True`) and scheduler/queue.py (lane census). The bench
entry point is `bench.py` (serving section).
"""

from .loadgen import ArrivalEvent, CLASS_LABEL, DEFAULT_MIX, LoadGen
from .slo import BIND, STARTUP, SLOTracker, percentile
from .harness import ServingHarness, ServingReport

__all__ = ["ArrivalEvent", "CLASS_LABEL", "DEFAULT_MIX", "LoadGen",
           "BIND", "STARTUP", "SLOTracker", "percentile",
           "ServingHarness", "ServingReport"]
