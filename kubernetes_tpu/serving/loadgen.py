"""Open-loop churn load generator — seeded Poisson arrivals of mixed
workload classes through the real client/controller stack.

The reference's scheduler_perf measures a one-shot batch drain; a
production control plane is judged on SUSTAINED pod-startup latency under
continuous churn. This generator drives that regime: arrivals follow a
Poisson process (exponential inter-arrival gaps) whose schedule is a PURE
FUNCTION of (seed, rate, mix, n_events) — the chaos harness's determinism
contract applied to load. Applying the schedule consumes no randomness,
so two runs with one seed issue the identical create/patch stream and
(on the FakeClock harness) produce identical arrival and bind event logs.

Workload classes, each exercising a different controller path:

  singleton    a plain pod, straight into the scheduling queue
  priority     a singleton at/above the scheduler's lane priority — rides
               the serving drain's express lane
  gang         a PodGroup + minMember member pods (the coscheduling path)
  deployment   the FIRST event creates a Deployment; every later one is a
               SCALE event (replicas += delta) — the Deployment/ReplicaSet
               controllers materialize the pods
  job          a Job (parallelism == completions) — the Job controller
               creates the pods, and completions retire them
  cronjob      up to `max_cronjobs` CronJobs on a every-minute schedule —
               the CronJob controller fires Jobs as virtual time crosses
               minute boundaries

Open-loop means arrivals never wait on the system: a saturated scheduler
faces a growing queue, exactly the regime adaptive batch sizing and
backpressure are judged in.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.apps import Deployment, DeploymentSpec
from ..api.batch import CronJob, CronJobSpec, Job, JobSpec
from ..api.core import Container, Pod, PodSpec, PodTemplateSpec, \
    ResourceRequirements
from ..api.meta import LabelSelector, ObjectMeta
from ..api.quantity import Quantity
from ..api.scheduling import PodGroup, PodGroupSpec
from ..api.wellknown import LABEL_POD_GROUP
from ..utils.clock import Clock, REAL_CLOCK

#: the label every generated pod (template) carries; the SLO tracker
#: buckets its latency percentiles by this
CLASS_LABEL = "serving.ktpu/class"

#: the tenant label (shared with tenancy.drf.TENANT_LABEL) stamped when
#: the generator runs with tenants > 0 — the isolation bench's
#: attribution key
TENANT_LABEL = "serving.ktpu/tenant"

#: default class mix (weights; renormalized by random.choices)
DEFAULT_MIX: Tuple[Tuple[str, float], ...] = (
    ("singleton", 0.40), ("deployment", 0.20), ("job", 0.15),
    ("gang", 0.12), ("priority", 0.08), ("cronjob", 0.05))


@dataclass
class ArrivalEvent:
    """One scheduled arrival: `t` is the offset (seconds) from run start;
    `params` carries every random draw the event needs, so applying it is
    deterministic."""
    idx: int
    t: float
    cls: str
    params: Dict[str, int] = field(default_factory=dict)


class LoadGen:
    """Seeded open-loop generator. Usage:

        gen = LoadGen(client, seed=7, rate=50.0)
        gen.begin(gen.make_schedule(500))
        while not gen.done:
            gen.step()          # applies every event due at clock.now()
            ...                 # tick the control plane / sleep
    """

    def __init__(self, client, seed: int = 0, rate: float = 50.0,
                 mix=None, clock: Clock = REAL_CLOCK,
                 namespace: str = "default",
                 lane_priority: int = 1000,
                 cpu_m: int = 100, memory: str = "64Mi",
                 gang_sizes: Tuple[int, int] = (2, 4),
                 deploy_step: Tuple[int, int] = (1, 8),
                 job_sizes: Tuple[int, int] = (1, 4),
                 max_cronjobs: int = 2,
                 tenants: int = 0,
                 tenant_name: Optional[str] = None):
        self.client = client
        self.seed = seed
        self.rate = float(rate)
        self.mix = tuple(mix) if mix is not None else DEFAULT_MIX
        self.clock = clock
        self.namespace = namespace
        self.lane_priority = lane_priority
        self.cpu_m = cpu_m
        self.memory = memory
        self.gang_sizes = gang_sizes
        self.deploy_step = deploy_step
        self.job_sizes = job_sizes
        self.max_cronjobs = max_cronjobs
        #: > 0 stamps every workload with a seeded TENANT_LABEL; 0 (the
        #: default) draws nothing, so legacy schedules stay byte-identical
        self.tenants = int(tenants)
        #: a FIXED tenant label on everything this generator emits (the
        #: isolation bench's single-tenant abuser); overrides draws
        self.tenant_name = tenant_name
        #: the applied-arrival log — (idx, cls, object name) in apply
        #: order; identical across same-seed runs (the determinism
        #: surface the serving smoke asserts on)
        self.log: List[Tuple[int, str, str]] = []
        #: direct pod arrivals by class (controller-materialized pods are
        #: counted by the SLO tracker at observation instead)
        self.arrivals: Dict[str, int] = {}
        self._schedule: List[ArrivalEvent] = []
        self._next = 0
        self._start: Optional[float] = None
        self._counters: Dict[str, int] = {}
        self._deploy_name: Optional[str] = None
        self._cronjobs: List[str] = []

    # --------------------------------------------------------- schedule

    def make_schedule(self, n_events: int) -> List[ArrivalEvent]:
        """The run's arrival script: a pure function of
        (seed, rate, mix, n_events). String seeding is process-stable."""
        rng = random.Random(
            f"serving-loadgen:{self.seed}:{self.rate}:{n_events}")
        # tenant draws come from their OWN stream — a pure function of
        # (seed, n) — so turning tenants on never perturbs the arrival
        # times/classes, and tenants=0 draws nothing at all (byte-identical
        # legacy schedules)
        trng = random.Random(
            f"serving-loadgen-tenants:{self.seed}:{n_events}") \
            if self.tenants > 0 else None
        names = [c for c, _ in self.mix]
        weights = [w for _, w in self.mix]
        t = 0.0
        out: List[ArrivalEvent] = []
        for i in range(n_events):
            t += rng.expovariate(self.rate)
            cls = rng.choices(names, weights=weights)[0]
            params = {"size": rng.randint(*self.gang_sizes),
                      "delta": rng.randint(*self.deploy_step),
                      "par": rng.randint(*self.job_sizes)}
            if trng is not None:
                params["tenant"] = trng.randrange(self.tenants)
            out.append(ArrivalEvent(idx=i, t=t, cls=cls, params=params))
        return out

    def begin(self, schedule: Optional[List[ArrivalEvent]] = None,
              n_events: int = 200) -> None:
        self._schedule = schedule if schedule is not None \
            else self.make_schedule(n_events)
        self._next = 0
        self._start = self.clock.now()

    @property
    def done(self) -> bool:
        return self._start is not None and \
            self._next >= len(self._schedule)

    @property
    def horizon(self) -> float:
        """The last scheduled arrival's offset (seconds)."""
        return self._schedule[-1].t if self._schedule else 0.0

    def step(self) -> int:
        """Apply every event whose offset has passed. Returns the number
        applied (0 while the clock sits between arrivals)."""
        if self._start is None:
            raise RuntimeError("begin() first")
        elapsed = self.clock.now() - self._start
        applied = 0
        while self._next < len(self._schedule) \
                and self._schedule[self._next].t <= elapsed:
            ev = self._schedule[self._next]
            self._next += 1
            name = self._apply(ev)
            self.log.append((ev.idx, ev.cls, name))
            applied += 1
        return applied

    # --------------------------------------------------------- appliers

    def _apply(self, ev: ArrivalEvent) -> str:
        fn = getattr(self, f"_do_{ev.cls}")
        return fn(ev)

    def _name(self, prefix: str) -> str:
        n = self._counters.get(prefix, 0) + 1
        self._counters[prefix] = n
        return f"srv-{prefix}-{n}"

    def _tenant_labels(self, ev: ArrivalEvent) -> Dict[str, str]:
        """The event's tenant label ({} when tenants are off)."""
        if self.tenant_name is not None:
            return {TENANT_LABEL: self.tenant_name}
        k = ev.params.get("tenant")
        return {} if k is None else {TENANT_LABEL: f"tenant-{k}"}

    def _pod_template(self, cls: str, extra_labels=None) -> PodTemplateSpec:
        labels = {CLASS_LABEL: cls, "app": f"srv-{cls}"}
        if extra_labels:
            labels.update(extra_labels)
        return PodTemplateSpec(
            metadata=ObjectMeta(labels=labels),
            spec=PodSpec(containers=[Container(
                name="c", image="pause",
                resources=ResourceRequirements(requests={
                    "cpu": Quantity(f"{self.cpu_m}m"),
                    "memory": Quantity(self.memory)}))]))

    def _make_pod(self, name: str, cls: str, priority=None,
                  extra_labels=None) -> Pod:
        tmpl = self._pod_template(cls, extra_labels)
        pod = Pod(metadata=ObjectMeta(
            name=name, namespace=self.namespace,
            labels=dict(tmpl.metadata.labels)), spec=tmpl.spec)
        if priority is not None:
            pod.spec.priority = priority
        return pod

    def _count(self, cls: str, n: int = 1) -> None:
        self.arrivals[cls] = self.arrivals.get(cls, 0) + n

    def _do_singleton(self, ev: ArrivalEvent) -> str:
        name = self._name("solo")
        self.client.pods(self.namespace).create(self._make_pod(
            name, "singleton", extra_labels=self._tenant_labels(ev)))
        self._count("singleton")
        return name

    def _do_priority(self, ev: ArrivalEvent) -> str:
        name = self._name("pri")
        self.client.pods(self.namespace).create(self._make_pod(
            name, "priority", priority=self.lane_priority,
            extra_labels=self._tenant_labels(ev)))
        self._count("priority")
        return name

    def _do_gang(self, ev: ArrivalEvent) -> str:
        size = ev.params["size"]
        gname = self._name("gang")
        self.client.pod_groups(self.namespace).create(PodGroup(
            metadata=ObjectMeta(name=gname, namespace=self.namespace),
            spec=PodGroupSpec(min_member=size)))
        labels = {LABEL_POD_GROUP: gname, **self._tenant_labels(ev)}
        for i in range(size):
            self.client.pods(self.namespace).create(self._make_pod(
                f"{gname}-w{i}", "gang", extra_labels=labels))
        self._count("gang", size)
        return gname

    def _do_deployment(self, ev: ArrivalEvent) -> str:
        delta = ev.params["delta"]
        if self._deploy_name is None:
            # first event creates the deployment; every later one scales
            self._deploy_name = self._name("deploy")
            self.client.deployments(self.namespace).create(Deployment(
                metadata=ObjectMeta(name=self._deploy_name,
                                    namespace=self.namespace),
                spec=DeploymentSpec(
                    replicas=delta,
                    selector=LabelSelector(
                        match_labels={"app": "srv-deployment"}),
                    template=self._pod_template(
                        "deployment",
                        extra_labels=self._tenant_labels(ev)))))
            return self._deploy_name

        def scale(cur):
            cur.spec.replicas = (cur.spec.replicas or 0) + delta
            return cur
        self.client.deployments(self.namespace).patch(
            self._deploy_name, scale)
        return f"{self._deploy_name}+{delta}"

    def _do_job(self, ev: ArrivalEvent) -> str:
        par = ev.params["par"]
        name = self._name("job")
        self.client.jobs(self.namespace).create(Job(
            metadata=ObjectMeta(name=name, namespace=self.namespace),
            spec=JobSpec(parallelism=par, completions=par,
                         template=self._pod_template(
                             "job",
                             extra_labels=self._tenant_labels(ev)))))
        return name

    def _do_cronjob(self, ev: ArrivalEvent) -> str:
        if len(self._cronjobs) >= self.max_cronjobs:
            return "cron-cap"  # deterministic noop beyond the cap
        name = self._name("cron")
        # job_template is the serde dict form (the CronJob controller
        # decodes it per firing); round-trip a real Job for field parity
        from ..api import serde
        tmpl_job = Job(spec=JobSpec(
            parallelism=1, completions=1,
            template=self._pod_template(
                "cronjob", extra_labels=self._tenant_labels(ev))))
        job_tmpl = {"spec": json.loads(
            serde.to_json_str(tmpl_job)).get("spec", {})}
        self.client.resource(CronJob, self.namespace).create(CronJob(
            metadata=ObjectMeta(name=name, namespace=self.namespace),
            spec=CronJobSpec(schedule="* * * * *",
                             job_template=job_tmpl)))
        self._cronjobs.append(name)
        return name

    # -------------------------------------------------------- lifecycle

    def suspend_cronjobs(self) -> None:
        """Quiesce helper: stop future firings (a cron on an every-minute
        schedule would otherwise generate churn forever and the run could
        never converge)."""
        def suspend(cur):
            cur.spec.suspend = True
            return cur
        for name in self._cronjobs:
            try:
                self.client.resource(CronJob, self.namespace).patch(
                    name, suspend)
            except Exception:
                pass
