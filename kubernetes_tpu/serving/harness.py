"""ServingHarness — the open-loop churn soak over the live control plane.

Stands up scheduler + workload controllers (Deployment, ReplicaSet, Job,
CronJob) + virtual kubelets against one store on a shared FakeClock, and
drives a seeded LoadGen schedule through them synchronously — the chaos
harness's determinism recipe (settle informers between steps, every
control loop stepped from the single driver thread) applied to sustained
load instead of faults. Two runs with one seed produce the identical
arrival log AND bind event log, which is what makes a latency SLO
assertable in tier-1.

The scheduler runs the SERVING drain policy: adaptive batch sizing
(`adaptive_batch=True` — cap follows queue depth), priority lanes
(`priority`-class arrivals pop as small express batches), and hub
backpressure. `batch_cap_log` lands in the report so tests can assert the
sizing policy's shape.

Chaos composition (the `-m slow` soak): the same FaultInjector the chaos
harness uses rides the control plane's client — API error rates in-process,
or wire latency/resets/watch-drops in `http=True` mode — plus
`restart_scheduler()` mid-run. The InvariantChecker sweeps the settled end
state, and `stuck` lists any arrived pod that never bound and never went
terminal: under churn + faults the liveness bar is "every pod eventually
binds or terminally fails", not a latency number.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.core import Node, NodeCondition, Pod, ResourceQuota
from ..api.batch import CronJob, Job
from ..api.policy import PriorityClass
from ..api.apps import Deployment, ReplicaSet
from ..api.meta import ObjectMeta
from ..api.quantity import Quantity
from ..api.scheduling import PodGroup
from ..chaos.harness import settle_informers
from ..chaos.injector import ChaosClient, ChaosHTTPClient, FaultInjector
from ..chaos.invariants import InvariantChecker
from ..controllers.cronjob import CronJobController
from ..controllers.deployment import DeploymentController
from ..controllers.job import JobController
from ..controllers.replicaset import ReplicaSetController
from ..scheduler.scheduler import DEFAULT_LANE_PRIORITY, Scheduler
from ..state.client import Client
from ..state.informer import SharedInformerFactory
from ..state.store import NotFoundError, Store
from ..utils.clock import FakeClock, now_iso
from ..utils.metrics import RobustnessMetrics, ServingMetrics
from .loadgen import CLASS_LABEL, TENANT_LABEL, LoadGen
from .slo import SLOTracker


@dataclass
class ServingReport:
    seed: int
    ticks: int = 0
    #: the loadgen's applied-arrival log — identical across same-seed runs
    arrival_log: List[Tuple] = field(default_factory=list)
    #: the SLO tracker's bind observations — same determinism contract
    bind_log: List[Tuple] = field(default_factory=list)
    #: (queue_depth, lane_depth, pressure, cap) per sized drain cycle
    batch_caps: List[Tuple] = field(default_factory=list)
    slo: dict = field(default_factory=dict)
    #: per-tenant bind/startup percentiles (tenants > 0 or an abuser)
    tenant_slo: dict = field(default_factory=dict)
    #: per-priority-band bind p99 vs the band's SLO target
    band_slo: dict = field(default_factory=dict)
    violations: List[str] = field(default_factory=list)
    #: arrived-but-never-bound, non-terminal pods after quiescence
    stuck: List[str] = field(default_factory=list)
    pods_bound: int = 0
    scheduler_restarts: int = 0
    #: mid-churn store restarts (restart_store) and journal records torn
    store_restarts: int = 0
    records_torn: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.stuck


class ServingHarness:
    def __init__(self, seed: int = 0, nodes: int = 8, rate: float = 20.0,
                 mix=None, tick_s: float = 1.0,
                 batch_size: int = 256, min_batch: int = 8,
                 lane_priority: int = DEFAULT_LANE_PRIORITY,
                 job_run_ticks: int = 2,
                 node_cpu: str = "8", node_mem: str = "32Gi",
                 http: bool = False,
                 error_rate: float = 0.0,
                 reset_rate: float = 0.0,
                 latency_rate: float = 0.0,
                 latency_max: float = 0.002,
                 watch_drop_rate: float = 0.0,
                 autoscaler: bool = False,
                 autoscaler_cooldown: float = 60.0,
                 autoscaler_max_nodes: int = 64,
                 tenants: int = 0,
                 quotas: Optional[Dict[str, Dict[str, str]]] = None,
                 abuse_rate: float = 0.0,
                 abuse_namespace: str = "abuse",
                 abuse_gang_sizes: Tuple[int, int] = (3, 5),
                 gang_run_ticks: Optional[int] = None,
                 wal_path: Optional[str] = None):
        self.seed = seed
        self.n_nodes = nodes
        self.tick_s = tick_s
        self.job_run_ticks = job_run_ticks
        self.node_cpu = node_cpu
        self.node_mem = node_mem
        self.http = http
        self.clock = FakeClock()
        self.metrics = RobustnessMetrics()
        self.serving_metrics = ServingMetrics()
        # one tracer across scheduler + SLO tracker on the shared
        # FakeClock (every pod sampled): stage_breakdown on the recorder
        # yields EXACT per-stage latencies, deterministic per seed
        from ..observability import SpanTracer
        self.tracer = SpanTracer(clock=self.clock, pod_sample=1)
        self.injector = FaultInjector(
            seed=seed, error_rate=error_rate, metrics=self.metrics,
            reset_rate=reset_rate, latency_rate=latency_rate,
            latency_max=latency_max, watch_drop_rate=watch_drop_rate)
        #: journaled when wal_path is given — restart_store() can then
        #: WAL-replay (or tear) the store mid-churn, the serving-scale
        #: durability fault the resilience soak composes with wire chaos
        self.wal_path = wal_path
        store = Store(wal_path=wal_path, metrics=self.metrics)
        #: fault-free admin view: workload creation (the loadgen) and
        #: virtual-kubelet writes stay stable so the run's INPUT is a
        #: pure function of the seed; only the control plane's handling
        #: of load (and faults) is under test
        self.admin = Client(store)
        self._server = None
        if http:
            from ..apiserver.httpclient import HTTPClient
            from ..apiserver.server import APIServer
            self._server = APIServer(store=store).start()
            self.client = ChaosHTTPClient(
                self.injector,
                HTTPClient(self._server.address,
                           wire_hook=self.injector.make_wire_hook()))
        else:
            self.client = ChaosClient(self.injector, store=store)
        self.factory = SharedInformerFactory(self.client)
        self._sched_factory = SharedInformerFactory(self.client)
        self.batch_size = batch_size
        self.min_batch = min_batch
        self.lane_priority = lane_priority
        self.scheduler = self._build_scheduler(self._sched_factory)
        self._build_controllers(self.factory)
        self.loadgen = LoadGen(self.admin, seed=seed, rate=rate, mix=mix,
                               clock=self.clock,
                               lane_priority=lane_priority,
                               tenants=tenants)
        self.serving_metrics.arrival_rate.set(rate)
        self.tracker = SLOTracker(clock=self.clock,
                                  metrics=self.serving_metrics,
                                  tracer=self.tracer)
        # ---- multi-tenancy (tenancy/) ----
        #: ResourceQuotas to create at start(): namespace -> hard caps
        #: (quantity strings); an `scheduling.ktpu/active-gangs` key caps
        #: that namespace's concurrent gangs at the queue gate
        self.quotas = dict(quotas or {})
        #: deterministic status.used reconciler, stepped per tick
        from ..tenancy import TenantQuotaController
        self.quota_controller = TenantQuotaController(self.admin) \
            if self.quotas else None
        #: gang-class pods retire after this many running ticks (None =
        #: never, the legacy behavior) — with an active-gang quota the
        #: gate's slots must recycle or the backlog can never converge
        self.gang_run_ticks = gang_run_ticks
        #: the abusive tenant: a second generator flooding gangs into its
        #: own namespace (namespace-as-tenant for DRF attribution)
        self.abuser = None
        if abuse_rate > 0:
            self.abuser = LoadGen(
                self.admin, seed=seed + 7919, rate=abuse_rate,
                mix=(("gang", 1.0),), clock=self.clock,
                namespace=abuse_namespace,
                gang_sizes=abuse_gang_sizes,
                tenant_name=abuse_namespace)
        #: per-tenant latency attribution (the isolation bench's surface)
        self.tenant_tracker = None
        if tenants > 0 or self.abuser is not None:
            self.tenant_tracker = SLOTracker(clock=self.clock,
                                             class_label=TENANT_LABEL)
        self._running_since: Dict[str, int] = {}
        self._tick_idx = 0
        self._started = False
        #: swallow control-loop exceptions only when the run actually
        #: injects faults (or rides a real wire) — a FAULT-FREE in-process
        #: run must fail fast at the real error, not minutes later as
        #: "stuck pods" with no traceback
        self._swallow_errors = http or any(
            r > 0 for r in (error_rate, reset_rate, latency_rate,
                            watch_drop_rate))
        #: carried across scheduler restarts (the log lives on the shell)
        self._batch_caps: List[Tuple] = []
        #: gang-aware capacity management under sustained load: same
        #: deterministic stepping contract as the chaos harness
        self.autoscaler = None
        self._ca_factory = None
        if autoscaler:
            from ..autoscaler import ClusterAutoscaler, \
                scheduler_demand_source
            self._ca_factory = SharedInformerFactory(self.client)
            self.autoscaler = ClusterAutoscaler(
                self.client, self._ca_factory,
                demand_source=scheduler_demand_source(
                    lambda: self.scheduler),
                clock=self.clock, cooldown=autoscaler_cooldown,
                max_nodes=autoscaler_max_nodes,
                node_cpu=self.node_cpu, node_mem=self.node_mem,
                robustness=self.metrics,
                # virtual kubelets own heartbeats in the harness
                maintain_heartbeats=False)

    # ------------------------------------------------------------ build

    def _build_scheduler(self, factory: SharedInformerFactory) -> Scheduler:
        # async_bind=False: the driver steps synchronously — binder-thread
        # timing would break the identical-bind-log contract
        return Scheduler(self.client, informer_factory=factory,
                         batch_size=self.batch_size, clock=self.clock,
                         async_bind=False, adaptive_batch=True,
                         min_batch=self.min_batch,
                         lane_priority=self.lane_priority,
                         tracer=self.tracer)

    def _build_controllers(self, factory: SharedInformerFactory) -> None:
        self.deployments = DeploymentController(self.client, factory)
        self.replicasets = ReplicaSetController(self.client, factory)
        self.jobs = JobController(self.client, factory)
        self.cronjobs = CronJobController(self.client, factory,
                                          clock=self.clock)

    def _factories(self) -> List[SharedInformerFactory]:
        extra = [self._ca_factory] if self._ca_factory is not None else []
        return [self.factory, self._sched_factory] + extra

    def start(self) -> None:
        if self._started:
            return
        for i in range(self.n_nodes):
            alloc = {"cpu": Quantity(self.node_cpu),
                     "memory": Quantity(self.node_mem),
                     "pods": Quantity("110")}
            node = Node(metadata=ObjectMeta(name=f"node-{i}"))
            node.status.capacity = dict(alloc)
            node.status.allocatable = dict(alloc)
            node.status.conditions = [NodeCondition(
                type="Ready", status="True", reason="KubeletReady",
                last_heartbeat_time=now_iso(self.clock))]
            self.admin.nodes().create(node)
        from ..api.core import ResourceQuotaSpec
        for ns in sorted(self.quotas):
            self.admin.resource_quotas(ns).create(ResourceQuota(
                metadata=ObjectMeta(name=f"quota-{ns}", namespace=ns),
                spec=ResourceQuotaSpec(hard={
                    k: Quantity(v) for k, v
                    in sorted(self.quotas[ns].items())})))
        for fac in self._factories():
            fac.start()
            fac.wait_for_cache_sync()
        self._settle()
        self._started = True

    def close(self) -> None:
        for fac in self._factories():
            fac.stop()
        if self._server is not None:
            self._server.stop()
        self.admin.store.close()

    def restart_scheduler(self) -> None:
        """Crash-replace the scheduler mid-churn: cache, assumed pods and
        adaptive-drain state die with it; the replacement rebuilds from a
        fresh informer sync while arrivals keep coming."""
        self.injector.record("restart_scheduler")
        self._batch_caps.extend(self.scheduler.batch_cap_log)
        self._sched_factory.stop()
        self.scheduler.crash()
        self._sched_factory = SharedInformerFactory(self.client)
        self.scheduler = self._build_scheduler(self._sched_factory)
        self._sched_factory.start()
        self._sched_factory.wait_for_cache_sync()
        self._settle()

    def restart_store(self, torn: int = 0) -> int:
        """WAL-replay the store in place mid-churn (the etcd-restart
        analog under sustained load): live watch streams sever, informers
        resume or relist, and with `torn=N` the last N journal records
        are LOST first — the rv clock regresses and bound pods whose
        binds were in the torn tail come back Pending. No-op without a
        wal_path. Returns the records actually torn."""
        if self.wal_path is None:
            return 0
        actual = self.admin.store.restart(torn=torn)
        if torn > 0:
            self.injector.tear_wal(actual)
        self.injector.record("restart_store")
        self._settle()
        return actual

    # -------------------------------------------------------------- run

    def run(self, n_events: int = 200, max_ticks: int = 600,
            quiesce_ticks: int = 40,
            restart_scheduler_at: Optional[int] = None,
            restart_store_at: Optional[int] = None,
            store_torn: int = 0,
            abuse_events: int = 0) -> ServingReport:
        """Drive the full schedule, then quiesce (cronjobs suspended,
        faults off) until every arrived pod is bound or terminal (or
        max_ticks). Returns the report with the determinism surfaces and
        the settled SLO."""
        self.start()
        report = ServingReport(seed=self.seed)
        self.loadgen.begin(self.loadgen.make_schedule(n_events))
        if self.abuser is not None and abuse_events > 0:
            self.abuser.begin(self.abuser.make_schedule(abuse_events))
        quiesced = False
        quiesce_left = quiesce_ticks
        while self._tick_idx < max_ticks:
            self.injector.advance(self._tick_idx)
            if restart_scheduler_at is not None \
                    and self._tick_idx == restart_scheduler_at:
                self.restart_scheduler()
                report.scheduler_restarts += 1
            if restart_store_at is not None \
                    and self._tick_idx == restart_store_at \
                    and self.wal_path is not None:
                report.records_torn += self.restart_store(torn=store_torn)
                report.store_restarts += 1
            self._tick()
            if self.loadgen.done and self._abuser_done() and not quiesced:
                # quiesce: no new arrivals, future cron firings off,
                # faults off — the backlog must now converge on its own
                quiesced = True
                self.loadgen.suspend_cronjobs()
                self.injector.error_rate = 0.0
                self.injector.reset_rate = 0.0
                self.injector.latency_rate = 0.0
                self.injector.watch_drop_rate = 0.0
            elif quiesced:
                quiesce_left -= 1
                if quiesce_left <= 0 and not self._unconverged():
                    break
        report.ticks = self._tick_idx
        report.arrival_log = list(self.loadgen.log)
        report.bind_log = list(self.tracker.bind_log)
        report.batch_caps = self._batch_caps + \
            list(self.scheduler.batch_cap_log)
        report.slo = self.tracker.report()
        if self.tenant_tracker is not None:
            report.tenant_slo = self.tenant_tracker.report()
        report.band_slo = self.tracker.band_report(self.scheduler.bands)
        report.stuck = self._stuck_pods()
        report.pods_bound = sum(
            1 for p in self.admin.pods().list(namespace=None)
            if p.spec.node_name)
        checker = InvariantChecker(self.admin, scheduler=self.scheduler,
                                   wal_path=self.wal_path)
        report.violations = checker.check()
        return report

    def _abuser_done(self) -> bool:
        return self.abuser is None or self.abuser._start is None \
            or self.abuser.done

    def _unconverged(self) -> bool:
        return bool(self._stuck_pods())

    def _stuck_pods(self) -> List[str]:
        return sorted(
            p.metadata.key()
            for p in self.admin.pods().list(namespace=None)
            if not p.spec.node_name
            and p.status.phase not in ("Succeeded", "Failed")
            and p.metadata.deletion_timestamp is None)

    # ------------------------------------------------------------- tick

    def _tick(self) -> None:
        """One serving step: arrivals land, controllers reconcile,
        the scheduler drains one adaptive cycle, kubelets report, the
        tracker observes — each stage settled so the next reads a
        deterministic view."""
        self.loadgen.step()
        if self.abuser is not None and self.abuser._start is not None:
            self.abuser.step()
        self._settle()
        self._controllers_pass()
        if self.quota_controller is not None:
            # after the workload controllers (their pods exist), before
            # the drain: status.used reflects this tick's arrivals
            try:
                self.quota_controller.sync_all()
            except Exception:
                if not self._swallow_errors:
                    raise
            self._settle()
        try:
            self.scheduler.schedule_pending(timeout=0)
        except Exception:
            if not self._swallow_errors:
                raise
            # an injected fault mid-cycle: retries next tick
        self.scheduler.cache.cleanup_expired_assumed_pods()
        self._settle()
        if self.autoscaler is not None:
            # after the drain so demand reflects this tick's failed
            # attempts; step() swallows-and-counts its own API faults
            self.autoscaler.step()
            self._settle()
        self._virtual_kubelets()
        self._settle()
        # deterministic SLO observation: the settled store, sorted keys
        pods = self.admin.pods().list(namespace=None)
        self.tracker.scan(pods)
        if self.tenant_tracker is not None:
            self.tenant_tracker.scan(pods)
        self.clock.step(self.tick_s)
        self._tick_idx += 1

    def _controllers_pass(self) -> None:
        """Run every workload control loop once, synchronously, in
        sorted-key order (their workqueue worker threads are never
        started — the driver thread IS the worker, which is what makes
        the pass deterministic). Cron fires before Job so a new minute's
        Job is acted on this tick."""
        try:
            self.cronjobs.sync_all()
        except Exception:
            if not self._swallow_errors:
                raise
        self._settle()
        for ctrl, cls in ((self.deployments, Deployment),
                          (self.replicasets, ReplicaSet),
                          (self.jobs, Job)):
            informer = self.factory.informer_for(cls)
            for key in sorted(o.metadata.key()
                              for o in informer.indexer.list()):
                try:
                    ctrl.sync(key)
                except Exception:
                    if not self._swallow_errors:
                        raise
                    # chaos mid-sync: the next tick re-syncs
            self._settle()

    def _virtual_kubelets(self) -> None:
        """Bound pods go Running; finite workloads (job/cronjob class)
        Succeed after job_run_ticks so Jobs complete and churn includes
        COMPLETIONS, not just arrivals."""
        for pod in sorted(self.admin.pods().list(namespace=None),
                          key=lambda p: p.metadata.key()):
            key = pod.metadata.key()
            if not pod.spec.node_name or \
                    pod.status.phase in ("Succeeded", "Failed"):
                continue
            cls = pod.metadata.labels.get(CLASS_LABEL, "")
            if pod.status.phase != "Running":
                def run_status(cur):
                    if cur.status.phase in ("Succeeded", "Failed"):
                        return cur
                    cur.status.phase = "Running"
                    if not cur.status.start_time:
                        cur.status.start_time = now_iso(self.clock)
                    return cur
                try:
                    self.admin.pods(pod.metadata.namespace).patch(
                        pod.metadata.name, run_status)
                except NotFoundError:
                    continue
                self._running_since[key] = self._tick_idx
            elif (cls in ("job", "cronjob")
                  or (cls == "gang" and self.gang_run_ticks is not None)
                  ) and \
                    self._tick_idx - self._running_since.get(
                        key, self._tick_idx) >= (
                        self.gang_run_ticks if cls == "gang"
                        else self.job_run_ticks):
                def done_status(cur):
                    if cur.status.phase == "Running":
                        cur.status.phase = "Succeeded"
                    return cur
                try:
                    self.admin.pods(pod.metadata.namespace).patch(
                        pod.metadata.name, done_status)
                except NotFoundError:
                    pass

    # ------------------------------------------------------------ settle

    #: resource classes the settling contract gates on — everything a
    #: serving control loop reads (only informers a factory actually
    #: created are compared; see chaos.harness.informers_current).
    #: ResourceQuota rides along since the scheduler's gang-quota gate
    #: and band catalog read their informers at pop time.
    _SETTLE_CLASSES = (Pod, Node, PodGroup, Deployment, ReplicaSet, Job,
                       CronJob, ResourceQuota, PriorityClass)

    def _settle(self, timeout: float = 10.0) -> None:
        """The chaos harness's settling contract over the serving
        resource classes — control-loop inputs identical across
        same-seed runs."""
        settle_informers(self.admin, self._factories(),
                         self._SETTLE_CLASSES, self.injector,
                         timeout=timeout, logger_name="serving",
                         step=self._tick_idx)
