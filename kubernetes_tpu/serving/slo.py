"""SLO tracker — pod creation→bound→running latency, per workload class.

Stamps the three lifecycle transitions every serving-mode pod makes:

    created   the pod object exists (arrival)
    bound     spec.nodeName set (the scheduler's decision landed)
    running   status.phase == Running (the kubelet started it)

and reports exact per-class percentiles (p50/p95/p99) of bind latency
(created→bound) and startup latency (created→running), plus the sustained
bound-pods/s rate — the serving analog of the reference's density-e2e
pod-startup SLO (its p99 ≤ 5s gate is judged on exactly this transition).

Two observation modes:

  - watch-driven (wall clock, the bench): attach `handlers()` to a pod
    informer; timestamps prefer the OBJECT's own stamps
    (metadata.creationTimestamp, the PodScheduled condition,
    status.startTime) so an observer thread lagging a burst's event
    backlog charges nothing to the cluster — the lesson the density
    bench's latency phase already encodes.
  - scan-driven (FakeClock, tier-1 determinism): call `scan(pods)` at a
    settled point each tick; transitions are stamped with the shared
    virtual clock and pods are visited in sorted-key order, so the bind
    log is identical across same-seed runs (object timestamps are wall
    clock and would break that).

Percentiles are EXACT (nearest-rank over the stored samples, not
histogram-bucket approximations) so a scalar replay of the samples must
reproduce them bit-for-bit — pinned by the serving smoke test.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..observability.tracer import nearest_rank_percentile
from ..state.informer import EventHandlers
from ..utils.clock import Clock, REAL_CLOCK, parse_iso
from .loadgen import CLASS_LABEL

#: transition kinds report() summarizes
BIND = "bind"        # created -> bound
STARTUP = "startup"  # created -> running


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile over a SORTED sample list — the scalar
    definition the smoke test replays against report(). Delegates to the
    ONE shared implementation (observability.tracer) so the SLO report
    and the span stage reports can never drift apart."""
    return nearest_rank_percentile(samples, q)


class SLOTracker:
    def __init__(self, clock: Clock = REAL_CLOCK, metrics=None,
                 class_label: str = CLASS_LABEL,
                 use_object_timestamps: bool = False,
                 tracer=None):
        self.clock = clock
        self.metrics = metrics
        self.class_label = class_label
        self.use_object_timestamps = use_object_timestamps
        #: observability.SpanTracer (optional): lifecycle transitions also
        #: land as pod spans (created/bound/running) so the flight
        #: recorder holds the kubelet-Running leg of each pod's trace
        self.tracer = tracer
        self._lock = threading.Lock()
        self._created: Dict[str, float] = {}
        self._bound: Dict[str, float] = {}
        self._running: Dict[str, float] = {}
        self._cls: Dict[str, str] = {}
        #: effective priority at first sight — band_report() buckets bind
        #: latency through a tenancy.BandCatalog with this
        self._prio: Dict[str, int] = {}
        #: (pod key, node) in first-observation order — with scan-driven
        #: observation this is the run's deterministic bind event log
        self.bind_log: List[Tuple[str, str]] = []
        self._t0: Optional[float] = None

    # ------------------------------------------------------ observation

    def handlers(self) -> EventHandlers:
        """Informer wiring for the watch-driven (wall-clock) mode."""
        return EventHandlers(on_add=self.observe,
                             on_update=lambda old, new: self.observe(new))

    def scan(self, pods) -> None:
        """Deterministic observation: visit a settled pod listing in
        sorted-key order (FakeClock mode)."""
        for pod in sorted(pods, key=lambda p: p.metadata.key()):
            self.observe(pod)

    def observe(self, pod) -> None:
        """Record any transition this pod object evidences (idempotent
        per phase; a pod is stamped once per transition, first sight
        wins)."""
        key = pod.metadata.key()
        now = self.clock.now()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            if key not in self._created:
                self._created[key] = self._stamp_created(pod, now)
                self._cls[key] = pod.metadata.labels.get(
                    self.class_label, "other")
                from ..api.helpers import pod_priority
                self._prio[key] = pod_priority(pod)
                if self.metrics is not None:
                    self.metrics.pods_observed.inc(
                        cls=self._cls[key], phase="created")
                if self.tracer is not None:
                    self.tracer.pod_event("lifecycle", "created", pod)
            cls = self._cls[key]
            if pod.spec.node_name and key not in self._bound:
                self._bound[key] = self._stamp_bound(pod, now)
                self.bind_log.append((key, pod.spec.node_name))
                if self.metrics is not None:
                    self.metrics.pods_observed.inc(cls=cls, phase="bound")
                    self.metrics.pod_bind_seconds.observe(
                        max(0.0, self._bound[key] - self._created[key]),
                        cls=cls)
                if self.tracer is not None:
                    self.tracer.pod_event("lifecycle", "bound", pod,
                                          node=pod.spec.node_name)
            if pod.status.phase == "Running" and key not in self._running:
                self._running[key] = self._stamp_running(pod, now)
                if self.metrics is not None:
                    self.metrics.pods_observed.inc(cls=cls,
                                                   phase="running")
                    self.metrics.pod_startup_seconds.observe(
                        max(0.0, self._running[key] - self._created[key]),
                        cls=cls)
                if self.tracer is not None:
                    self.tracer.pod_event("lifecycle", "running", pod)

    def _stamp_created(self, pod, now: float) -> float:
        if self.use_object_timestamps:
            t = parse_iso(pod.metadata.creation_timestamp or "")
            if t is not None:
                return t
        return now

    def _stamp_bound(self, pod, now: float) -> float:
        if self.use_object_timestamps:
            for cond in pod.status.conditions:
                if cond.type == "PodScheduled" and cond.status == "True":
                    t = parse_iso(cond.last_transition_time or "")
                    if t is not None:
                        return t
        return now

    def _stamp_running(self, pod, now: float) -> float:
        if self.use_object_timestamps:
            t = parse_iso(pod.status.start_time or "")
            if t is not None:
                return t
        return now

    # --------------------------------------------------------- reporting

    def samples(self, kind: str) -> Dict[str, List[float]]:
        """Per-class latency samples for one transition kind, each list
        sorted ascending — the raw material report() summarizes (and the
        smoke test's scalar-replay surface)."""
        ends = self._bound if kind == BIND else self._running
        with self._lock:
            out: Dict[str, List[float]] = {}
            for key, t_end in ends.items():
                out.setdefault(self._cls[key], []).append(
                    max(0.0, t_end - self._created[key]))
            for v in out.values():
                v.sort()
            return out

    def report(self) -> dict:
        """Per-class p50/p95/p99 for bind and startup latency, counts,
        and the sustained bound rate over the observation window."""
        with self._lock:
            elapsed = (self.clock.now() - self._t0) if self._t0 else 0.0
            n_created = len(self._created)
            n_bound = len(self._bound)
            n_running = len(self._running)
        classes: dict = {}
        for kind in (BIND, STARTUP):
            for cls, vals in self.samples(kind).items():
                entry = classes.setdefault(cls, {})
                entry[kind] = {
                    "count": len(vals),
                    "p50_s": round(percentile(vals, 0.50), 6),
                    "p95_s": round(percentile(vals, 0.95), 6),
                    "p99_s": round(percentile(vals, 0.99), 6),
                    "mean_s": round(sum(vals) / len(vals), 6),
                    "max_s": round(vals[-1], 6),
                }
        return {
            "created": n_created, "bound": n_bound, "running": n_running,
            "window_s": round(elapsed, 3),
            "sustained_bound_per_s": round(n_bound / elapsed, 2)
            if elapsed > 0 else 0.0,
            "classes": classes,
        }

    def band_report(self, catalog) -> dict:
        """Per-band bind latency vs. the band's SLO target: each bound
        pod falls into the catalog band its recorded priority reaches
        (tenancy.BandCatalog.band_of), and a band carrying a
        slo_p99_bind_s target reports whether its observed p99 met it.
        Bands with no bound pods are omitted."""
        with self._lock:
            per_band: Dict[str, List[float]] = {}
            for key, t_end in self._bound.items():
                band = catalog.band_of(self._prio.get(key, 0))
                per_band.setdefault(band.name, []).append(
                    max(0.0, t_end - self._created[key]))
        out: dict = {}
        for band in catalog.bands:
            vals = sorted(per_band.get(band.name, []))
            if not vals:
                continue
            p99 = percentile(vals, 0.99)
            entry = {
                "count": len(vals),
                "priority_floor": band.value,
                "p50_s": round(percentile(vals, 0.50), 6),
                "p99_s": round(p99, 6),
            }
            if band.slo_p99_bind_s is not None:
                entry["slo_p99_bind_s"] = band.slo_p99_bind_s
                entry["slo_met"] = bool(p99 <= band.slo_p99_bind_s)
            out[band.name] = entry
        return out

    def unfinished(self) -> List[str]:
        """Pods observed created but never bound — the liveness surface
        the chaos soak checks ('no pod permanently stuck')."""
        with self._lock:
            return sorted(k for k in self._created if k not in self._bound)

    #: (stage, (from milestone, to milestone)) pairs stage_breakdown cuts
    #: a pod's span trail into — milestones are span names across the
    #: queue/scheduler/kubelet/lifecycle components
    STAGES = (
        ("queue_wait", ("admit", "drain_member")),
        ("schedule_to_bound", ("drain_member", "bound")),
        ("bound_to_running", ("bound", "running")),
        ("e2e", ("admit", "running")),
    )

    @classmethod
    def stage_breakdown(cls, recorder) -> dict:
        """EXACT per-stage latency percentiles from a flight recorder's
        pod spans: each sampled pod's trace is cut at its first 'admit',
        'drain_member', 'bound', and 'running' milestones (emitted by the
        queue, the drain, and the kubelet/lifecycle observers), giving
        the stage-level answer the SLO's aggregate bind/startup
        percentiles can't: WHERE a slow pod spent its time."""
        marks: Dict[str, Dict[str, float]] = {}
        for span in recorder.spans():
            if not span.trace_id:
                continue
            d = marks.setdefault(span.trace_id, {})
            if span.name not in d:  # first sighting wins (re-queues keep
                d[span.name] = span.end  # the original admit stamp)
        out: dict = {}
        for stage, (a, b) in cls.STAGES:
            vals = sorted(m[b] - m[a] for m in marks.values()
                          if a in m and b in m and m[b] >= m[a])
            if not vals:
                continue
            out[stage] = {
                "count": len(vals),
                "p50_s": round(percentile(vals, 0.50), 6),
                "p95_s": round(percentile(vals, 0.95), 6),
                "p99_s": round(percentile(vals, 0.99), 6),
            }
        return out
