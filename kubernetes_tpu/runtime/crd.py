"""CustomResourceDefinitions — dynamic resource registration.

Ref: staging/src/k8s.io/apiextensions-apiserver/pkg/apiserver/
customresource_handler.go (crdHandler serving CR CRUD straight out of a
generic store once a CRD names the resource) and pkg/apis/apiextensions
types. Reduced: no OpenAPI schema validation, no conversion webhooks, one
served version — a CR is metadata + free-form spec/status dicts.

The tpu-native twist is architectural: the reference spins up a separate
apiextensions-apiserver and aggregates it; here the Scheme IS the serving
table, so registration is `type()`-ing a DynamicResource subclass per CRD
and adding it to the scheme — every existing layer (store buckets, watch,
informers, HTTP routing, kubectl) then serves the new kind with zero
special cases. WAL replay re-registers CRDs as it encounters them so CR
instance records later in the log decode (state/store.py _replay_wal).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Type

from ..api.meta import ObjectMeta


@dataclass
class CustomResourceDefinitionNames:
    plural: str = ""
    singular: str = ""
    kind: str = ""
    list_kind: str = ""
    short_names: List[str] = field(default_factory=list)


@dataclass
class CustomResourceDefinitionVersion:
    name: str = "v1"
    served: bool = True
    storage: bool = True


@dataclass
class CustomResourceDefinitionSpec:
    group: str = ""
    names: CustomResourceDefinitionNames = field(
        default_factory=CustomResourceDefinitionNames)
    scope: str = "Namespaced"  # Namespaced | Cluster
    #: empty means one served+storage "v1" (storage_version's fallback) —
    #: a non-empty default would break encode/decode round-tripping of []
    versions: List[CustomResourceDefinitionVersion] = field(
        default_factory=list)


@dataclass
class CustomResourceDefinitionCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class CustomResourceDefinitionStatus:
    accepted_names: CustomResourceDefinitionNames = field(
        default_factory=CustomResourceDefinitionNames)
    conditions: List[CustomResourceDefinitionCondition] = field(
        default_factory=list)


@dataclass
class CustomResourceDefinition:
    api_version: str = "apiextensions.k8s.io/v1"
    kind: str = "CustomResourceDefinition"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CustomResourceDefinitionSpec = field(
        default_factory=CustomResourceDefinitionSpec)
    status: CustomResourceDefinitionStatus = field(
        default_factory=CustomResourceDefinitionStatus)


@dataclass
class DynamicResource:
    """The schema-less custom object: typed metadata, free-form payload.
    One subclass is `type()`-generated per CRD so the scheme's cls-keyed
    tables stay unambiguous."""
    api_version: str = ""
    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


def storage_version(crd: CustomResourceDefinition) -> str:
    for v in crd.spec.versions:
        if v.storage:
            return v.name
    return crd.spec.versions[0].name if crd.spec.versions else "v1"


def validate_crd(crd: CustomResourceDefinition, scheme=None) -> None:
    """Field checks a CRD must pass before it may land in the store OR
    register a type (callers run this before either side effect so a
    failure leaves nothing half-done). With a scheme, also checks the
    plural is free — register_crd would reject it after the store write."""
    names = crd.spec.names
    if not (crd.spec.group and names.plural and names.kind):
        raise ValueError(
            "CRD needs spec.group, spec.names.plural and spec.names.kind")
    if scheme is not None:
        holder = scheme.type_for_resource(names.plural)
        if holder is not None and \
                getattr(holder, "_crd_group", None) != crd.spec.group:
            raise ValueError(
                f"resource {names.plural!r} is already registered")


def register_crd(crd: CustomResourceDefinition, scheme=None) -> Type:
    """Generate and register the dynamic type for a CRD. Idempotent: the
    same (group, version, kind) re-registers over itself."""
    from .scheme import SCHEME
    scheme = scheme or SCHEME
    validate_crd(crd)
    names = crd.spec.names
    api_version = f"{crd.spec.group}/{storage_version(crd)}"
    # exact-gvk check: type_for's kind-only fallback would conflate
    # same-kind CRDs from different groups
    existing = scheme.type_for_exact(api_version, names.kind)
    if existing is not None and \
            getattr(existing, "_crd_resource", None) == names.plural:
        return existing
    holder = scheme.type_for_resource(names.plural)
    if holder is not None and \
            getattr(holder, "_crd_group", None) != crd.spec.group:
        # the flat resource table has no per-group URL space: a plural
        # already owned by a builtin or another group's CRD must be
        # rejected, not silently stolen
        raise ValueError(
            f"resource {names.plural!r} is already registered")
    cls = type(names.kind, (DynamicResource,), {
        "_crd_resource": names.plural,
        "_crd_group": crd.spec.group,
    })
    # dataclass machinery is inherited; instances still default api_version
    # and kind to "" — stamp per-class defaults so bare cls() is well-formed
    def _init(self, api_version=api_version, kind=names.kind,
              metadata=None, spec=None, status=None):
        DynamicResource.__init__(
            self, api_version, kind, metadata or ObjectMeta(),
            spec if spec is not None else {},
            status if status is not None else {})
    cls.__init__ = _init
    scheme.register(cls, api_version, names.kind, names.plural,
                    namespaced=(crd.spec.scope != "Cluster"))
    return cls


def unregister_crd(crd: CustomResourceDefinition, scheme=None) -> None:
    from .scheme import SCHEME
    scheme = scheme or SCHEME
    api_version = f"{crd.spec.group}/{storage_version(crd)}"
    scheme.unregister(api_version, crd.spec.names.kind,
                      crd.spec.names.plural)
