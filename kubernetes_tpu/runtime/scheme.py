"""Scheme — kind registry mapping (apiVersion, kind) <-> python type and
resource (plural) names, with decode dispatch on TypeMeta.

Ref: staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go, reduced: there is
one internal representation (the dataclasses) and one wire version per group,
so conversion collapses to serde.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

from ..api import serde
from ..api.apps import DaemonSet, Deployment, ReplicaSet, StatefulSet
from ..api.batch import CronJob, Job
from ..api.core import (Binding, ConfigMap, Endpoints, Event, LimitRange,
                        Namespace, Node, PersistentVolume,
                        PersistentVolumeClaim, Pod, ReplicationController,
                        ResourceQuota, Secret, Service, ServiceAccount)
from ..api.rbac import (ClusterRole, ClusterRoleBinding, Role, RoleBinding)
from ..api.policy import Lease, PodDisruptionBudget, PriorityClass, StorageClass


class Scheme:
    def __init__(self):
        self._by_gvk: Dict[Tuple[str, str], Type] = {}
        self._by_type: Dict[Type, Tuple[str, str]] = {}
        self._resource_by_type: Dict[Type, str] = {}
        self._type_by_resource: Dict[str, Type] = {}
        self._namespaced: Dict[Type, bool] = {}

    def register(self, cls: Type, api_version: str, kind: str, resource: str,
                 namespaced: bool = True) -> None:
        self._by_gvk[(api_version, kind)] = cls
        self._by_type[cls] = (api_version, kind)
        self._resource_by_type[cls] = resource
        self._type_by_resource[resource] = cls
        self._namespaced[cls] = namespaced
        if not namespaced:
            # keep generic validation's scope knowledge in sync (it cannot
            # import the scheme: api <- runtime would cycle); keyed by
            # CLASS — a kind-name key would collide with builtins
            from ..api import validation
            validation.CLUSTER_SCOPED_TYPES.add(cls)

    def unregister(self, api_version: str, kind: str, resource: str) -> None:
        """Remove a dynamically-registered kind (CRD deletion)."""
        cls = self._by_gvk.pop((api_version, kind), None)
        if cls is None:
            return
        self._by_type.pop(cls, None)
        self._resource_by_type.pop(cls, None)
        if self._type_by_resource.get(resource) is cls:
            del self._type_by_resource[resource]
        self._namespaced.pop(cls, None)
        from ..api import validation
        validation.CLUSTER_SCOPED_TYPES.discard(cls)

    def type_for(self, api_version: str, kind: str) -> Optional[Type]:
        return self._by_gvk.get((api_version, kind)) or \
            next((cls for (v, k), cls in self._by_gvk.items() if k == kind), None)

    def type_for_exact(self, api_version: str, kind: str) -> Optional[Type]:
        """Exact-gvk lookup, no kind-only fallback — same-kind CRDs in
        different groups must not resolve to each other."""
        return self._by_gvk.get((api_version, kind))

    def type_for_resource(self, resource: str) -> Optional[Type]:
        return self._type_by_resource.get(resource)

    def resource_for(self, cls_or_obj) -> str:
        cls = cls_or_obj if isinstance(cls_or_obj, type) else type(cls_or_obj)
        return self._resource_by_type[cls]

    def gvk_for(self, cls_or_obj) -> Tuple[str, str]:
        cls = cls_or_obj if isinstance(cls_or_obj, type) else type(cls_or_obj)
        return self._by_type[cls]

    def is_namespaced(self, cls_or_obj) -> bool:
        cls = cls_or_obj if isinstance(cls_or_obj, type) else type(cls_or_obj)
        return self._namespaced[cls]

    def resources(self):
        return list(self._type_by_resource)

    def decode_any(self, data: Dict[str, Any]):
        """Decode arbitrary manifest data by its TypeMeta."""
        kind = data.get("kind", "")
        api_version = data.get("apiVersion", "")
        cls = self.type_for(api_version, kind)
        if cls is None:
            raise KeyError(f"no kind registered for {api_version}/{kind}")
        return serde.decode(cls, data)


def default_scheme() -> Scheme:
    s = Scheme()
    s.register(Pod, "v1", "Pod", "pods")
    s.register(Node, "v1", "Node", "nodes", namespaced=False)
    s.register(Service, "v1", "Service", "services")
    s.register(Endpoints, "v1", "Endpoints", "endpoints")
    s.register(Namespace, "v1", "Namespace", "namespaces", namespaced=False)
    s.register(Event, "v1", "Event", "events")
    s.register(Binding, "v1", "Binding", "bindings")
    s.register(PersistentVolume, "v1", "PersistentVolume",
               "persistentvolumes", namespaced=False)
    s.register(PersistentVolumeClaim, "v1", "PersistentVolumeClaim",
               "persistentvolumeclaims")
    s.register(ReplicationController, "v1", "ReplicationController",
               "replicationcontrollers")
    s.register(ResourceQuota, "v1", "ResourceQuota", "resourcequotas")
    s.register(LimitRange, "v1", "LimitRange", "limitranges")
    s.register(ConfigMap, "v1", "ConfigMap", "configmaps")
    s.register(Secret, "v1", "Secret", "secrets")
    s.register(ServiceAccount, "v1", "ServiceAccount", "serviceaccounts")
    s.register(Role, "rbac.authorization.k8s.io/v1", "Role", "roles")
    s.register(ClusterRole, "rbac.authorization.k8s.io/v1", "ClusterRole",
               "clusterroles", namespaced=False)
    s.register(RoleBinding, "rbac.authorization.k8s.io/v1", "RoleBinding",
               "rolebindings")
    s.register(ClusterRoleBinding, "rbac.authorization.k8s.io/v1",
               "ClusterRoleBinding", "clusterrolebindings",
               namespaced=False)
    s.register(Deployment, "apps/v1", "Deployment", "deployments")
    s.register(ReplicaSet, "apps/v1", "ReplicaSet", "replicasets")
    s.register(StatefulSet, "apps/v1", "StatefulSet", "statefulsets")
    s.register(DaemonSet, "apps/v1", "DaemonSet", "daemonsets")
    s.register(Job, "batch/v1", "Job", "jobs")
    s.register(CronJob, "batch/v1beta1", "CronJob", "cronjobs")
    s.register(PodDisruptionBudget, "policy/v1beta1", "PodDisruptionBudget",
               "poddisruptionbudgets")
    s.register(PriorityClass, "scheduling.k8s.io/v1", "PriorityClass",
               "priorityclasses", namespaced=False)
    s.register(StorageClass, "storage.k8s.io/v1", "StorageClass",
               "storageclasses", namespaced=False)
    s.register(Lease, "coordination.k8s.io/v1", "Lease", "leases")
    from ..api.scheduling import PodGroup
    s.register(PodGroup, "scheduling.k8s.io/v1alpha1", "PodGroup",
               "podgroups")
    from .crd import CustomResourceDefinition
    s.register(CustomResourceDefinition, "apiextensions.k8s.io/v1",
               "CustomResourceDefinition", "customresourcedefinitions",
               namespaced=False)
    from ..api.autoscaling import HorizontalPodAutoscaler
    s.register(HorizontalPodAutoscaler, "autoscaling/v1",
               "HorizontalPodAutoscaler", "horizontalpodautoscalers")
    from ..api.certificates import CertificateSigningRequest
    s.register(CertificateSigningRequest, "certificates.k8s.io/v1",
               "CertificateSigningRequest", "certificatesigningrequests",
               namespaced=False)
    from ..api.admissionregistration import (MutatingWebhookConfiguration,
                                             ValidatingWebhookConfiguration)
    s.register(MutatingWebhookConfiguration,
               "admissionregistration.k8s.io/v1",
               "MutatingWebhookConfiguration",
               "mutatingwebhookconfigurations", namespaced=False)
    s.register(ValidatingWebhookConfiguration,
               "admissionregistration.k8s.io/v1",
               "ValidatingWebhookConfiguration",
               "validatingwebhookconfigurations", namespaced=False)
    from ..api.apiregistration import APIService
    s.register(APIService, "apiregistration.k8s.io/v1", "APIService",
               "apiservices", namespaced=False)
    return s


#: process-wide scheme, mirroring the reference's legacyscheme.Scheme
SCHEME = default_scheme()
