"""Runtime machinery (ref: staging/src/k8s.io/apimachinery/pkg/runtime)."""

from .scheme import SCHEME, Scheme, default_scheme

__all__ = ["SCHEME", "Scheme", "default_scheme"]
