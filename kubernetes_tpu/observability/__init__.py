"""Observability layer: span tracing with a flight recorder, and the
cross-component metrics scrape surface (ISSUE 11).

Components emit spans through a SpanTracer (clock-injectable — the
chaos/serving determinism contract extends to traces) into a bounded
FlightRecorder; component metric registries aggregate into one
MetricsRegistry the APIServer serves at GET /metrics, next to
/debug/traces and /debug/pending."""

from .registry import MetricsRegistry, parse_exposition
from .tracer import (DEFAULT_POD_SAMPLE, FlightRecorder, NULL_TRACER,
                     Span, SpanTracer, nearest_rank_percentile,
                     stage_percentiles)

__all__ = [
    "DEFAULT_POD_SAMPLE", "FlightRecorder", "MetricsRegistry",
    "NULL_TRACER", "Span", "SpanTracer", "nearest_rank_percentile",
    "parse_exposition", "stage_percentiles",
]
