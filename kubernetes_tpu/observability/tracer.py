"""Span tracing — follow one pod across components, deterministically.

Ref: the reference traces each scheduling attempt with utiltrace
(generic_scheduler.go:185) and exports nothing structured; here the span
layer is first-class: every span carries a trace_id (pod UID for
lifecycle spans, "" for batch/stage spans), timestamps come from an
INJECTABLE clock (REAL_CLOCK or the shared FakeClock), and spans land in
a bounded in-memory flight recorder.

Determinism contract (the chaos harness's, extended to traces): on a
FakeClock with synchronous stepping, two same-seed runs produce
byte-identical span logs — timestamps are virtual, pod UIDs are the
store's deterministic counters, and sampling is a pure function of
trace_id. The exported JSONL is canonically ordered (export_jsonl), so
the contract rests on the deterministic SET of spans, not on which
informer thread's append won a race within a settle window.

Cost model: batch/stage spans are one record per batch (always on);
pod-lifecycle spans are sampled 1-in-`pod_sample` by a crc32 of the
trace_id (default 16, KTPU_TRACE_SAMPLE overrides; harnesses pass 1 to
capture every pod). The recorder is a per-component ring — oldest spans
evict, and the eviction count is itself visible (`dropped`).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from collections import deque
from typing import Dict, Iterable, List, Optional

from ..utils.clock import Clock, REAL_CLOCK

#: 1-in-N pod-lifecycle sampling when the caller does not choose
#: (KTPU_TRACE_SAMPLE overrides; 1 = trace every pod, 0 = disable)
DEFAULT_POD_SAMPLE = 16


class Span:
    """One recorded interval (start == end for instant events)."""

    __slots__ = ("trace_id", "component", "name", "start", "end", "attrs")

    def __init__(self, trace_id: str, component: str, name: str,
                 start: float, end: float,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.component = component
        self.name = name
        self.start = start
        self.end = end
        self.attrs = attrs or {}

    @property
    def duration(self) -> float:
        return self.end - self.start

    def to_dict(self) -> dict:
        d = {"component": self.component, "name": self.name,
             "trace": self.trace_id, "start": self.start, "end": self.end}
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    def to_line(self) -> str:
        # sort_keys: the byte-identity contract must not hinge on dict
        # insertion order surviving refactors
        return json.dumps(self.to_dict(), sort_keys=True)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Span({self.to_line()})"


class FlightRecorder:
    """Bounded per-component span buffers, JSONL-exportable.

    Oldest spans evict when a component's ring fills; the drop count per
    component is kept so a truncated export never silently reads as "the
    whole history"."""

    DEFAULT_CAPACITY = 8192

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._buffers: Dict[str, deque] = {}
        self.dropped: Dict[str, int] = {}

    def record(self, span: Span) -> None:
        with self._lock:
            buf = self._buffers.get(span.component)
            if buf is None:
                buf = self._buffers[span.component] = deque(
                    maxlen=self.capacity)
            if len(buf) == buf.maxlen:
                self.dropped[span.component] = \
                    self.dropped.get(span.component, 0) + 1
            buf.append(span)

    def components(self) -> List[str]:
        with self._lock:
            return sorted(self._buffers)

    def spans(self, component: Optional[str] = None,
              trace_id: Optional[str] = None,
              name: Optional[str] = None) -> List[Span]:
        """Snapshot (insertion order per component, components sorted)."""
        with self._lock:
            if component is not None:
                items = list(self._buffers.get(component, ()))
            else:
                items = [s for c in sorted(self._buffers)
                         for s in self._buffers[c]]
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        if name is not None:
            items = [s for s in items if s.name == name]
        return items

    def export_jsonl(self, component: Optional[str] = None,
                     trace_id: Optional[str] = None) -> str:
        """One JSON object per line in CANONICAL order — sorted by
        (component, start, rendered line). The byte-identity contract is
        asserted on this export: the SET of spans is deterministic under
        the harness's settling contract, while two informer delivery
        threads may interleave their appends within one settle window —
        canonical ordering keeps that non-signal out of the bytes."""
        spans = self.spans(component=component, trace_id=trace_id)
        lines = sorted((s.component, s.start, s.to_line()) for s in spans)
        return "\n".join(line for _, _, line in lines) \
            + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._buffers.clear()
            self.dropped.clear()

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buffers.values())


class SpanTracer:
    """The emitting half: components call record()/event()/pod_event()
    and the spans land in the shared FlightRecorder. All timestamps come
    from the injected clock — REAL_CLOCK in production, the harness's
    FakeClock under test (same seed => identical span logs)."""

    def __init__(self, clock: Clock = REAL_CLOCK,
                 recorder: Optional[FlightRecorder] = None,
                 pod_sample: Optional[int] = None,
                 enabled: bool = True):
        self.clock = clock
        self.recorder = recorder if recorder is not None else FlightRecorder()
        if pod_sample is None:
            pod_sample = int(os.environ.get("KTPU_TRACE_SAMPLE",
                                            DEFAULT_POD_SAMPLE))
        self.pod_sample = max(0, int(pod_sample))
        self.enabled = enabled and self.pod_sample != 0

    def now(self) -> float:
        """Span timestamps: monotonic on the real clock (NTP steps must
        never yield a negative stage duration), virtual time on FakeClock
        — the two coincide there, preserving determinism."""
        return self.clock.monotonic()

    def sampled(self, trace_id: str) -> bool:
        """Deterministic 1-in-N per trace: a pure function of trace_id,
        so the SAME pods are traced in every same-seed run (and across
        components within one run)."""
        if self.pod_sample <= 1:
            return self.enabled
        return zlib.crc32(trace_id.encode()) % self.pod_sample == 0

    def record(self, component: str, name: str, start: float,
               end: Optional[float] = None, trace_id: str = "",
               **attrs) -> None:
        """Record a finished interval (batch/stage spans — always on)."""
        if not self.enabled:
            return
        self.recorder.record(Span(trace_id, component, name, start,
                                  end if end is not None else start,
                                  attrs or None))

    def event(self, component: str, name: str, trace_id: str = "",
              **attrs) -> None:
        """Instant span at now() (unsampled — callers own the rate)."""
        if not self.enabled:
            return
        t = self.clock.monotonic()
        self.recorder.record(Span(trace_id, component, name, t, t,
                                  attrs or None))

    def pod_event(self, component: str, name: str, pod, **attrs) -> None:
        """Pod-lifecycle milestone, trace_id = pod UID, sampled 1-in-N.
        The hot-path shape: one crc32 per call for unsampled pods."""
        if not self.enabled:
            return
        meta = pod.metadata
        tid = meta.uid or meta.key()
        if self.pod_sample > 1 and \
                zlib.crc32(tid.encode()) % self.pod_sample != 0:
            return
        t = self.clock.monotonic()
        a = {"pod": meta.key()}
        if attrs:
            a.update(attrs)
        self.recorder.record(Span(tid, component, name, t, t, a))


#: a disabled tracer callers can share instead of None-checking
NULL_TRACER = SpanTracer(enabled=False, pod_sample=1)


def nearest_rank_percentile(sorted_vals: List[float], q: float) -> float:
    """THE nearest-rank percentile over a SORTED sample list — the one
    definition shared by the serving SLO tracker (serving/slo.percentile
    delegates here) and the span stage reports, so the two surfaces
    bench --trace cross-checks can never desynchronize."""
    if not sorted_vals:
        return 0.0
    import math
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[rank - 1]


def stage_percentiles(recorder: FlightRecorder,
                      component: Optional[str] = None,
                      names: Optional[Iterable[str]] = None) -> dict:
    """Per-stage duration percentiles from batch/stage spans (trace-less
    spans with a real interval) — the bench's --trace report and the
    cross-check against measure_device_profile's pipeline section."""
    by_name: Dict[str, List[float]] = {}
    for s in recorder.spans(component=component):
        if s.trace_id:
            continue  # pod milestones are instants, not stages
        if names is not None and s.name not in names:
            continue
        by_name.setdefault(s.name, []).append(s.duration)
    out = {}
    for name, vals in sorted(by_name.items()):
        vals.sort()
        out[name] = {
            "count": len(vals),
            "p50_s": round(nearest_rank_percentile(vals, 0.50), 6),
            "p95_s": round(nearest_rank_percentile(vals, 0.95), 6),
            "p99_s": round(nearest_rank_percentile(vals, 0.99), 6),
            "total_s": round(sum(vals), 6),
        }
    return out
