"""MetricsRegistry — the cross-component scrape surface.

Ref: each reference component registers its families into one prometheus
default registry and serves them on /metrics (pkg/scheduler/metrics,
apiserver endpoints/metrics). Our components each own a utils.metrics
Registry; this aggregator joins them into ONE text exposition with
name-collision detection:

  - two components exporting the SAME family name with a DIFFERENT
    type, help text, or histogram buckets is a registration error
    (raised at add_registry — the tier-1 registry-completeness check);
  - the same family name with an IDENTICAL signature (two schedulers,
    scheduler + controller-manager RobustnessMetrics) merges label-wise
    at expose time, like prometheus multi-process aggregation, so the
    exposition never carries a duplicate HELP/TYPE header.

`parse_exposition` is the reverse direction: text -> families/samples,
used by the scrape round-trip test to assert histogram invariants hold
at the source.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.metrics import (Registry, _Metric, _fmt_labels,
                             expose_histogram_series)


def _signature(m: _Metric) -> tuple:
    return (m.kind, m.help, getattr(m, "buckets", None))


class MetricsRegistry:
    """Aggregates component registries into one /metrics exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        #: component name -> live Registry (enumerated fresh at expose,
        #: so families a component registers late still ride the scrape)
        self._components: Dict[str, Registry] = {}

    # ------------------------------------------------------- registration

    def add_registry(self, component: str, registry: Registry) -> Registry:
        """Attach a component's registry. Raises on a component-name
        reuse (unless it is the same registry) or on any family whose
        signature conflicts with an already-attached family."""
        with self._lock:
            cur = self._components.get(component)
            if cur is not None and cur is not registry:
                raise ValueError(
                    f"component {component!r} already registered with a "
                    f"different registry")
            conflicts = self._conflicts_locked(extra=(component, registry))
            if conflicts:
                raise ValueError("metric family collision: "
                                 + "; ".join(conflicts))
            self._components[component] = registry
        return registry

    def components(self) -> List[str]:
        with self._lock:
            return sorted(self._components)

    def _families_locked(self, extra: Optional[tuple] = None
                         ) -> List[Tuple[str, List[_Metric]]]:
        """(name, metrics) in first-registration order, deduped by
        object identity (one registry attached under two components must
        not double its values)."""
        comps = list(self._components.items())
        if extra is not None and extra[0] not in self._components:
            comps.append(extra)
        order: List[str] = []
        families: Dict[str, List[_Metric]] = {}
        for _, reg in comps:
            with reg._lock:
                metrics = list(reg._metrics.values())
            for m in metrics:
                group = families.get(m.name)
                if group is None:
                    order.append(m.name)
                    families[m.name] = [m]
                elif not any(g is m for g in group):
                    group.append(m)
        return [(name, families[name]) for name in order]

    def _conflicts_locked(self, extra: Optional[tuple] = None) -> List[str]:
        out = []
        for name, group in self._families_locked(extra=extra):
            sigs = {_signature(m) for m in group}
            if len(sigs) > 1:
                kinds = sorted({m.kind for m in group})
                out.append(f"{name} registered with conflicting "
                           f"signatures (kinds {kinds})")
        return out

    def check_collisions(self) -> List[str]:
        """Re-verify the no-conflict invariant over families registered
        since attach time (the completeness check's second pass)."""
        with self._lock:
            return self._conflicts_locked()

    # --------------------------------------------------------- exposition

    def expose(self) -> str:
        with self._lock:
            families = self._families_locked()
        lines: List[str] = []
        for name, group in families:
            if len(group) == 1:
                lines.extend(group[0].expose())
            else:
                lines.extend(self._merged_expose(name, group))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _merged_expose(name: str, group: List[_Metric]) -> List[str]:
        """Label-wise merge of same-signature duplicates. A conflicting
        group (possible only via post-attach registration) exposes the
        FIRST member and skips the rest — a scrape must stay valid text
        even when check_collisions() has findings to report."""
        first = group[0]
        sig = _signature(first)
        members = [m for m in group if _signature(m) == sig]
        out = first._header()
        if first.kind == "histogram":
            merged: Dict[tuple, list] = {}
            for m in members:
                for key, (counts, total, n) in m.snapshot().items():
                    s = merged.get(key)
                    if s is None:
                        merged[key] = [list(counts), total, n]
                    else:
                        s[0] = [a + b for a, b in zip(s[0], counts)]
                        s[1] += total
                        s[2] += n
            out.extend(expose_histogram_series(
                name, first.buckets, sorted(merged.items())))
            return out
        totals: Dict[tuple, float] = {}
        for m in members:
            for key, v in m.snapshot().items():
                totals[key] = totals.get(key, 0.0) + v
        for key, v in sorted(totals.items()) or [((), 0.0)]:
            out.append(f"{name}{_fmt_labels(key)} {v}")
        return out

    def reset(self) -> None:
        """DELETE /metrics semantics across every component: values zero,
        families stay registered (utils.metrics.Registry.reset)."""
        with self._lock:
            regs = list(self._components.values())
        for reg in regs:
            reg.reset()


# ----------------------------------------------------------------- parsing

_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')


def parse_exposition(text: str) -> Dict[str, dict]:
    """Text exposition -> {family: {"type", "help", "samples"}} where
    samples are (sample_name, labels dict, float value) — the scrape-side
    half of the round-trip test. Histogram/summary suffixes (_bucket,
    _sum, _count) attach to their base family."""
    families: Dict[str, dict] = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"type": "untyped", "help": "", "samples": []})

    for raw in text.splitlines():
        if raw.startswith("# HELP "):
            name, _, help_text = raw[len("# HELP "):].partition(" ")
            fam(name)["help"] = help_text
        elif raw.startswith("# TYPE "):
            name, _, kind = raw[len("# TYPE "):].partition(" ")
            fam(name)["type"] = kind.strip()
        elif raw.startswith("#") or not raw.strip():
            continue
        else:
            m = _SAMPLE_RE.match(raw)
            if m is None:
                raise ValueError(f"malformed exposition line: {raw!r}")
            sample_name, labels_raw, value = m.groups()
            labels = dict(_LABEL_RE.findall(labels_raw or ""))
            base = sample_name
            for suffix in ("_bucket", "_sum", "_count"):
                stem = sample_name[:-len(suffix)] \
                    if sample_name.endswith(suffix) else None
                if stem is not None and stem in families:
                    base = stem
                    break
            fam(base)["samples"].append(
                (sample_name, labels, float(value)))
    return families
