"""Log-once-per-streak accounting for deliberately swallowed errors.

The repo's error-handling contract (established piecemeal by PRs 2, 4,
and 8; machine-checked by ktpulint rule KTPU001) forbids silently
dropped exceptions: a handler that decides an error is survivable must
still (a) log the FIRST failure of a streak — so a soak's logs show
that something started failing without drowning in repeats — and
(b) count EVERY one, so metrics surface the failure rate the logs
deliberately compress. This helper packages that idiom for
drop-and-continue paths that do NOT want retries; writes that should
be retried route through utils.backoff.retry instead.

Usage:

    self._swallowed = SwallowedErrors("podgc", metrics)
    ...
    try:
        self.client.pods(ns).delete(name)
        self._swallowed.ok("delete_pod")
    except Exception as e:
        self._swallowed.swallow("delete_pod", e)
        return False

Counting lands in RobustnessMetrics.swallowed_errors
(`swallowed_errors_total{component,op}`); with no metrics wired the
helper still logs. Streaks are per-op: a success on an op re-arms its
log so the NEXT failure of that op is visible again (the same contract
state/wal.py and state/leaderelection.py implement inline).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional


class SwallowedErrors:
    """Per-component accounting for handled-and-dropped exceptions."""

    def __init__(self, component: str, metrics=None,
                 logger: Optional[logging.Logger] = None):
        self.component = component
        self.metrics = metrics  # utils.metrics.RobustnessMetrics or None
        self._logger = logger or logging.getLogger(
            f"kubernetes_tpu.{component}")
        self._lock = threading.Lock()
        #: op -> consecutive swallowed failures since the last ok()
        self._streaks: Dict[str, int] = {}

    def swallow(self, op: str, exc: BaseException) -> None:
        """Record a survivable, dropped failure: the first of a streak
        logs (with the exception), every one counts."""
        with self._lock:
            streak = self._streaks.get(op, 0)
            self._streaks[op] = streak + 1
        if streak == 0:
            self._logger.warning(
                "%s/%s: swallowed %r; further failures counted in "
                "swallowed_errors_total until the streak clears",
                self.component, op, exc)
        if self.metrics is not None:
            self.metrics.swallowed_errors.inc(
                component=self.component, op=op)

    def ok(self, op: str) -> None:
        """A success ends the op's failure streak; the next failure
        logs again."""
        with self._lock:
            if self._streaks.get(op):
                self._streaks[op] = 0

    def streak(self, op: str) -> int:
        """Current consecutive-failure count (introspection/tests)."""
        with self._lock:
            return self._streaks.get(op, 0)
