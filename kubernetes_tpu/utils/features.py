"""Feature gates.

Ref: pkg/features/kube_features.go (144 gates with maturity levels) +
staging/src/k8s.io/apiserver/pkg/util/feature/feature_gate.go: a mutable
global gate set from --feature-gates=K=true,K2=false; GA features are
locked on and cannot be disabled (feature_gate.go's
lockToDefault/specialFeatures handling).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    prerelease: str = ALPHA
    lock_to_default: bool = False


class FeatureGate:
    def __init__(self, known: Dict[str, FeatureSpec]):
        self._lock = threading.Lock()
        self._known = dict(known)
        self._enabled: Dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name}")
            return spec.default

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name}")
            if spec.lock_to_default and value != spec.default:
                raise ValueError(
                    f"feature {name} is {spec.prerelease} and locked to "
                    f"{spec.default}")
            self._enabled[name] = value

    def set_from_map(self, flags: Dict[str, bool]) -> None:
        for k, v in flags.items():
            self.set(k, v)

    def parse(self, flag: str) -> None:
        """--feature-gates=A=true,B=false."""
        for part in flag.split(","):
            if not part:
                continue
            k, _, v = part.partition("=")
            self.set(k.strip(), v.strip().lower() in ("true", "1", "yes"))

    def known(self) -> Dict[str, FeatureSpec]:
        with self._lock:
            return dict(self._known)


#: the gate set this framework consults (the kube_features.go analog,
#: scoped to behaviors that actually branch here)
DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    # pod priority & preemption (GA in the reference era; locked on)
    "PodPriority": FeatureSpec(default=True, prerelease=GA,
                               lock_to_default=True),
    # taint-based evictions by the node lifecycle controller
    "TaintBasedEvictions": FeatureSpec(default=True, prerelease=BETA),
    # delayed volume binding (WaitForFirstConsumer)
    "VolumeScheduling": FeatureSpec(default=True, prerelease=GA,
                                    lock_to_default=True),
    # node leases as heartbeats
    "NodeLease": FeatureSpec(default=True, prerelease=BETA),
    # ttlSecondsAfterFinished cleanup
    "TTLAfterFinished": FeatureSpec(default=True, prerelease=ALPHA),
    # device-usage chaining across batches in the scheduler drain
    # (batch extension; no reference analog)
    "SchedulerDeviceChaining": FeatureSpec(default=True, prerelease=BETA),
    # nominated-pod reservation tensors in the assignment kernel
    "SchedulerNominatedReservations": FeatureSpec(default=True,
                                                  prerelease=BETA),
}

#: process-wide gate (ref: utilfeature.DefaultFeatureGate)
DEFAULT_FEATURE_GATE = FeatureGate(DEFAULT_FEATURES)
