"""Prometheus-style metrics: Counter / Gauge / Histogram + text exposition.

Ref: the reference instruments every component with prometheus client_golang
(e.g. pkg/scheduler/metrics/metrics.go, apiserver endpoints/metrics). This
is the minimal compatible core: labeled metric families, histogram buckets
matching prometheus semantics (+Inf bucket, _sum/_count), and the text
exposition format scrapers parse.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (0.001, 0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                   0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)

#: codec-latency buckets: payload encode/decode runs in the micro- to
#: low-millisecond range, far below DEFAULT_BUCKETS' 1ms floor
WIRE_CODEC_BUCKETS = (0.00001, 0.00005, 0.0001, 0.0005, 0.001,
                      0.005, 0.02, 0.1, 0.5)


def _label_key(labels: Dict[str, str]) -> Tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(labels: Tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def expose_histogram_series(name: str, buckets: Sequence[float],
                            items) -> List[str]:
    """Histogram sample lines (no header) from (label key, (per-bucket
    counts, sum, count)) items — shared by Histogram.expose and the
    observability MetricsRegistry's label-wise merge, so the two paths
    can never drift in format."""
    out: List[str] = []
    for key, (counts, total, n) in items:
        acc = 0
        for i, b in enumerate(buckets):
            acc += counts[i]
            lab = dict(key)
            lab["le"] = repr(b) if b != int(b) else str(b)
            out.append(f"{name}_bucket{_fmt_labels(_label_key(lab))} {acc}")
        lab = dict(key)
        lab["le"] = "+Inf"
        out.append(f"{name}_bucket{_fmt_labels(_label_key(lab))} {n}")
        out.append(f"{name}_sum{_fmt_labels(key)} {total}")
        out.append(f"{name}_count{_fmt_labels(key)} {n}")
    return out


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()

    def expose(self) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def clear(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.kind}"]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def snapshot(self) -> Dict[Tuple, float]:
        """Label key -> value copy (the aggregator's merge input)."""
        with self._lock:
            return dict(self._values)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_text: str = "", fn=None):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}
        self._fn = fn  # callback gauge: sampled at expose time

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def snapshot(self) -> Dict[Tuple, float]:
        """Label key -> value copy (callback gauges sample the fn)."""
        if self._fn is not None:
            return {(): float(self._fn())}
        with self._lock:
            return dict(self._values)

    def expose(self) -> List[str]:
        out = self._header()
        if self._fn is not None:
            out.append(f"{self.name} {float(self._fn())}")
            return out
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(buckets)
        # label key -> (bucket counts, sum, count)
        self._series: Dict[Tuple, list] = {}

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = s
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s[0][i] += 1
                    break
            else:
                s[0][-1] += 1
            s[1] += v
            s[2] += 1

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s[2] if s else 0

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s[1] if s else 0.0

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    def quantile(self, q: float, **labels) -> float:
        """Approximate quantile with linear interpolation inside the
        owning bucket (scrape-side histogram_quantile equivalent, for
        tests and bench reporting). Interpolation matters when callers
        RATIO two quantiles: power-of-two buckets would otherwise
        quantize every ratio to a power of two."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if not s or s[2] == 0:
                return 0.0
            target = q * s[2]
            acc = 0
            lower = 0.0
            for i, c in enumerate(s[0][:-1]):
                if c > 0 and acc + c >= target:
                    frac = (target - acc) / c
                    return lower + (self.buckets[i] - lower) * frac
                acc += c
                lower = self.buckets[i]
            return float("inf")

    def snapshot(self) -> Dict[Tuple, Tuple[list, float, int]]:
        """Label key -> (per-bucket counts, sum, count) copy."""
        with self._lock:
            return {k: ([*s[0]], s[1], s[2])
                    for k, s in self._series.items()}

    def expose(self) -> List[str]:
        items = sorted(self.snapshot().items())
        out = self._header()
        out.extend(expose_histogram_series(self.name, self.buckets, items))
        return out


class GangMetrics:
    """Gang-scheduling metric families (PodGroup coscheduling). Kept here
    with the metric core — the gang gate lives below the scheduler package
    and the controller manager samples the same families — registered into
    the caller's registry so they ride the same /metrics exposition."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: gangs currently held below minMember (queue gate) or waiting at
        #: the permit gate, respectively
        self.gangs_pending = r.gauge(
            "scheduler_gangs_pending",
            "PodGroups with members held back by the gang gate, by stage")
        self.gangs_admitted = r.counter(
            "scheduler_gangs_admitted_total",
            "PodGroups whose full gang passed the permit gate and bound")
        self.gangs_timed_out = r.counter(
            "scheduler_gangs_timed_out_total",
            "PodGroups whose permit wait expired; reservations rolled back")
        self.gangs_node_lost = r.counter(
            "scheduler_gangs_node_lost_total",
            "PodGroups whose reservations rolled back because a reserved "
            "node died (deleted or NoExecute-dead)")
        self.gangs_rejected = r.counter(
            "scheduler_gangs_rejected_total",
            "Gangs the all-or-nothing kernel could not place atomically")
        self.gang_permit_wait = r.histogram(
            "scheduler_gang_permit_wait_seconds",
            "Seconds a gang member held a reservation at the permit gate")


class InformerMetrics:
    """Reflector/informer observability: how often watch streams break,
    how they recover (resume at last_sync_rv vs full relist), and how
    stale a live stream is. One family set is shared by every informer of
    a factory — series are labeled by resource."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: watch streams re-established at last_sync_rv WITHOUT a relist —
        #: the reflector resume path (a dropped connection costs one
        #: reconnect, not one LIST of every object)
        self.watch_reconnects = r.counter(
            "informer_watch_reconnects_total",
            "Watch streams re-established at last_sync_rv without a "
            "relist, by resource")
        #: full LIST+replace resyncs: first sync, 410 history overflow,
        #: or a server that lost its watch history (store restart)
        self.relists = r.counter(
            "informer_relists_total",
            "Full LIST+replace resyncs (initial sync or 410 Gone), "
            "by resource")
        #: watch streams that terminated with a recorded error (vs the
        #: server's clean close), by resource and error class
        self.watch_stream_errors = r.counter(
            "informer_watch_stream_errors_total",
            "Watch streams torn down by a stream error, by resource "
            "and reason")
        #: seconds since the last byte (events OR server heartbeats) on
        #: the informer's current watch stream; sampled while the event
        #: queue is idle. A stream past the staleness timeout is killed
        #: and resumed instead of hanging forever.
        self.watch_staleness = r.gauge(
            "informer_watch_staleness_seconds",
            "Seconds since the last byte on the informer's watch stream, "
            "by resource")
        #: streams killed by the staleness watchdog (silently-dead TCP:
        #: no FIN, no heartbeats — the read would otherwise block forever)
        self.watch_stale_kills = r.counter(
            "informer_watch_stale_kills_total",
            "Watch streams killed after heartbeat staleness, by resource")
        #: BOOKMARK heartbeat frames consumed (allowWatchBookmarks): each
        #: advances last_sync_rv through a quiet period, shrinking the
        #: window in which a reconnect would 410 into a full relist
        self.watch_bookmarks = r.counter(
            "informer_watch_bookmarks_total",
            "Watch BOOKMARK frames that advanced last_sync_rv, by resource")
        #: repoint() calls — the informer's upstream swapped to a new
        #: client (replica promotion) and the next watch round resumed at
        #: last_sync_rv through it; pairs with relists to prove the
        #: promote drill's no-relist contract
        self.repoints = r.counter(
            "informer_repoints_total",
            "Informer upstreams swapped by repoint(), by resource")


class RobustnessMetrics:
    """Failure-handling metric families: retried/abandoned API writes
    (utils/backoff.retry), gang-atomic evictions (nodelifecycle), and
    chaos-injected faults (chaos/injector). Registered into the caller's
    registry so they ride the same /metrics exposition as the component
    that owns them."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: transient API-write failures retried with backoff, by
        #: component/op — what the bare `except: pass` blocks used to hide
        self.api_retries = r.counter(
            "api_request_retries_total",
            "API writes retried after a transient failure")
        self.api_give_ups = r.counter(
            "api_request_give_ups_total",
            "API writes abandoned after exhausting the backoff policy")
        #: whole-PodGroup evictions driven by a member's node dying
        self.gang_evictions = r.counter(
            "nodelifecycle_gang_evictions_total",
            "PodGroups evicted atomically because a member's node died")
        self.pods_evicted = r.counter(
            "nodelifecycle_pods_evicted_total",
            "Pods removed or failed by the node-lifecycle eviction path")
        #: PodGroups rebuilt from Failed back to Pending as one unit
        self.gang_resubmissions = r.counter(
            "podgroup_resubmissions_total",
            "Failed PodGroups resubmitted (members recreated as a unit)")
        #: faults the chaos injector actually fired, by kind
        self.faults_injected = r.counter(
            "chaos_faults_injected_total",
            "Faults injected by the chaos harness, by kind")
        #: pipelined commits whose failure rolled chained device usage
        #: back (forget assumed pods + invalidate + phantom-mark) — the
        #: self-heal path the mid-commit chaos test drives
        self.commit_rollbacks = r.counter(
            "scheduler_pipelined_commit_rollbacks_total",
            "Pipelined commit stages that lost winners and invalidated "
            "chained device usage")
        #: records the deferred WAL worker could NOT write — silent data
        #: loss at the next replay unless someone is watching this
        self.wal_append_errors = r.counter(
            "wal_append_errors_total",
            "WAL records dropped by a failed append on the writer worker")
        #: torn/corrupt-tail recovery accounting, accumulated across every
        #: replay (store open + restart) this process performed
        self.wal_recovery_records_replayed = r.counter(
            "wal_recovery_records_replayed_total",
            "Verified WAL records replayed across store opens/restarts")
        self.wal_recovery_records_dropped = r.counter(
            "wal_recovery_records_dropped_total",
            "Complete-but-corrupt WAL records discarded at replay "
            "(CRC mismatch or unparseable body)")
        self.wal_recovery_truncated_bytes = r.counter(
            "wal_recovery_truncated_bytes_total",
            "Bytes cut off the journal tail by truncate-on-open")
        #: leadership changes (a fresh acquire by a non-holder), by
        #: election name — the reference's leader_election_master_status
        #: flaps collapsed to a transition counter
        self.leader_transitions = r.counter(
            "leader_transitions_total",
            "Leader elections won by a new holder, by election name")
        #: lease-expiry -> standby's first effective action (first bind
        #: for the scheduler election) — the availability gap a leader
        #: kill actually costs, in (virtual) seconds
        self.leader_failover_seconds = r.histogram(
            "leader_failover_seconds",
            "Seconds between losing a leader and the standby's first "
            "bind, by election name",
            buckets=(1.0, 2.0, 5.0, 10.0, 15.0, 20.0, 30.0, 45.0, 60.0,
                     90.0, 120.0, 180.0))
        #: successful lease renews that landed past slow_renew_fraction of
        #: the renew deadline — near-fence conditions visible BEFORE a
        #: failover (one more slow round-trip and the holder self-fences)
        self.slow_renews = r.counter(
            "leaderelection_slow_renews_total",
            "Successful lease renews that approached the renew deadline, "
            "by election name")
        #: how far the follower trails the primary, in rv units (records):
        #: primary resource_version minus the replica store's high-water rv
        self.replication_lag = r.gauge(
            "replication_lag_records",
            "Records the replica store trails the primary by "
            "(primary rv - replica rv)")
        #: replication stream re-established after an error (wire reset,
        #: dropped watch, primary restart) — each costs one LIST+watch
        #: round against the primary
        self.replication_reconnects = r.counter(
            "replication_reconnects_total",
            "Replication reflector streams re-established after an "
            "error, by resource")
        #: read-path rotations by the replica ReadRouter: a follower
        #: gated out of read rotation for lagging (to_primary) or fanned
        #: back in after catching up (to_replica)
        self.replication_read_rotations = r.counter(
            "replication_read_rotations_total",
            "Informer read-path rotations between replica and primary, "
            "by direction")
        #: containers a virtual kubelet garbage-collected because the
        #: store no longer knows their pod (torn-WAL recovery: the pod's
        #: create was lost with the journal tail)
        self.kubelet_orphans_gced = r.counter(
            "kubelet_orphan_containers_gced_total",
            "Containers removed for pods the store no longer knows")
        #: exceptions a drop-and-continue handler deliberately dropped
        #: (utils.errlog.SwallowedErrors — the KTPU001 contract: logged
        #: once per streak, counted every time). Distinct from
        #: api_give_ups, which counts writes a RETRY policy abandoned.
        self.swallowed_errors = r.counter(
            "swallowed_errors_total",
            "Exceptions handled by drop-and-continue paths, by "
            "component and op")


#: pod-startup latency buckets (seconds) — wider than the scheduler's
#: per-batch buckets: startup rides controller sync + schedule + kubelet
SERVING_LATENCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 8.0,
                           13.0, 21.0, 34.0, 55.0)


class ServingMetrics:
    """Serving-mode (open-loop churn) metric families: per-class pod
    lifecycle latencies the SLO tracker observes, and the arrival rate
    the load generator sustains. Registered into the caller's registry so
    they ride the same /metrics exposition as the scheduler's families."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: created -> Running, by workload class — the latency the SLO is
        #: judged on (the density e2e's p99 <= 5s gate, sustained)
        self.pod_startup_seconds = r.histogram(
            "serving_pod_startup_seconds",
            "Pod creation to Running latency under churn, by class",
            buckets=SERVING_LATENCY_BUCKETS)
        #: created -> bound (spec.nodeName set) — the scheduler's share
        self.pod_bind_seconds = r.histogram(
            "serving_pod_bind_seconds",
            "Pod creation to bound latency under churn, by class",
            buckets=SERVING_LATENCY_BUCKETS)
        #: lifecycle transitions observed, by class and phase
        #: {created, bound, running}
        self.pods_observed = r.counter(
            "serving_pods_observed_total",
            "Pod lifecycle transitions the SLO tracker stamped, "
            "by class and phase")
        #: the open-loop generator's configured arrival rate (pods/s
        #: equivalent; deployment scale deltas and gang members count as
        #: their pod counts)
        self.arrival_rate = r.gauge(
            "serving_arrival_rate_events_per_s",
            "Configured open-loop arrival rate (events/s)")


class APIServerMetrics:
    """The hub's own request/watch families (ref: apiserver
    endpoints/metrics — apiserver_request_total{verb,resource,code} and
    the registered-watcher gauges), self-served on its /metrics next to
    the component registries it aggregates."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: every completed request, including the error mappings — code
        #: is the HTTP status the response actually carried
        self.requests = r.counter(
            "apiserver_request_total",
            "API requests by verb, resource, and HTTP code")
        #: non-watch request wall time (watches are long-running and
        #: would saturate every bucket with their stream lifetime)
        self.request_duration = r.histogram(
            "apiserver_request_duration_seconds",
            "Request latency for non-watch requests, by verb")
        #: currently-open watch streams (the long-running exemption's
        #: population — what the inflight limits deliberately don't cap)
        self.watch_streams = r.gauge(
            "apiserver_registered_watchers",
            "Currently-open watch streams, by resource")
        #: event frames written to watch streams (coalesced slim frames
        #: count every event they carry)
        self.watch_events = r.counter(
            "apiserver_watch_events_sent_total",
            "Watch events written to streams, by resource")
        #: wire volume split by encoding so the r04 bottleneck
        #: attribution (json encode vs transport) can be re-measured
        #: per negotiated encoding (ref: apiserver response-size
        #: families, split by content type)
        self.wire_bytes_sent = r.counter(
            "apiserver_wire_bytes_sent_total",
            "Response + watch-frame bytes written, by encoding")
        self.wire_bytes_received = r.counter(
            "apiserver_wire_bytes_received_total",
            "Request body bytes read, by encoding")
        #: serialization cost per encoding: payload/frame encode time on
        #: the hub (decode time lives client-side in httpclient's
        #: standalone families)
        self.wire_encode_seconds = r.histogram(
            "apiserver_wire_encode_seconds",
            "Payload encode latency, by encoding",
            buckets=WIRE_CODEC_BUCKETS)
        #: watch frames served from the per-(event, encoding) byte cache
        #: instead of re-serializing per registered watcher
        self.watch_frame_cache_hits = r.counter(
            "apiserver_watch_frame_cache_hits_total",
            "Watch frames reused from the shared per-event byte cache, "
            "by encoding")


class FlowControlMetrics:
    """API Priority & Fairness families (ref: apiserver_flowcontrol_*
    — dispatched/rejected counts and queue-wait by priority level),
    registered on the hub's /metrics beside the request families."""

    def __init__(self, registry: Optional["Registry"] = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        #: requests handed a seat (immediately or after queueing)
        self.dispatched = r.counter(
            "flowcontrol_dispatched_total",
            "Requests dispatched to a seat, by priority level")
        #: requests that had to queue before dispatch
        self.queued = r.counter(
            "flowcontrol_queued_total",
            "Requests that entered a fair queue, by priority level")
        #: requests shed with 429 (queue overflow or queue timeout)
        self.rejected = r.counter(
            "flowcontrol_rejected_total",
            "Requests rejected by flow control, by priority level "
            "and reason")
        #: time spent parked in a fair queue before dispatch
        self.queue_wait = r.histogram(
            "flowcontrol_queue_wait_seconds",
            "Fair-queue wait before dispatch, by priority level")


class Registry:
    """Metric family registry with /metrics text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"metric {metric.name} already registered")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self.register(Counter(name, help_text))  # type: ignore

    def gauge(self, name: str, help_text: str = "", fn=None) -> Gauge:
        return self.register(Gauge(name, help_text, fn=fn))  # type: ignore

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, buckets))  # type: ignore

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Ref: the scheduler serves DELETE /metrics -> metrics.Reset()
        (cmd/kube-scheduler/app/server.go:287-291). Values are zeroed but
        the families STAY registered — holders keep observing into the same
        objects and /metrics keeps serving them."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()
