"""Component health + metrics serving.

Ref: apiserver/pkg/server/healthz (every component serves /healthz with
named checks) and the scheduler's insecure serving mux which also exposes
/metrics with DELETE -> Reset (cmd/kube-scheduler/app/server.go:194-211,
:287-291).

`HealthChecks` is the named-check set itself, shareable between the
standalone HealthzServer and the APIServer's /readyz (the hub answers
ready only while every registered component contributor passes).
Component contributors — `scheduler_contributors`,
`controller_manager_contributors`, `leaderelection_contributor` — turn
liveness signals the components already carry (informer sync +
staleness, queue progress, elector thread) into named checks, so
"server up" stops being the whole readiness story.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .metrics import Registry


def _safe(fn) -> bool:
    try:
        return bool(fn())
    except Exception:
        return False


class HealthChecks:
    """Named boolean checks (ref: healthz.NamedCheck). A check that
    raises counts as failed — a probe must never take the server down."""

    def __init__(self):
        self._lock = threading.Lock()
        self._checks: Dict[str, Callable[[], bool]] = {
            "ping": lambda: True}

    def add(self, name: str, fn: Callable[[], bool]) -> None:
        with self._lock:
            self._checks[name] = fn

    def add_all(self, contributors: Dict[str, Callable[[], bool]]) -> None:
        with self._lock:
            self._checks.update(contributors)

    def remove(self, name: str) -> None:
        with self._lock:
            self._checks.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._checks)

    def failed(self) -> List[str]:
        with self._lock:
            items = sorted(self._checks.items())
        return [n for n, fn in items if not _safe(fn)]

    # dict-ish compatibility for the HealthzServer handler
    def items(self):
        with self._lock:
            return list(self._checks.items())


# ------------------------------------------------- component contributors

def _informers_synced(factory) -> bool:
    """Every STARTED informer of the factory completed its first sync
    (the one informer-liveness predicate both contributors share)."""
    with factory._lock:
        informers = list(factory._informers.values())
    return all(inf.has_synced() for inf in informers
               if getattr(inf, "_started", False))


def scheduler_contributors(scheduler, staleness_max: float = 60.0,
                           stuck_after: float = 300.0
                           ) -> Dict[str, Callable[[], bool]]:
    """The scheduler's liveness surface as named checks:

      - informers-synced: every STARTED informer completed its first sync
      - informer-staleness: no live watch stream has gone silent past
        `staleness_max` (the InformerMetrics staleness gauge)
      - queue-progress: pods are pending but no scheduling cycle has
        started for `stuck_after` seconds (injected clock) — the "depth
        stuck" tell that the drain loop died while the process lives
    """
    def informers_synced() -> bool:
        return _informers_synced(scheduler.informers)

    def informers_fresh() -> bool:
        staleness = scheduler.informers.metrics.watch_staleness.snapshot()
        return all(v < staleness_max for v in staleness.values())

    state = {"cycle": -1, "since": None}

    def queue_progress() -> bool:
        now = scheduler.clock.now()
        cycle = scheduler.queue.scheduling_cycle
        if scheduler.queue.num_pending() == 0 or cycle != state["cycle"]:
            state["cycle"] = cycle
            state["since"] = now
            return True
        if state["since"] is None:
            state["since"] = now
        return (now - state["since"]) < stuck_after

    name = getattr(scheduler, "scheduler_name", "scheduler")
    return {
        f"{name}-informers-synced": informers_synced,
        f"{name}-informer-staleness": informers_fresh,
        f"{name}-queue-progress": queue_progress,
    }


def controller_manager_contributors(manager
                                    ) -> Dict[str, Callable[[], bool]]:
    """Controller-manager liveness: informers synced, and every control
    loop that was started still has a live worker thread."""
    def informers_synced() -> bool:
        return _informers_synced(manager.informers)

    def controllers_running() -> bool:
        for c in getattr(manager, "controllers", ()):
            t = getattr(c, "_thread", None)
            if t is not None and not t.is_alive():
                return False
        return True

    return {
        "controller-manager-informers-synced": informers_synced,
        "controller-manager-loops-running": controllers_running,
    }


def leaderelection_contributor(elector, name: str = "leader-election"
                               ) -> Dict[str, Callable[[], bool]]:
    """Leader status as a check: healthy while the elector is running
    (leading OR standing by) — a dead election loop means the component
    will never (re)acquire, which is unreadiness even though the process
    lives. A standby is READY by design (the reference's healthz does
    not fail followers)."""
    def alive() -> bool:
        t = getattr(elector, "_thread", None)
        if t is not None:
            return t.is_alive()
        # step()-driven electors (the chaos harness) have no thread;
        # they are healthy while not stopped
        return not getattr(elector, "_stop", threading.Event()).is_set()
    return {name: alive}


def replication_contributor(replica, max_lag_records: int = 1024,
                            name: str = "replication-lag"
                            ) -> Dict[str, Callable[[], bool]]:
    """Replica readiness as a check: unready while the follower trails
    the primary by more than `max_lag_records` rv units (the last
    observe_lag() sample) — a standby that far behind would lose
    acknowledged writes if promoted, so load balancers must stop
    treating it as a viable failover target. A PROMOTED replica is
    always ready (it IS the primary now; lag is moot)."""
    def caught_up() -> bool:
        if getattr(replica, "promoted", False):
            return True
        return replica.last_lag_records <= max_lag_records
    return {name: caught_up}


class HealthzServer:
    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 health: Optional[HealthChecks] = None):
        self.registry = registry
        self.health = health if health is not None else HealthChecks()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _write(self, code: int, body: bytes,
                       ctype: str = "text/plain") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz") or \
                        self.path.startswith("/readyz") or \
                        self.path.startswith("/livez"):
                    failed = outer.health.failed()
                    if failed:
                        self._write(500, ("unhealthy: " +
                                          ",".join(failed)).encode())
                    else:
                        self._write(200, b"ok")
                elif self.path.startswith("/metrics"):
                    if outer.registry is None:
                        self._write(404, b"no metrics registry")
                    else:
                        self._write(200, outer.registry.expose().encode(),
                                    "text/plain; version=0.0.4")
                else:
                    self._write(404, b"not found")

            def do_DELETE(self):
                # ref: server.go:287-291 DELETE /metrics -> metrics.Reset()
                if self.path.startswith("/metrics") and \
                        outer.registry is not None:
                    outer.registry.reset()
                    self._write(200, b"metrics reset")
                else:
                    self._write(404, b"not found")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def checks(self) -> Dict[str, Callable[[], bool]]:
        """Back-compat view of the named checks."""
        return dict(self.health.items())

    def add_check(self, name: str, fn: Callable[[], bool]) -> None:
        self.health.add(name, fn)

    def start(self) -> "HealthzServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="healthz")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
