"""Component health + metrics serving.

Ref: apiserver/pkg/server/healthz (every component serves /healthz with
named checks) and the scheduler's insecure serving mux which also exposes
/metrics with DELETE -> Reset (cmd/kube-scheduler/app/server.go:194-211,
:287-291).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from .metrics import Registry


class HealthzServer:
    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.checks: Dict[str, Callable[[], bool]] = {"ping": lambda: True}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _write(self, code: int, body: bytes,
                       ctype: str = "text/plain") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz") or \
                        self.path.startswith("/readyz") or \
                        self.path.startswith("/livez"):
                    failed = [n for n, fn in outer.checks.items()
                              if not _safe(fn)]
                    if failed:
                        self._write(500, ("unhealthy: " +
                                          ",".join(failed)).encode())
                    else:
                        self._write(200, b"ok")
                elif self.path.startswith("/metrics"):
                    if outer.registry is None:
                        self._write(404, b"no metrics registry")
                    else:
                        self._write(200, outer.registry.expose().encode(),
                                    "text/plain; version=0.0.4")
                else:
                    self._write(404, b"not found")

            def do_DELETE(self):
                # ref: server.go:287-291 DELETE /metrics -> metrics.Reset()
                if self.path.startswith("/metrics") and \
                        outer.registry is not None:
                    outer.registry.reset()
                    self._write(200, b"metrics reset")
                else:
                    self._write(404, b"not found")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def add_check(self, name: str, fn: Callable[[], bool]) -> None:
        self.checks[name] = fn

    def start(self) -> "HealthzServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="healthz")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def _safe(fn) -> bool:
    try:
        return bool(fn())
    except Exception:
        return False
