"""Cluster PKI helpers — CA, serving/client certs, CSR signing.

Ref: the reference's cert machinery spread over cmd/kubeadm/app/phases/
certs, staging/src/k8s.io/client-go/util/cert and
pkg/controller/certificates/signer. Backed by the `cryptography` package;
PEM in, PEM out so the artifacts interoperate with openssl.

`cryptography` is an OPTIONAL dependency: this module (and everything
that imports it — the CSR controllers, kubeadm, the x509 authenticator)
must stay importable without it, so the import is deferred to first use
and every entry point raises a clear ImportError via require() instead of
failing at import time. Tests skip on HAVE_CRYPTOGRAPHY.
"""

from __future__ import annotations

import datetime
from typing import List, Optional, Tuple

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - exercised in slim containers
    HAVE_CRYPTOGRAPHY = False

_ONE_DAY = datetime.timedelta(days=1)


def require() -> None:
    """Raise a clear error where a PKI operation actually needs the
    optional dependency (import keeps working without it)."""
    if not HAVE_CRYPTOGRAPHY:
        raise ImportError(
            "the 'cryptography' package is required for certificate "
            "operations but is not installed")


def _key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def _pem_cert(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def new_ca(common_name: str = "kubernetes-ca",
           days: int = 3650) -> Tuple[bytes, bytes]:
    """(cert_pem, key_pem) for a self-signed CA."""
    require()
    key = _key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return _pem_cert(cert), _pem_key(key)


def issue_cert(ca_cert_pem: bytes, ca_key_pem: bytes, common_name: str,
               organizations: Tuple[str, ...] = (),
               sans: Tuple[str, ...] = (), days: int = 365,
               server: bool = False, client: bool = True
               ) -> Tuple[bytes, bytes]:
    """(cert_pem, key_pem) signed by the CA. CN -> user name, O -> groups
    (the reference's x509 authenticator mapping)."""
    require()
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = _key()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
        + [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
           for o in organizations])
    now = datetime.datetime.now(datetime.timezone.utc)
    usages = []
    if client:
        usages.append(ExtendedKeyUsageOID.CLIENT_AUTH)
    if server:
        usages.append(ExtendedKeyUsageOID.SERVER_AUTH)
    builder = (x509.CertificateBuilder()
               .subject_name(name).issuer_name(ca_cert.subject)
               .public_key(key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - _ONE_DAY)
               .not_valid_after(now + datetime.timedelta(days=days))
               .add_extension(x509.ExtendedKeyUsage(usages), critical=False))
    if sans:
        alts: List[x509.GeneralName] = []
        for s in sans:
            try:
                import ipaddress
                alts.append(x509.IPAddress(ipaddress.ip_address(s)))
            except ValueError:
                alts.append(x509.DNSName(s))
        builder = builder.add_extension(
            x509.SubjectAlternativeName(alts), critical=False)
    cert = builder.sign(ca_key, hashes.SHA256())
    return _pem_cert(cert), _pem_key(key)


def _san_entries(sans: Tuple[str, ...]) -> List[x509.GeneralName]:
    import ipaddress
    alts: List[x509.GeneralName] = []
    for s in sans:
        try:
            alts.append(x509.IPAddress(ipaddress.ip_address(s)))
        except ValueError:
            alts.append(x509.DNSName(s))
    return alts


def new_csr(common_name: str,
            organizations: Tuple[str, ...] = (),
            sans: Tuple[str, ...] = ()) -> Tuple[bytes, bytes]:
    """(csr_pem, key_pem) — what a kubelet submits as a
    CertificateSigningRequest. Serving CSRs carry the node's
    hostnames/IPs as SubjectAlternativeNames (ref: the kubelet's
    certificate.Manager requests SANs for kubelet-serving)."""
    require()
    key = _key()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
        + [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
           for o in organizations])
    builder = x509.CertificateSigningRequestBuilder().subject_name(name)
    if sans:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(_san_entries(sans)), critical=False)
    csr = builder.sign(key, hashes.SHA256())
    return csr.public_bytes(serialization.Encoding.PEM), _pem_key(key)


def sign_csr(ca_cert_pem: bytes, ca_key_pem: bytes, csr_pem: bytes,
             days: int = 365, server: bool = False) -> bytes:
    """cert_pem for a CSR, preserving its subject (the csrsigning
    controller's core)."""
    require()
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    csr = x509.load_pem_x509_csr(csr_pem)
    if not csr.is_signature_valid:
        raise ValueError("CSR signature invalid")
    now = datetime.datetime.now(datetime.timezone.utc)
    usages = [ExtendedKeyUsageOID.SERVER_AUTH] if server \
        else [ExtendedKeyUsageOID.CLIENT_AUTH]
    builder = (x509.CertificateBuilder()
               .subject_name(csr.subject).issuer_name(ca_cert.subject)
               .public_key(csr.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - _ONE_DAY)
               .not_valid_after(now + datetime.timedelta(days=days))
               .add_extension(x509.ExtendedKeyUsage(usages),
                              critical=False))
    if server:
        # serving certs are useless without SANs — TLS stacks (including
        # Python's ssl) ignore CN for hostname verification, so the CSR's
        # requested SubjectAlternativeName must survive signing (ref: the
        # signer whitelists and preserves requested SANs)
        try:
            san = csr.extensions.get_extension_for_class(
                x509.SubjectAlternativeName)
            builder = builder.add_extension(san.value, critical=False)
        except x509.ExtensionNotFound:
            pass
    cert = builder.sign(ca_key, hashes.SHA256())
    return _pem_cert(cert)


def _subject(name: x509.Name) -> Tuple[str, Tuple[str, ...]]:
    cn = ""
    orgs: List[str] = []
    for attr in name:
        if attr.oid == NameOID.COMMON_NAME:
            cn = str(attr.value)
        elif attr.oid == NameOID.ORGANIZATION_NAME:
            orgs.append(str(attr.value))
    return cn, tuple(orgs)


def subject_of(cert_pem: bytes) -> Tuple[str, Tuple[str, ...]]:
    """(common_name, organizations) — the x509 authenticator's user
    mapping (ref: authentication/request/x509: CommonNameUserConversion)."""
    require()
    return _subject(x509.load_pem_x509_certificate(cert_pem).subject)


def csr_subject_of(csr_pem: bytes) -> Tuple[str, Tuple[str, ...]]:
    require()
    return _subject(x509.load_pem_x509_csr(csr_pem).subject)


def ca_cert_hash(ca_cert_pem: bytes) -> str:
    """kubeadm's discovery-token-ca-cert-hash: sha256 over the CA's
    SubjectPublicKeyInfo DER (ref: kubeadm pubkeypin)."""
    require()
    import hashlib
    cert = x509.load_pem_x509_certificate(ca_cert_pem)
    spki = cert.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return "sha256:" + hashlib.sha256(spki).hexdigest()


def csr_sans_of(csr_pem: bytes) -> Tuple[str, ...]:
    """Requested SubjectAlternativeNames (DNS names + IPs as strings)."""
    require()
    csr = x509.load_pem_x509_csr(csr_pem)
    try:
        san = csr.extensions.get_extension_for_class(
            x509.SubjectAlternativeName)
    except x509.ExtensionNotFound:
        return ()
    out: List[str] = []
    for entry in san.value:
        out.append(str(entry.value))
    return tuple(out)
