"""Step tracing — the k8s.io/utils/trace analog.

Ref: utiltrace.Trace as used per scheduling attempt
(generic_scheduler.go:185-186 creates one, steps at :204,223,246, and the
whole trace logs only when total time exceeds a threshold — 100ms there).
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional, Tuple


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[float, str]] = []
        self._nested: List["Trace"] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def nest(self, name: str, **fields) -> "Trace":
        t = Trace(name, **fields)
        self._nested.append(t)
        return t

    def total_ms(self) -> float:
        return (time.perf_counter() - self.start) * 1000.0

    def log_if_long(self, threshold_ms: float = 100.0,
                    out=None) -> Optional[str]:
        """Render + emit when total exceeds the threshold (ref:
        Trace.LogIfLong); returns the rendering (tests) or None."""
        if self.total_ms() < threshold_ms:
            return None
        text = self.render()
        print(text, file=out or sys.stderr)
        return text

    def render(self) -> str:
        kv = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" {kv} '
                 f"(total {self.total_ms():.1f}ms):"]
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  step {((ts - prev) * 1000):.1f}ms: {msg}")
            prev = ts
        for t in self._nested:
            lines.extend("  " + line for line in t.render().splitlines())
        return "\n".join(lines)
