"""Step tracing — the k8s.io/utils/trace analog.

Ref: utiltrace.Trace as used per scheduling attempt
(generic_scheduler.go:185-186 creates one, steps at :204,223,246, and the
whole trace logs only when total time exceeds a threshold — 100ms there).

The clock is INJECTABLE (default REAL_CLOCK): a Trace created on the
chaos/serving harnesses' FakeClock measures virtual time, so threshold
logic is deterministic under test instead of blind to stepped clocks.
Intervals read `clock.monotonic()` — perf_counter on the real clock (an
NTP step must not suppress a slow-attempt log or fabricate one), virtual
time on FakeClock. Slow traces go through the logging module (logger
"kubernetes_tpu.trace"), not bare stderr prints; log_if_long still
returns the rendered string so tests can assert on it.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from .clock import Clock, REAL_CLOCK

LOGGER = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str, clock: Clock = REAL_CLOCK, **fields):
        self.name = name
        self.fields = fields
        self.clock = clock
        self.start = clock.monotonic()
        self.steps: List[Tuple[float, str]] = []
        self._nested: List["Trace"] = []

    def step(self, msg: str) -> None:
        self.steps.append((self.clock.monotonic(), msg))

    def nest(self, name: str, **fields) -> "Trace":
        t = Trace(name, clock=self.clock, **fields)
        self._nested.append(t)
        return t

    def total_ms(self) -> float:
        return (self.clock.monotonic() - self.start) * 1000.0

    def log_if_long(self, threshold_ms: float = 100.0,
                    logger: Optional[logging.Logger] = None
                    ) -> Optional[str]:
        """Render + emit when total exceeds the threshold (ref:
        Trace.LogIfLong); returns the rendering (tests) or None."""
        if self.total_ms() < threshold_ms:
            return None
        text = self.render()
        (logger or LOGGER).warning("%s", text)
        return text

    def render(self) -> str:
        kv = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" {kv} '
                 f"(total {self.total_ms():.1f}ms):"]
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  step {((ts - prev) * 1000):.1f}ms: {msg}")
            prev = ts
        for t in self._nested:
            lines.extend("  " + line for line in t.render().splitlines())
        return "\n".join(lines)
