"""Cross-cutting utilities (clock, heap, backoff)."""

from .clock import Clock, FakeClock, REAL_CLOCK, now_iso

__all__ = ["Clock", "FakeClock", "REAL_CLOCK", "now_iso"]
