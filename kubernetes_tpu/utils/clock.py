"""Injectable clocks (ref: k8s.io/utils/clock — the scheduler queue and
backoff take an injected clock so tests control time deterministically)."""

from __future__ import annotations

import threading
import time
from datetime import datetime, timezone


class Clock:
    def now(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        """Interval measurement: a source that never steps backwards
        (time.time can — NTP), so durations computed from two reads are
        always >= 0. FakeClock unifies the two (virtual time only moves
        forward), which is what keeps traces deterministic under test."""
        return time.perf_counter()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Manually stepped clock; sleep() advances virtual time instantly."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def now(self) -> float:
        with self._lock:
            return self._now

    def monotonic(self) -> float:
        return self.now()

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._cond:
            self._now += seconds
            self._cond.notify_all()


REAL_CLOCK = Clock()


def now_iso(clock: Clock = REAL_CLOCK) -> str:
    """RFC3339 with microseconds (the reference's MicroTime precision —
    plain second granularity makes sub-second grace periods flap)."""
    return datetime.fromtimestamp(clock.now(), tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ")


def parse_iso(ts: str):
    """RFC3339 (with or without fractional seconds) -> unix seconds, or
    None on malformed input."""
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.strptime(ts, fmt) \
                .replace(tzinfo=timezone.utc).timestamp()
        except (ValueError, TypeError):
            continue
    return None
