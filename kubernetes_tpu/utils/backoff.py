"""Exponential backoff with deterministic jitter — the shared retry helper.

Ref: k8s.io/client-go/util/retry (RetryOnConflict / OnError with a
wait.Backoff) and the reference's DefaultRetry{Steps:5, Duration:10ms,
Factor:1.0, Jitter:0.1}. Control-plane writes here used to swallow
failures (`except Exception: pass`); every such site now routes through
`retry()` so transient API errors are retried with backoff, logged once
on give-up, and counted in utils/metrics.RobustnessMetrics.

Jitter is DETERMINISTIC: it derives from a seeded `random.Random` keyed
by (seed, op) so a chaos run replayed from the same seed sleeps the same
virtual durations — `(seed, schedule)` fully reproduces a run (the
chaos/ subsystem's contract). Sleeps go through the injected Clock, so a
FakeClock makes retries free in tests and soaks.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple, Type

from .clock import Clock, REAL_CLOCK

logger = logging.getLogger("backoff")


@dataclass(frozen=True)
class BackoffPolicy:
    """Delay schedule: base * factor^n, jittered ±(jitter * delay), capped.

    `attempts` counts CALLS, not retries: attempts=4 means one initial
    try plus up to three retries."""

    base: float = 0.05
    factor: float = 2.0
    cap: float = 2.0
    attempts: int = 4
    jitter: float = 0.2

    def delays(self, seed: Optional[int] = None, op: str = "") -> Iterator[float]:
        """The (attempts - 1) sleep durations between calls."""
        # string seeding hashes via sha512 — stable across processes,
        # unlike tuple seeding which rides the salted builtin hash()
        rng = random.Random(f"{seed if seed is not None else 0}:{op}")
        delay = self.base
        for _ in range(max(0, self.attempts - 1)):
            jit = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield min(self.cap, delay) * jit
            delay *= self.factor

    def delays_forever(self, seed: Optional[int] = None,
                       op: str = "") -> Iterator[float]:
        """The retry-forever schedule: the policy's escalation, then its
        cap unjittered for good — for loops that must never exhaust
        (informer reflectors, replication followers)."""
        yield from self.delays(seed=seed, op=op)
        while True:
            yield self.cap


#: the control-plane default (nodelifecycle patches, scheduler binds)
DEFAULT_POLICY = BackoffPolicy()


def retry(fn: Callable[[], object], *,
          policy: BackoffPolicy = DEFAULT_POLICY,
          clock: Clock = REAL_CLOCK,
          give_up_on: Tuple[Type[BaseException], ...] = (),
          metrics=None, component: str = "", op: str = "",
          seed: Optional[int] = None):
    """Call `fn` until it succeeds or the policy is exhausted.

    Exceptions in `give_up_on` are PERMANENT (NotFound for a deleted
    object, Conflict the caller handles itself): re-raised immediately,
    uncounted — retrying a 404 only delays the informer's cleanup.
    Everything else is transient: counted in `metrics.api_retries`
    (RobustnessMetrics), slept through the injected clock, retried.
    Exhaustion logs once, counts `metrics.api_give_ups`, and re-raises
    the last error so callers' requeue machinery still fires.
    """
    last: Optional[BaseException] = None
    for delay in policy.delays(seed=seed, op=op):
        try:
            return fn()
        except give_up_on:
            raise
        except Exception as e:  # transient: back off and retry
            last = e
            if metrics is not None:
                metrics.api_retries.inc(component=component, op=op)
            # a server-supplied hint (TooManyRequestsError carries the
            # parsed Retry-After) is a FLOOR under the backoff delay:
            # retrying sooner than the server asked just re-joins the
            # overload it was shed from
            ra = getattr(e, "retry_after", None)
            if ra:
                delay = max(delay, float(ra))
            clock.sleep(delay)
    try:
        return fn()
    except give_up_on:
        raise
    except Exception as e:
        last = e  # the FINAL attempt's error is what the log must show
        if metrics is not None:
            metrics.api_give_ups.inc(component=component, op=op)
        logger.warning("%s/%s failed after %d attempts (last: %r)",
                       component or "?", op or "?", policy.attempts, last)
        raise
