"""kube-controller-manager entry point.

Ref: cmd/kube-controller-manager/app (controllermanager.go Run — leader
election wrapping StartControllers against the shared informer factory).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from ..apiserver.httpclient import HTTPClient
from ..controllers import ControllerManager
from ..state.leaderelection import LeaderElector


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-controller-manager")
    p.add_argument("--master", required=True)
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--node-monitor-period", type=float, default=5.0)
    p.add_argument("--node-monitor-grace-period", type=float, default=40.0)
    p.add_argument("--pod-eviction-timeout", type=float, default=300.0)
    p.add_argument("--cluster-signing-cert-file", default=None,
                   help="cluster CA certificate for the CSR signer")
    p.add_argument("--cluster-signing-key-file", default=None)
    p.add_argument("--kubeconfig-token", default=None,
                   help="bearer token for a secured master")
    p.add_argument("--certificate-authority", default=None,
                   help="CA file pinning an https master")
    args = p.parse_args(argv)
    if bool(args.cluster_signing_cert_file) != \
            bool(args.cluster_signing_key_file):
        p.error("--cluster-signing-cert-file and "
                "--cluster-signing-key-file must be given together")

    client = HTTPClient(args.master, token=args.kubeconfig_token,
                        ca_file=args.certificate_authority)
    cluster_ca = None
    if args.cluster_signing_cert_file:
        cluster_ca = (open(args.cluster_signing_cert_file, "rb").read(),
                      open(args.cluster_signing_key_file, "rb").read())
    mgr = ControllerManager(
        client,
        node_monitor_period=args.node_monitor_period,
        node_grace_period=args.node_monitor_grace_period,
        pod_eviction_timeout=args.pod_eviction_timeout,
        cluster_ca=cluster_ca)
    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    if args.leader_elect:
        def lost_lease():
            # ref: controllermanager.go OnStoppedLeading -> Fatalf; exit and
            # let the supervisor restart a fresh process
            mgr.stop()
            stop.set()
        elector = LeaderElector(
            client, name="kube-controller-manager",
            identity=f"{os.uname().nodename}_{os.getpid()}",
            on_started_leading=mgr.start,
            on_stopped_leading=lost_lease)
        elector.start()
        stop.wait()
        elector.stop()
    else:
        mgr.start()
        stop.wait()
        mgr.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
