"""kube-replica entry point: a follower read server.

Ref: etcd learners serving follower reads / the apiserver's
"watch from cache". A StoreReplica follows --primary over the same
list+watch protocol the informers use (preserving the primary's
resourceVersions), and a read-only APIServer over the follower store
serves LIST and watch to informer fleets — the replica read fan-out's
own process, so the primary sheds its read path onto a second CPU.
Writes against this server answer 503 until the replica is promoted;
/readyz carries the replication-lag contributor, so a load balancer
(or the bench harness) can gate a lagging follower out of rotation.

The replication stream's encoding follows KTPU_WIRE exactly like any
other client, so a binary-wire fleet replicates over binary frames too.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-replica")
    p.add_argument("--primary", required=True,
                   help="primary apiserver base URL to follow")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--data-dir", default=None,
                   help="journal applied records (replayed on restart)")
    args = p.parse_args(argv)

    from ..apiserver.httpclient import HTTPClient
    from ..apiserver.server import APIServer
    from ..state.replication import ReadOnlyStore, StoreReplica

    wal_path = None
    if args.data_dir:
        import os
        os.makedirs(args.data_dir, exist_ok=True)
        wal_path = os.path.join(args.data_dir, "replica.wal")
    replica = StoreReplica(HTTPClient(args.primary),
                           store=ReadOnlyStore(wal_path=wal_path))
    replica.start()
    replica.wait_synced()
    srv = APIServer(store=replica.store, host=args.bind_address,
                    port=args.port)
    srv.attach_replica(replica)
    srv.start()
    print(f"following {args.primary}, serving reads on {srv.address}",
          flush=True)
    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    stop.wait()
    replica.stop()
    srv.stop()
    replica.store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
