"""kube-scheduler entry point.

Ref: cmd/kube-scheduler/app/server.go (NewSchedulerCommand :62, runCommand
:109, Run :159): load component config, optional Policy, optional leader
election, healthz+metrics serving, then Scheduler.Run against the hub.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from ..apiserver.httpclient import HTTPClient
from ..scheduler.config import (KubeSchedulerConfiguration, Policy,
                                build_scheduler)
from ..state.leaderelection import LeaderElector
from ..utils.healthz import HealthzServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-scheduler")
    p.add_argument("--master", required=True,
                   help="API server URL, e.g. http://127.0.0.1:8080")
    p.add_argument("--config", help="KubeSchedulerConfiguration JSON file")
    p.add_argument("--policy-config-file", help="Policy JSON file")
    p.add_argument("--scheduler-name", default=None)
    p.add_argument("--leader-elect", action="store_true", default=None)
    p.add_argument("--healthz-port", type=int, default=None,
                   help="healthz+metrics port (0 disables)")
    p.add_argument("--disable-preemption", action="store_true", default=None)
    args = p.parse_args(argv)

    cfg = KubeSchedulerConfiguration.from_file(args.config) if args.config \
        else KubeSchedulerConfiguration()
    # flags override the config file (component-base precedence)
    if args.policy_config_file:
        cfg.policy = Policy.from_file(args.policy_config_file)
    if args.scheduler_name is not None:
        cfg.scheduler_name = args.scheduler_name
    if args.leader_elect is not None:
        cfg.leader_election.leader_elect = args.leader_elect
    if args.healthz_port is not None:
        cfg.healthz_bind_port = args.healthz_port
    if args.disable_preemption is not None:
        cfg.disable_preemption = args.disable_preemption

    client = HTTPClient(args.master)
    sched = build_scheduler(client, cfg)

    healthz = None
    if cfg.healthz_bind_port > 0:
        healthz = HealthzServer(registry=sched.metrics.registry,
                                port=cfg.healthz_bind_port)
        healthz.add_check("scheduler",
                          lambda: sched._thread is None
                          or sched._thread.is_alive())
        healthz.start()
        print(f"healthz+metrics on {healthz.url}", flush=True)

    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    if cfg.leader_election.leader_elect:
        le = cfg.leader_election

        def lost_lease():
            # ref: server.go OnStoppedLeading -> klog.Fatalf("leaderelection
            # lost") — the process EXITS and the supervisor restarts it; a
            # stopped Scheduler is not restartable in-process (closed queue)
            sched.stop()
            stop.set()
        elector = LeaderElector(
            client, name=le.resource_name,
            identity=f"{os.uname().nodename}_{os.getpid()}",
            namespace=le.resource_namespace,
            lease_duration=le.lease_duration_seconds,
            renew_deadline=le.renew_deadline_seconds,
            retry_period=le.retry_period_seconds,
            on_started_leading=sched.start,
            on_stopped_leading=lost_lease)
        elector.start()
        stop.wait()
        elector.stop()
    else:
        sched.start()
        stop.wait()
        sched.stop()
    if healthz is not None:
        healthz.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
