"""kube-apiserver entry point.

Ref: cmd/kube-apiserver/app/server.go — here the generic server IS the
assembly (no aggregation layers yet); serves REST+watch on --port.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from ..apiserver.server import APIServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    args = p.parse_args(argv)
    srv = APIServer(host=args.bind_address, port=args.port).start()
    print(f"serving on {srv.address}", flush=True)
    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
