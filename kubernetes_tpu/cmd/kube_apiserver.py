"""kube-apiserver entry point.

Ref: cmd/kube-apiserver/app/server.go — here the generic server IS the
assembly (no aggregation layers yet); serves REST+watch on --port.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from ..apiserver.server import APIServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--data-dir", default=None,
                   help="enable WAL persistence (replayed on restart)")
    p.add_argument("--wal-sync", action="store_true",
                   help="fdatasync each transaction")
    p.add_argument("--wal-compact-bytes", type=int, default=64 << 20,
                   help="compact the WAL when it exceeds this size")
    p.add_argument("--token-auth-file", default=None,
                   help="CSV token,user[,uid],group1;group2 — enables authn "
                        "(+ default-deny RBAC; system:masters gets all)")
    p.add_argument("--audit-log-path", default=None,
                   help="append one JSON audit line per request")
    p.add_argument("--tls-cert-file", default=None)
    p.add_argument("--tls-private-key-file", default=None)
    p.add_argument("--client-ca-file", default=None,
                   help="verify client certs against this CA; their "
                        "CN/O become user/groups (x509 authn)")
    args = p.parse_args(argv)
    if args.client_ca_file and not args.tls_cert_file:
        # client certs can only arrive over TLS; without a serving cert
        # the CA would silently never be consulted and every request
        # would be rejected by default-deny RBAC
        p.error("--client-ca-file requires --tls-cert-file/"
                "--tls-private-key-file")
    store = None
    wal_file = None
    if args.data_dir:
        import os

        from ..state.store import Store
        os.makedirs(args.data_dir, exist_ok=True)
        wal_file = os.path.join(args.data_dir, "store.wal")
        store = Store(wal_path=wal_file, wal_sync=args.wal_sync)
    srv = APIServer(store=store, host=args.bind_address,
                    port=args.port, audit_log_path=args.audit_log_path,
                    tls_cert_file=args.tls_cert_file,
                    tls_key_file=args.tls_private_key_file,
                    client_ca_file=args.client_ca_file)
    if args.client_ca_file and not args.token_auth_file:
        # x509-only authn: cert identities + default-deny RBAC
        from ..apiserver.auth import CertAuthenticator, RBACAuthorizer
        srv.authenticator = CertAuthenticator()
        authz = RBACAuthorizer()
        authz.grant("group:system:masters", ["*"], ["*"])
        authz.use_store(srv.client)
        srv.authorizer = authz
    if args.token_auth_file:
        from ..apiserver.auth import (RBACAuthorizer, TokenAuthenticator,
                                      UserInfo)
        authn = TokenAuthenticator()
        with open(args.token_auth_file) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                fields = [x.strip() for x in line.split(",")]
                if len(fields) < 2:
                    print(f"skipping malformed token line: {line!r}",
                          flush=True)
                    continue
                token, user = fields[0], fields[1]
                # 3 fields = token,user,groups; 4+ = token,user,uid,groups
                # (the reference's --token-auth-file CSV)
                groups_field = fields[3] if len(fields) >= 4 else (
                    fields[2] if len(fields) == 3 else "")
                authn.add(token, UserInfo(
                    user, tuple(g for g in groups_field.split(";") if g)))
        authz = RBACAuthorizer()
        # the bootstrap superuser binding (ref: system:masters)
        authz.grant("group:system:masters", ["*"], ["*"])
        # stored Role/ClusterRole(+Binding) objects feed the live policy
        authz.use_store(srv.client)
        if args.client_ca_file:
            from ..apiserver.auth import CertAuthenticator
            authn = CertAuthenticator(fallback=authn)
        srv.authenticator = authn
        srv.authorizer = authz
    srv.start()
    compactor = None
    if store is not None:
        import os

        def compact_loop():
            # size-triggered compaction bounds replay time by live objects,
            # not total write history (the etcd snapshot analog)
            while not stop.wait(30.0):
                try:
                    if os.path.getsize(wal_file) > args.wal_compact_bytes:
                        store.compact()
                except Exception:
                    pass
        compactor = threading.Thread(target=compact_loop, daemon=True)
    print(f"serving on {srv.address}", flush=True)
    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    if compactor is not None:
        compactor.start()
    stop.wait()
    srv.stop()
    if store is not None:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
