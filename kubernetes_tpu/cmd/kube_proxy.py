"""kube-proxy entry point.

Ref: cmd/kube-proxy — ProxyServer against the hub; the dataplane here is
the inspectable fake (no kernel netfilter in scope).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from ..apiserver.httpclient import HTTPClient
from ..node.proxy import ProxyServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-proxy")
    p.add_argument("--master", required=True)
    args = p.parse_args(argv)
    proxy = ProxyServer(HTTPClient(args.master)).start()
    stop = threading.Event()

    def shutdown(*_):
        stop.set()
    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    stop.wait()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
