"""hyperkube — every component behind one entry point.

Ref: cmd/hyperkube (the all-in-one multiplexer binary). Usage:

    python -m kubernetes_tpu.cmd.hyperkube <component> [args...]

where component is one of: kube-apiserver, kube-scheduler,
kube-controller-manager, kube-proxy, kubectl, kubeadm.
"""

from __future__ import annotations

import sys

COMPONENTS = {
    "kube-apiserver": "kube_apiserver",
    "apiserver": "kube_apiserver",
    "kube-scheduler": "kube_scheduler",
    "scheduler": "kube_scheduler",
    "kube-controller-manager": "kube_controller_manager",
    "controller-manager": "kube_controller_manager",
    "kube-proxy": "kube_proxy",
    "proxy": "kube_proxy",
    "kubectl": "kubectl",
    "kubeadm": "kubeadm",
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: hyperkube <component> [args...]\n"
              f"components: {', '.join(sorted(set(COMPONENTS)))}")
        return 0 if argv else 1
    name = argv[0]
    mod_name = COMPONENTS.get(name)
    if mod_name is None:
        print(f"unknown component {name!r}; one of "
              f"{', '.join(sorted(set(COMPONENTS)))}", file=sys.stderr)
        return 1
    import importlib
    mod = importlib.import_module(f".{mod_name}", package=__package__)
    return mod.main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
