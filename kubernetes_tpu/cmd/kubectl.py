"""kubectl subset — get / describe / create / apply / delete / scale /
cordon / uncordon.

Ref: pkg/kubectl/cmd (45+ cobra subcommands over cli-runtime's resource
builder and pkg/printers). The subset here covers the verbs the judge's
day-one user needs against the hub; output follows the reference's table
shapes (NAME/READY/STATUS/... for pods, NAME/STATUS/AGE for nodes).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..api import serde
from ..api.meta import controller_ref
from ..apiserver.httpclient import HTTPClient
from ..runtime.scheme import SCHEME
from ..utils.clock import parse_iso


def _client(args) -> HTTPClient:
    return HTTPClient(args.master)


def _resolve(resource: str, client=None):
    aliases = {
        "po": "pods", "pod": "pods",
        "no": "nodes", "node": "nodes",
        "deploy": "deployments", "deployment": "deployments",
        "rs": "replicasets", "replicaset": "replicasets",
        "svc": "services", "service": "services",
        "ns": "namespaces", "namespace": "namespaces",
        "pv": "persistentvolumes", "pvc": "persistentvolumeclaims",
        "sc": "storageclasses", "pdb": "poddisruptionbudgets",
        "ds": "daemonsets", "sts": "statefulsets", "job": "jobs",
        "cj": "cronjobs", "ev": "events", "ep": "endpoints",
        "hpa": "horizontalpodautoscalers",
        "crd": "customresourcedefinitions",
        "crds": "customresourcedefinitions",
        "quota": "resourcequotas", "limits": "limitranges",
        "cm": "configmaps", "configmap": "configmaps",
        "secret": "secrets", "sa": "serviceaccounts",
        "serviceaccount": "serviceaccounts",
        "role": "roles", "rolebinding": "rolebindings",
        "clusterrole": "clusterroles",
        "clusterrolebinding": "clusterrolebindings",
    }
    resource = aliases.get(resource, resource)
    cls = SCHEME.type_for_resource(resource)
    if cls is None and client is not None:
        # discovery: an unknown resource may be a server-side CRD — fetch
        # definitions and register the dynamic type locally (the
        # reference's RESTMapper discovery against /apis)
        from ..runtime.crd import CustomResourceDefinition, register_crd
        try:
            for crd in client.resource(CustomResourceDefinition).list():
                names = crd.spec.names
                if resource in (names.plural, names.singular,
                                names.kind.lower(), *names.short_names):
                    register_crd(crd)
                    resource = names.plural
                    cls = SCHEME.type_for_resource(resource)
                    break
        except Exception:
            pass
    if cls is None:
        raise SystemExit(f"error: the server doesn't have a resource "
                         f"type \"{resource}\"")
    return resource, cls


def _age(ts) -> str:
    import time
    t = parse_iso(ts or "")
    if t is None:
        return "<unknown>"
    s = int(time.time() - t)
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    if s < 172800:
        return f"{s // 3600}h"
    return f"{s // 86400}d"


def _print_table(rows, headers) -> None:
    widths = [max(len(str(r[i])) for r in [headers] + rows)
              for i in range(len(headers))]
    for r in [headers] + rows:
        print("   ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip())


def _pod_row(p):
    total = len(p.spec.containers)
    ready = sum(1 for cs in p.status.container_statuses if cs.ready)
    status = p.status.phase
    if p.metadata.deletion_timestamp is not None:
        status = "Terminating"
    elif p.status.phase == "Pending" and p.spec.node_name:
        status = "ContainerCreating"
    return [p.metadata.name, f"{ready}/{total}", status,
            p.spec.node_name or "<none>",
            _age(p.metadata.creation_timestamp)]


def _node_row(n):
    ready = next((c.status for c in n.status.conditions
                  if c.type == "Ready"), "Unknown")
    status = "Ready" if ready == "True" else "NotReady"
    if n.spec.unschedulable:
        status += ",SchedulingDisabled"
    return [n.metadata.name, status, _age(n.metadata.creation_timestamp)]


def cmd_get(args) -> int:
    resource, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    items = [rc.get(args.name, namespace=args.namespace)] if args.name \
        else rc.list(namespace=None if args.all_namespaces
                     else args.namespace)
    if args.output == "json":
        out = [serde.encode(o) for o in items]
        print(json.dumps(out[0] if args.name else
                         {"apiVersion": "v1", "kind": "List", "items": out},
                         indent=2))
        return 0
    if not items:
        print(f"No resources found in {args.namespace} namespace.")
        return 0
    if resource == "pods":
        _print_table([_pod_row(p) for p in items],
                     ["NAME", "READY", "STATUS", "NODE", "AGE"])
    elif resource == "nodes":
        _print_table([_node_row(n) for n in items],
                     ["NAME", "STATUS", "AGE"])
    elif resource == "deployments":
        _print_table(
            [[d.metadata.name,
              f"{d.status.ready_replicas}/{d.spec.replicas}",
              d.status.updated_replicas, d.status.available_replicas,
              _age(d.metadata.creation_timestamp)] for d in items],
            ["NAME", "READY", "UP-TO-DATE", "AVAILABLE", "AGE"])
    else:
        _print_table(
            [[o.metadata.name, _age(o.metadata.creation_timestamp)]
             for o in items],
            ["NAME", "AGE"])
    return 0


def cmd_describe(args) -> int:
    _, cls = _resolve(args.resource, _client(args))
    obj = _client(args).resource(cls, args.namespace).get(
        args.name, namespace=args.namespace)
    data = serde.encode(obj)

    def walk(d, indent=0):
        pad = "  " * indent
        for k, v in d.items():
            if isinstance(v, dict) and v:
                print(f"{pad}{k}:")
                walk(v, indent + 1)
            elif isinstance(v, list) and v:
                print(f"{pad}{k}:")
                for item in v:
                    if isinstance(item, dict):
                        walk(item, indent + 1)
                        print()
                    else:
                        print(f"{pad}  - {item}")
            elif v not in (None, "", [], {}):
                print(f"{pad}{k}: {v}")
    walk(data)
    return 0


def _load_manifest_dicts(path: str):
    raw = sys.stdin.read() if path == "-" else open(path).read()
    data = json.loads(raw)
    return data.get("items", [data]) if isinstance(data, dict) else data


def _load_manifests(path: str):
    return [SCHEME.decode_any(d) for d in _load_manifest_dicts(path)]


def _decode_with_discovery(raw: dict, client):
    """decode_any, falling back to server-side CRD discovery for custom
    kinds the local scheme hasn't seen."""
    try:
        return SCHEME.decode_any(raw)
    except KeyError:
        from ..runtime.crd import CustomResourceDefinition, register_crd
        kind = raw.get("kind", "")
        for crd in client.resource(CustomResourceDefinition).list():
            if crd.spec.names.kind == kind:
                register_crd(crd)
                return SCHEME.decode_any(raw)
        raise


def cmd_create(args) -> int:
    client = _client(args)
    for raw in _load_manifest_dicts(args.filename):
        obj = _decode_with_discovery(raw, client)
        rc = client.resource(type(obj), obj.metadata.namespace or
                             args.namespace)
        out = rc.create(obj)
        kind = SCHEME.resource_for(obj)
        print(f"{kind}/{out.metadata.name} created")
    return 0


def cmd_apply(args) -> int:
    """Declarative apply with the reference's 3-way merge: the previous
    apply's config (the last-applied-configuration annotation) decides
    which fields WE own — fields we set before and dropped now are
    deleted; fields other writers own (defaulted values, controller
    status, foreign labels) are left alone.
    Ref: k8s.io/kubectl/pkg/cmd/apply + util/apply.go."""
    from ..api.patch import LAST_APPLIED, three_way_merge_patch
    from ..state.store import NotFoundError
    client = _client(args)
    for raw in _load_manifest_dicts(args.filename):
        # the RAW manifest is what we own — re-encoding the decoded object
        # would materialize defaulted fields (e.g. clusterIP: "") and make
        # apply claim ownership of values the user never wrote
        obj = _decode_with_discovery(raw, client)
        ns = obj.metadata.namespace or args.namespace
        rc = client.resource(type(obj), ns)
        kind = SCHEME.resource_for(obj)
        new_cfg = raw
        try:
            live = rc.get(obj.metadata.name, namespace=ns)
        except NotFoundError:
            obj.metadata.annotations[LAST_APPLIED] = \
                json.dumps(new_cfg, sort_keys=True)
            rc.create(obj)
            print(f"{kind}/{obj.metadata.name} created")
            continue
        last_applied = json.dumps(new_cfg, sort_keys=True)
        original = json.loads(
            live.metadata.annotations.get(LAST_APPLIED, "") or "{}")
        current = serde.encode(live)
        patch = three_way_merge_patch(original, new_cfg, current)
        patch.pop("status", None)  # apply never writes status
        md = patch.setdefault("metadata", {})
        md.pop("resourceVersion", None)
        from ..api.patch import json_merge_patch
        # simulate the patch: if the DECODED result equals the live object
        # (wire-level list replacements often differ textually but decode
        # identically), skip the write — it would only bump the rv and
        # wake every watcher on each re-apply
        simulated = SCHEME.decode_any({**json_merge_patch(current, patch),
                                       "apiVersion": raw.get("apiVersion"),
                                       "kind": raw.get("kind")})
        if simulated == live and \
                live.metadata.annotations.get(LAST_APPLIED) == last_applied:
            print(f"{kind}/{obj.metadata.name} unchanged")
            continue
        md.setdefault("annotations", {})[LAST_APPLIED] = last_applied
        # the patch is RFC 7386 (lists carry full replacements, no
        # $patch:delete directives) — strategic named-list merging would
        # resurrect list entries the new config dropped
        rc.merge_patch(obj.metadata.name, patch, namespace=ns,
                       strategic=False)
        print(f"{kind}/{obj.metadata.name} configured")
    return 0


def cmd_diff(args) -> int:
    """kubectl diff: unified diff of each manifest's POST-APPLY state
    against the live object — the same 3-way merge `kubectl apply` would
    perform, so diff-clean exactly when apply would print "unchanged"
    (ref: k8s.io/kubectl/pkg/cmd/diff; exit 0 clean, 1 differences)."""
    import difflib

    from ..api.patch import (LAST_APPLIED, json_merge_patch,
                             three_way_merge_patch)
    from ..state.store import NotFoundError
    client = _client(args)
    changed = False
    for raw in _load_manifest_dicts(args.filename):
        obj = _decode_with_discovery(raw, client)
        ns = obj.metadata.namespace or args.namespace
        kind = SCHEME.resource_for(obj)
        name = obj.metadata.name
        rc = client.resource(type(obj), ns)
        try:
            live = rc.get(name, namespace=ns)
        except NotFoundError:
            live = None
        if live is None:
            live_doc = {}
            merged = serde.encode(obj)
        else:
            # the exact merge cmd_apply performs: fields WE own (the
            # last-applied config) update/delete; foreign fields stay
            live_doc = serde.encode(live)
            original = json.loads(
                live.metadata.annotations.get(LAST_APPLIED, "") or "{}")
            patch = three_way_merge_patch(original, raw, live_doc)
            patch.pop("status", None)
            md = patch.setdefault("metadata", {})
            md.pop("resourceVersion", None)
            md.setdefault("annotations", {})[LAST_APPLIED] = \
                json.dumps(raw, sort_keys=True)
            merged = json_merge_patch(live_doc, patch)
        a = json.dumps(live_doc, indent=2, sort_keys=True).splitlines()
        b = json.dumps(merged, indent=2, sort_keys=True).splitlines()
        delta = list(difflib.unified_diff(
            a, b, fromfile=f"live/{kind}/{name}",
            tofile=f"merged/{kind}/{name}", lineterm=""))
        if delta:
            changed = True
            print("\n".join(delta))
    return 1 if changed else 0


def cmd_edit(args) -> int:
    """kubectl edit: dump the live object to a temp file, run $EDITOR,
    PUT the edited version back under CAS (ref: kubectl/pkg/cmd/edit)."""
    import os
    import subprocess
    import tempfile
    client = _client(args)
    resource, cls = _resolve(args.resource, client)
    rc = client.resource(cls, args.namespace)
    live = rc.get(args.name, namespace=args.namespace)
    doc = serde.encode(live)
    import shlex
    editor = shlex.split(os.environ.get("EDITOR", "vi"))
    with tempfile.NamedTemporaryFile("w+", suffix=".json",
                                     delete=False) as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        path = f.name
    # the temp file is only removed on the SUCCESS and no-change paths:
    # a parse error or CAS conflict must not destroy the user's edits
    # (the reference preserves the file and names it)
    try:
        if subprocess.call(editor + [path]) != 0:
            print(f"error: editor failed; edits preserved at {path}",
                  file=sys.stderr)
            return 1
        with open(path) as f:
            edited = json.load(f)
        if edited == doc:
            print("Edit cancelled, no changes made.")
            os.unlink(path)
            return 0
        obj = SCHEME.decode_any(edited)
        # CAS: the rv captured at read time rides the PUT, so an edit
        # raced by another writer 409s instead of clobbering
        obj.metadata.resource_version = live.metadata.resource_version
        rc.update(obj)
    except Exception as e:
        print(f"error: {e}; edits preserved at {path}", file=sys.stderr)
        return 1
    os.unlink(path)
    print(f"{resource}/{args.name} edited")
    return 0


def cmd_delete(args) -> int:
    resource, cls = _resolve(args.resource, _client(args))
    _client(args).resource(cls, args.namespace).delete(
        args.name, namespace=args.namespace)
    print(f"{resource}/{args.name} deleted")
    return 0


def cmd_scale(args) -> int:
    """Scales through the server's /scale subresource — the privilege is
    {resource}/scale, not full object update (the reference's kubectl
    scale uses the scale client the same way)."""
    from ..state.store import ConflictError
    resource, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    for attempt in range(16):
        scale = rc.get_scale(args.name, namespace=args.namespace)
        scale.spec.replicas = args.replicas
        try:
            rc.update_scale(args.name, scale, namespace=args.namespace)
            break
        except ConflictError:
            # a concurrent writer bumped the rv between get and put —
            # re-read and retry (the reference's scale client does the
            # same RetryOnConflict dance)
            continue
    else:
        raise ConflictError(f"{resource}/{args.name}: too many conflicts")
    print(f"{resource}/{args.name} scaled")
    return 0


def cmd_autoscale(args) -> int:
    """kubectl autoscale: create an HPA targeting the resource."""
    from ..api.autoscaling import (CrossVersionObjectReference,
                                   HorizontalPodAutoscaler,
                                   HorizontalPodAutoscalerSpec)
    from ..api.meta import ObjectMeta
    resource, cls = _resolve(args.resource, _client(args))
    sample = cls()
    hpa = HorizontalPodAutoscaler(
        metadata=ObjectMeta(name=args.name, namespace=args.namespace),
        spec=HorizontalPodAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind=sample.kind, name=args.name,
                api_version=sample.api_version),
            min_replicas=args.min, max_replicas=args.max,
            target_cpu_utilization_percentage=args.cpu_percent))
    _client(args).resource(HorizontalPodAutoscaler,
                           args.namespace).create(hpa)
    print(f"horizontalpodautoscaler/{args.name} autoscaled")
    return 0


def _set_unschedulable(args, value: bool, verb: str) -> int:
    def mutate(cur):
        cur.spec.unschedulable = value
        return cur
    _client(args).nodes().patch(args.name, mutate)
    print(f"node/{args.name} {verb}")
    return 0


def cmd_cordon(args) -> int:
    return _set_unschedulable(args, True, "cordoned")


def cmd_uncordon(args) -> int:
    return _set_unschedulable(args, False, "uncordoned")


def cmd_logs(args) -> int:
    """kubectl logs <pod> [-c container]: resolve the pod's node, then
    ride the apiserver->kubelet proxy to /containerLogs (ref:
    pkg/kubectl/cmd/logs + the kubelet server's log endpoint)."""
    client = _client(args)
    pod = client.pods(args.namespace).get(args.name,
                                          namespace=args.namespace)
    if not pod.spec.node_name:
        print(f"error: pod {args.name} is not scheduled yet",
              file=sys.stderr)
        return 1
    container = args.container or pod.spec.containers[0].name
    try:
        body = _proxy_get(
            args.master, pod.spec.node_name,
            f"containerLogs/{args.namespace}/{args.name}/{container}",
            timeout=15)
        sys.stdout.write(body.decode(errors="replace"))
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def _exec_via_api(master, namespace, pod_name, container, command,
                  stdin: bytes = b""):
    """One exec round trip through the pods/exec subresource. Returns
    (exitCode, output bytes)."""
    import base64
    import json as _json
    from urllib import request as urlrequest
    url = (f"{master}/api/v1/namespaces/{namespace}/pods/"
           f"{pod_name}/exec")
    body = _json.dumps({
        "container": container, "command": list(command),
        "stdin": base64.b64encode(stdin).decode()}).encode()
    req = urlrequest.Request(url, data=body,
                             headers={"Content-Type": "application/json"},
                             method="POST")
    with urlrequest.urlopen(req, timeout=15) as r:
        resp = _json.loads(r.read())
    return resp.get("exitCode", 1), base64.b64decode(resp.get("output", ""))


def cmd_exec(args) -> int:
    """kubectl exec <pod> [-c container] -- command...: runs in the
    pod's container through apiserver->kubelet (ref: pkg/kubectl/cmd/exec
    over the ExecREST/getExec transport)."""
    # argparse.REMAINDER swallows flags placed after the pod name (the
    # standard kubectl order `exec POD -c C -- cmd`): recover them here
    command = list(args.command)
    container = args.container
    while len(command) >= 2 and command[0] in ("-c", "--container"):
        container = command[1]
        command = command[2:]
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("error: a command is required", file=sys.stderr)
        return 1
    try:
        code, output = _exec_via_api(args.master, args.namespace,
                                     args.name, container, command)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(output.decode(errors="replace"))
    return code


def cmd_attach(args) -> int:
    """kubectl attach <pod> [-c container]: the container's current
    output stream (ref: pkg/kubectl/cmd/attach over AttachREST)."""
    from urllib import request as urlrequest
    url = (f"{args.master}/api/v1/namespaces/{args.namespace}/pods/"
           f"{args.name}/attach")
    if args.container:
        url += f"?container={args.container}"
    try:
        with urlrequest.urlopen(url, timeout=15) as r:
            sys.stdout.write(r.read().decode(errors="replace"))
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_cp(args) -> int:
    """kubectl cp <pod>:<path> <local> | <local> <pod>:<path> — file
    transfer over the exec transport (ref: pkg/kubectl/cmd/cp, which
    streams tar through exec; here cat/tee carry the bytes)."""
    def parse(spec):
        if ":" in spec:
            pod, _, path = spec.partition(":")
            return pod, path
        return None, spec
    src_pod, src_path = parse(args.src)
    dst_pod, dst_path = parse(args.dst)
    if (src_pod is None) == (dst_pod is None):
        print("error: exactly one of src/dst must be pod:path",
              file=sys.stderr)
        return 1
    try:
        if src_pod is not None:  # pod -> local
            code, data = _exec_via_api(args.master, args.namespace,
                                       src_pod, args.container,
                                       ["cat", src_path])
            if code != 0:
                sys.stderr.write(data.decode(errors="replace"))
                return code
            with open(dst_path, "wb") as f:
                f.write(data)
            return 0
        with open(src_path, "rb") as f:  # local -> pod
            data = f.read()
        code, out = _exec_via_api(args.master, args.namespace, dst_pod,
                                  args.container, ["tee", dst_path],
                                  stdin=data)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if code != 0:
        sys.stderr.write(out.decode(errors="replace"))
    return code


def _proxy_get(master: str, node: str, path: str, timeout: float = 4.0):
    """GET through the apiserver->kubelet proxy (shared by logs/top —
    one place owns the URL shape; the server-side dial cap is 3s, so a
    4s client timeout bounds a dead node without dead weight)."""
    from urllib import request as urlrequest
    url = f"{master}/api/v1/nodes/{node}/proxy/{path}"
    with urlrequest.urlopen(url, timeout=timeout) as r:
        return r.read()


def cmd_top(args) -> int:
    """kubectl top nodes|pods: live resource usage scraped from each
    kubelet's /stats/summary through the apiserver->kubelet proxy (ref:
    kubectl top's resource-metrics pipeline; this rides the same summary
    endpoint the HPA consumes instead of a metrics-server deployment).
    Nodes are scraped CONCURRENTLY; a node without a kubelet endpoint
    (503) is skipped, any other failure is reported."""
    import urllib.error
    from concurrent.futures import ThreadPoolExecutor
    client = _client(args)
    nodes = client.nodes().list()

    def scrape(node):
        try:
            return node, json.loads(
                _proxy_get(args.master, node.metadata.name,
                           "stats/summary")), None
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return node, None, None  # no kubelet endpoint published
            return node, None, f"HTTP {e.code}"
        except Exception as e:
            return node, None, str(e)
    with ThreadPoolExecutor(max_workers=16) as ex:
        scraped = list(ex.map(scrape, nodes))
    rows = []
    errors = 0
    for node, summary, err in scraped:
        if err is not None:
            print(f"error scraping {node.metadata.name}: {err}",
                  file=sys.stderr)
            errors += 1
            continue
        if summary is None:
            continue
        pods = summary.get("pods", [])
        if args.kind == "nodes":
            total = sum(p.get("cpu", {}).get("usageNanoCores", 0)
                        for p in pods)
            rows.append((node.metadata.name,
                         f"{total / 1_000_000:.0f}m", str(len(pods))))
        else:
            for p in pods:
                ref = p.get("podRef", {})
                if args.namespace and \
                        ref.get("namespace") != args.namespace:
                    continue
                rows.append((ref.get("name", ""),
                             f"{p.get('cpu', {}).get('usageNanoCores', 0) / 1_000_000:.0f}m",
                             node.metadata.name))
    hdr = ["NAME", "CPU(cores)", "PODS"] if args.kind == "nodes" \
        else ["NAME", "CPU(cores)", "NODE"]
    _print_table(sorted(rows), hdr)
    return 1 if errors else 0


def cmd_drain(args) -> int:
    """kubectl drain: cordon, then evict every pod off the node through
    the PDB-guarded eviction API, backing off while budgets refuse (ref:
    pkg/kubectl/cmd/drain — GetPodsForDeletion filters + evictPods loop)."""
    import time as _t
    client = _client(args)
    _set_unschedulable(args, True, "cordoned")
    pending = []
    for pod in client.pods(None).list(namespace=None):
        if pod.spec.node_name != args.name:
            continue
        ref = next((r for r in pod.metadata.owner_references
                    if r.controller), None)
        if ref is not None and ref.kind == "DaemonSet":
            if not args.ignore_daemonsets:
                print(f"error: pod {pod.metadata.name} is DaemonSet-managed"
                      f" (use --ignore-daemonsets)", file=sys.stderr)
                return 1
            print(f"ignoring DaemonSet-managed pod {pod.metadata.name}")
            continue
        if ref is None and not args.force:
            print(f"error: pod {pod.metadata.name} has no controller "
                  f"(use --force)", file=sys.stderr)
            return 1
        pending.append(pod)
    from ..state.client import TooManyDisruptions
    from ..state.store import NotFoundError
    deadline = _t.time() + args.timeout
    while pending:
        still = []
        for pod in pending:
            try:
                client.pods(pod.metadata.namespace).evict(
                    pod.metadata.name, namespace=pod.metadata.namespace)
                print(f"pod/{pod.metadata.name} evicted")
            except NotFoundError:
                pass  # already gone
            except TooManyDisruptions:
                still.append(pod)  # budget exhausted; retry after backoff
        pending = still
        if pending:
            if _t.time() > deadline:
                names = ", ".join(p.metadata.name for p in pending)
                print(f"error: drain timed out waiting for disruption "
                      f"budget; still on node: {names}", file=sys.stderr)
                return 1
            _t.sleep(min(args.poll_interval,
                         max(0.0, deadline - _t.time())))
    print(f"node/{args.name} drained")
    return 0


def cmd_rollout(args) -> int:
    """kubectl rollout status|restart <deploy|sts|ds> <name>."""
    resource, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    if args.action == "status":
        if resource != "deployments":
            print(f"error: rollout status supports deployments, "
                  f"not {resource}", file=sys.stderr)
            return 1
        import time as _t
        deadline = _t.time() + args.timeout
        while True:
            d = rc.get(args.name, namespace=args.namespace)
            if (d.status.observed_generation >= d.metadata.generation
                    and d.status.updated_replicas >= d.spec.replicas
                    and d.status.available_replicas >= d.spec.replicas
                    # no surplus old-template replicas still alive
                    and d.status.replicas == d.status.updated_replicas):
                print(f'deployment "{args.name}" successfully rolled out')
                return 0
            if _t.time() > deadline:
                print(f'Waiting for deployment "{args.name}" rollout: '
                      f'{d.status.available_replicas} of '
                      f'{d.spec.replicas} updated replicas are available',
                      file=sys.stderr)
                return 1
            _t.sleep(0.2)
    elif args.action == "history":
        if resource != "deployments":
            print("error: rollout history supports deployments",
                  file=sys.stderr)
            return 1
        from ..controllers.deployment import REVISION_ANN, HASH_LABEL
        d = rc.get(args.name, namespace=args.namespace)
        rows = []
        for rs in _owned_rses(_client(args), d):
            rev = rs.metadata.annotations.get(REVISION_ANN, "0")
            rows.append([rev, rs.metadata.name,
                         rs.spec.template.spec.containers[0].image
                         if rs.spec.template.spec.containers else ""])
        rows.sort(key=lambda r: int(r[0]))
        _print_table(rows, ["REVISION", "REPLICASET", "IMAGE"])
        return 0
    elif args.action == "undo":
        if resource != "deployments":
            print("error: rollout undo supports deployments",
                  file=sys.stderr)
            return 1
        from ..controllers.deployment import (HASH_LABEL, REVISION_ANN,
                                              DeploymentController)
        d = rc.get(args.name, namespace=args.namespace)
        rses = _owned_rses(_client(args), d)
        if not rses:
            print("error: no rollout history", file=sys.stderr)
            return 1
        cur_rev = int(d.metadata.annotations.get(REVISION_ANN, "0"))
        if args.to_revision:
            target = next((rs for rs in rses
                           if int(rs.metadata.annotations.get(
                               REVISION_ANN, "0")) == args.to_revision),
                          None)
        else:
            older = [rs for rs in rses
                     if int(rs.metadata.annotations.get(REVISION_ANN,
                                                        "0")) < cur_rev]
            target = max(older, key=DeploymentController.revision_of) \
                if older else None
        if target is None:
            print("error: revision not found", file=sys.stderr)
            return 1
        tmpl = serde.encode(target.spec.template)
        tmpl.get("metadata", {}).get("labels", {}).pop(HASH_LABEL, None)
        rc.merge_patch(args.name, {"spec": {"template": tmpl}},
                       namespace=args.namespace, strategic=False)
        print(f"deployment.apps/{args.name} rolled back")
        return 0
    elif args.action == "restart":
        if resource not in ("deployments", "statefulsets", "daemonsets"):
            print(f"error: rollout restart supports deployments/"
                  f"statefulsets/daemonsets, not {resource}",
                  file=sys.stderr)
            return 1
        # the reference stamps a restartedAt annotation into the pod
        # template, rolling every pod through the update machinery
        from datetime import datetime, timezone
        stamp = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        rc.merge_patch(args.name, {"spec": {"template": {"metadata": {
            "annotations": {
                "kubectl.kubernetes.io/restartedAt": stamp}}}}},
            namespace=args.namespace, strategic=False)
        print(f"{resource[:-1]}.apps/{args.name} restarted")
        return 0
    print(f"error: unknown rollout action {args.action}", file=sys.stderr)
    return 1


def _owned_rses(client, d):
    from ..api.apps import ReplicaSet
    from ..api.meta import controller_ref
    out = []
    for rs in client.resource(ReplicaSet,
                              d.metadata.namespace).list():
        ref = controller_ref(rs.metadata)
        if ref is not None and ref.uid == d.metadata.uid:
            out.append(rs)
    return out


def cmd_api_resources(args) -> int:
    rows = []
    for resource in sorted(SCHEME.resources()):
        cls = SCHEME.type_for_resource(resource)
        av, kind = SCHEME.gvk_for(cls)
        rows.append([resource, av, str(SCHEME.is_namespaced(cls)).lower(),
                     kind])
    _print_table(rows, ["NAME", "APIVERSION", "NAMESPACED", "KIND"])
    return 0


def cmd_patch(args) -> int:
    """kubectl patch -p '{"spec": {...}}' [--type strategic|merge|json]."""
    _, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    body = json.loads(args.patch)
    if args.type == "json":
        out = rc.json_patch(args.name, body, namespace=args.namespace)
    else:
        out = rc.merge_patch(args.name, body, namespace=args.namespace,
                             strategic=(args.type == "strategic"))
    print(f"{SCHEME.resource_for(out)}/{out.metadata.name} patched")
    return 0


def cmd_label(args) -> int:
    """kubectl label <resource> <name> k=v ... k- (trailing - removes)."""
    _, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    labels = {}
    for kv in args.labels:
        if kv.endswith("-") and "=" not in kv:
            labels[kv[:-1]] = None
        else:
            k, _, v = kv.partition("=")
            labels[k] = v
    out = rc.merge_patch(args.name, {"metadata": {"labels": labels}},
                         namespace=args.namespace, strategic=False)
    print(f"{SCHEME.resource_for(out)}/{out.metadata.name} labeled")
    return 0


def cmd_annotate(args) -> int:
    _, cls = _resolve(args.resource, _client(args))
    rc = _client(args).resource(cls, args.namespace)
    annotations = {}
    for kv in args.annotations:
        if kv.endswith("-") and "=" not in kv:
            annotations[kv[:-1]] = None
        else:
            k, _, v = kv.partition("=")
            annotations[k] = v
    out = rc.merge_patch(
        args.name, {"metadata": {"annotations": annotations}},
        namespace=args.namespace, strategic=False)
    print(f"{SCHEME.resource_for(out)}/{out.metadata.name} annotated")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubectl")
    p.add_argument("--master", "-s", default="http://127.0.0.1:8080")
    p.add_argument("--namespace", "-n", default="default")
    sub = p.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("--output", "-o", choices=["table", "json"],
                   default="table")
    g.add_argument("--all-namespaces", "-A", action="store_true")
    g.set_defaults(fn=cmd_get)

    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")
    d.set_defaults(fn=cmd_describe)

    for verb, fn in (("create", cmd_create), ("apply", cmd_apply),
                     ("diff", cmd_diff)):
        c = sub.add_parser(verb)
        c.add_argument("--filename", "-f", required=True)
        c.set_defaults(fn=fn)

    ed = sub.add_parser("edit")
    ed.add_argument("resource")
    ed.add_argument("name")
    ed.set_defaults(fn=cmd_edit)

    tp = sub.add_parser("top")
    tp.add_argument("kind", choices=["nodes", "pods"])
    tp.set_defaults(fn=cmd_top)

    x = sub.add_parser("delete")
    x.add_argument("resource")
    x.add_argument("name")
    x.set_defaults(fn=cmd_delete)

    s = sub.add_parser("scale")
    s.add_argument("resource")
    s.add_argument("name")
    s.add_argument("--replicas", type=int, required=True)
    s.set_defaults(fn=cmd_scale)

    au = sub.add_parser("autoscale")
    au.add_argument("resource")
    au.add_argument("name")
    au.add_argument("--min", type=int, default=1)
    au.add_argument("--max", type=int, required=True)
    au.add_argument("--cpu-percent", type=int, default=80)
    au.set_defaults(fn=cmd_autoscale)

    for verb, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon)):
        c = sub.add_parser(verb)
        c.add_argument("name")
        c.set_defaults(fn=fn)

    lo = sub.add_parser("logs")
    lo.add_argument("name")
    lo.add_argument("--container", "-c", default="")
    lo.set_defaults(fn=cmd_logs)

    ex = sub.add_parser("exec")
    ex.add_argument("name")
    ex.add_argument("--container", "-c", default="")
    ex.add_argument("command", nargs=argparse.REMAINDER)
    ex.set_defaults(fn=cmd_exec)

    at = sub.add_parser("attach")
    at.add_argument("name")
    at.add_argument("--container", "-c", default="")
    at.set_defaults(fn=cmd_attach)

    cp = sub.add_parser("cp")
    cp.add_argument("src")
    cp.add_argument("dst")
    cp.add_argument("--container", "-c", default="")
    cp.set_defaults(fn=cmd_cp)

    dr = sub.add_parser("drain")
    dr.add_argument("name")
    dr.add_argument("--ignore-daemonsets", action="store_true")
    dr.add_argument("--force", action="store_true")
    dr.add_argument("--timeout", type=float, default=60.0)
    dr.add_argument("--poll-interval", type=float, default=0.5)
    dr.set_defaults(fn=cmd_drain)

    ro = sub.add_parser("rollout")
    ro.add_argument("action", choices=["status", "restart", "history",
                                       "undo"])
    ro.add_argument("resource")  # deployment (the rollout-managed kind)
    ro.add_argument("name")
    ro.add_argument("--timeout", type=float, default=60.0)
    ro.add_argument("--to-revision", type=int, default=0)
    ro.set_defaults(fn=cmd_rollout)

    ar = sub.add_parser("api-resources")
    ar.set_defaults(fn=cmd_api_resources)

    pa = sub.add_parser("patch")
    pa.add_argument("resource")
    pa.add_argument("name")
    pa.add_argument("--patch", "-p", required=True)
    pa.add_argument("--type", choices=["strategic", "merge", "json"],
                    default="strategic")
    pa.set_defaults(fn=cmd_patch)

    la = sub.add_parser("label")
    la.add_argument("resource")
    la.add_argument("name")
    la.add_argument("labels", nargs="+")
    la.set_defaults(fn=cmd_label)

    an = sub.add_parser("annotate")
    an.add_argument("resource")
    an.add_argument("name")
    an.add_argument("annotations", nargs="+")
    an.set_defaults(fn=cmd_annotate)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except SystemExit:
        raise
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
