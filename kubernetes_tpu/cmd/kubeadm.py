"""kubeadm — cluster bootstrap.

Ref: cmd/kubeadm/app (init: PKI + control-plane bring-up + bootstrap
tokens + RBAC; join: TLS bootstrap via CSR). Here init generates the
cluster PKI, writes kubeconfigs, and runs the whole control plane
(apiserver with TLS + x509/token authn + stored-RBAC authz, controller
manager incl. the CSR approver/signer, scheduler) in one process; join
performs the reference's kubelet TLS bootstrap: authenticate with the
bootstrap token, POST a CertificateSigningRequest
(CN=system:node:<name>, O=system:nodes), wait for the auto-approved +
signed certificate, then run the node agent with its x509 identity.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import secrets
import sys
import threading
import time
from typing import Optional, Tuple


def _wipe_dir(data_dir: str) -> None:
    """Empty a kubeadm data dir (pki, WAL, audit) without removing the
    dir itself — shared by ControlPlane.reset and the reset CLI."""
    import shutil
    if not os.path.isdir(data_dir):
        return
    for entry in os.listdir(data_dir):
        path = os.path.join(data_dir, entry)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


def _write(path: str, data: bytes) -> str:
    # key material must never be world-readable (the reference's
    # keyutil.WriteKey uses 0600); harmless extra strictness for certs
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    return path


def generate_pki(pki_dir: str, server_sans=("127.0.0.1", "localhost")):
    """CA + apiserver serving cert + admin client cert (ref: kubeadm's
    certs phase). Returns a dict of paths."""
    from ..utils import certs as certutil
    os.makedirs(pki_dir, exist_ok=True)
    ca_cert, ca_key = certutil.new_ca()
    srv_cert, srv_key = certutil.issue_cert(
        ca_cert, ca_key, "kube-apiserver", sans=tuple(server_sans),
        server=True, client=False)
    adm_cert, adm_key = certutil.issue_cert(
        ca_cert, ca_key, "kubernetes-admin",
        organizations=("system:masters",))
    paths = {
        "ca_cert": _write(os.path.join(pki_dir, "ca.crt"), ca_cert),
        "ca_key": _write(os.path.join(pki_dir, "ca.key"), ca_key),
        "server_cert": _write(os.path.join(pki_dir, "apiserver.crt"),
                              srv_cert),
        "server_key": _write(os.path.join(pki_dir, "apiserver.key"),
                             srv_key),
        "admin_cert": _write(os.path.join(pki_dir, "admin.crt"), adm_cert),
        "admin_key": _write(os.path.join(pki_dir, "admin.key"), adm_key),
    }
    return paths


class ControlPlane:
    """Everything `kubeadm init` brings up, embeddable for tests."""

    def __init__(self, data_dir: str, port: int = 0,
                 host: str = "127.0.0.1"):
        from ..apiserver.auth import (CertAuthenticator, RBACAuthorizer,
                                      TokenAuthenticator, UserInfo)
        from ..apiserver.server import APIServer
        from ..state.store import Store
        os.makedirs(data_dir, exist_ok=True)
        self.pki = generate_pki(os.path.join(data_dir, "pki"),
                                server_sans=(host, "localhost",
                                             "127.0.0.1"))
        store = Store(wal_path=os.path.join(data_dir, "store.wal"))
        self.server = APIServer(
            store=store, host=host, port=port,
            tls_cert_file=self.pki["server_cert"],
            tls_key_file=self.pki["server_key"],
            client_ca_file=self.pki["ca_cert"],
            audit_log_path=os.path.join(data_dir, "audit.log"))
        self._store = store
        # bootstrap token (ref: kubeadm token create): a STORED secret of
        # type bootstrap.kubernetes.io/token — authenticated live, revoked
        # by deletion or expiry (tokencleaner)
        from ..apiserver.bootstrap import (BootstrapTokenAuthenticator,
                                           generate_token,
                                           make_token_secret)
        from ..utils.clock import now_iso
        import datetime
        self.bootstrap_token = generate_token()
        expiry = (datetime.datetime.now(datetime.timezone.utc)
                  + datetime.timedelta(hours=24)).isoformat()
        self.server.client.secrets("kube-system").create(
            make_token_secret(self.bootstrap_token, expiration_iso=expiry))
        # cluster-info in kube-public (ref: kubeadm's uploadconfig phase):
        # the UNAUTHENTICATED discovery document joiners verify via the
        # bootstrapsigner's per-token JWS + the CA public-key hash
        from ..api.core import ConfigMap
        from ..api.meta import ObjectMeta
        ca_pem = open(self.pki["ca_cert"], "rb").read()
        cluster_info = json.dumps({
            "server": self.server.address,
            "certificate-authority-data":
                base64.b64encode(ca_pem).decode()})
        self.server.client.config_maps("kube-public").create(ConfigMap(
            metadata=ObjectMeta(name="cluster-info",
                                namespace="kube-public"),
            data={"kubeconfig": cluster_info}))
        # the uploaded ClusterConfiguration (ref: kubeadm's uploadconfig
        # phase writing the kubeadm-config ConfigMap) — `upgrade` CAS-es
        # this document and restarts components against it
        self.version = "v1.0.0"
        self.server.client.config_maps("kube-system").create(ConfigMap(
            metadata=ObjectMeta(name="kubeadm-config",
                                namespace="kube-system"),
            data={"ClusterConfiguration": json.dumps(
                {"kubernetesVersion": self.version,
                 "clusterName": "kubernetes"})}))
        authz = RBACAuthorizer()
        authz.grant("group:system:masters", ["*"], ["*"])
        # bootstrappers may create and read CSRs, nothing else
        authz.grant("group:system:bootstrappers",
                    ["create", "get", "list", "watch"],
                    ["certificatesigningrequests"])
        # anonymous discovery: cluster-info only (ref: kubeadm's
        # cluster-info RBAC for system:unauthenticated)
        authz.grant("group:system:unauthenticated", ["get"],
                    ["configmaps"], namespaces=("kube-public",))
        authz.use_store(self.server.client)
        # node identities are scoped by the Node authorizer (their OWN
        # node/pods/lease only) instead of a broad RBAC grant
        from ..apiserver.auth import NodeAuthorizer
        from ..state.store import NotFoundError

        def pod_node_of(ns, name):
            try:
                return self.server.client.pods(ns or "default") \
                    .get(name).spec.node_name
            except NotFoundError:
                return None

        import time as _time
        _cm_cache: dict = {}  # node -> (expires_at, refs)

        def node_configmaps_of(node):
            # configmaps volume-referenced by pods bound to this node —
            # the graph authorizer's kubelet->configmap edge. The scan is
            # O(pods), so amortize it with a short TTL instead of paying
            # it on every kubelet GET (the reference keeps an incremental
            # graph; a 1s-stale grant only delays a NEW pod's configmap
            # read by one cache window)
            hit = _cm_cache.get(node)
            now = _time.monotonic()
            if hit is not None and hit[0] > now:
                return hit[1]
            refs = set()
            for p in self.server.client.pods(None).list():
                if p.spec.node_name != node:
                    continue
                ns = p.metadata.namespace or "default"
                for v in p.spec.volumes:
                    cm = v.config_map or {}
                    if cm.get("name"):
                        refs.add((ns, cm["name"]))
            _cm_cache[node] = (now + 1.0, refs)
            return refs
        self.server.authenticator = CertAuthenticator(
            fallback=BootstrapTokenAuthenticator(self.server.client))
        self.server.authorizer = NodeAuthorizer(
            authz, pod_node_of=pod_node_of,
            node_configmaps_of=node_configmaps_of)
        self.manager = None
        self.scheduler = None

    def start(self) -> "ControlPlane":
        from ..apiserver.httpclient import HTTPClient
        from ..controllers import ControllerManager
        from ..scheduler import Scheduler
        self.server.start()
        ca = (open(self.pki["ca_cert"], "rb").read(),
              open(self.pki["ca_key"], "rb").read())
        self.admin_client = HTTPClient(
            self.server.address, ca_file=self.pki["ca_cert"],
            cert_file=self.pki["admin_cert"],
            key_file=self.pki["admin_key"])
        self.manager = ControllerManager(self.admin_client, cluster_ca=ca)
        self.manager.start()
        self.scheduler = Scheduler(self.admin_client)
        self.scheduler.start()
        return self

    def stop(self) -> None:
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.manager is not None:
            self.manager.stop()
        self.server.stop()
        self._store.close()

    # ------------------------------------------------------------ upgrade

    @staticmethod
    def _version_tuple(v: str):
        """Numeric ordering key for vX.Y.Z[-suffix] strings; a component
        with no numeric prefix is a clean error, not a traceback."""
        import re
        parts = []
        for x in v.lstrip("v").split("-")[0].split("."):
            m = re.match(r"\d+", x)
            if m is None:
                raise ValueError(f"unparseable version {v!r}")
            parts.append(int(m.group()))
        return tuple(parts)

    def upgrade(self, target_version: str) -> dict:
        """`kubeadm upgrade apply` (ref: cmd/kubeadm/app/cmd/upgrade.go
        + phases/upgrade): preflight the stored ClusterConfiguration,
        re-render it at the target version, then restart control-plane
        components in the reference's order — the API server keeps
        serving (it IS the upgrade transport), controller-manager
        restarts before the scheduler. Returns the upgrade plan record."""
        from ..controllers import ControllerManager
        from ..scheduler import Scheduler
        cm = self.server.client.config_maps("kube-system").get(
            "kubeadm-config")
        cfg = json.loads(cm.data["ClusterConfiguration"])
        current = cfg["kubernetesVersion"]
        if self._version_tuple(target_version) <= \
                self._version_tuple(current):
            raise ValueError(
                f"target {target_version} is not newer than {current}")
        # phase: re-render + upload the new ClusterConfiguration (CAS —
        # a concurrent upgrade loses cleanly)
        cfg["kubernetesVersion"] = target_version
        cm.data["ClusterConfiguration"] = json.dumps(cfg)
        self.server.client.config_maps("kube-system").update(cm)
        # phase: restart components in order against the SAME store;
        # leader leases release on stop, the replacements re-acquire
        plan = {"from": current, "to": target_version, "restarted": []}
        ca = (open(self.pki["ca_cert"], "rb").read(),
              open(self.pki["ca_key"], "rb").read())
        if self.manager is not None:
            self.manager.stop()
            self.manager = ControllerManager(self.admin_client,
                                             cluster_ca=ca)
            self.manager.start()
            plan["restarted"].append("kube-controller-manager")
        if self.scheduler is not None:
            self.scheduler.stop()
            self.scheduler = Scheduler(self.admin_client)
            self.scheduler.start()
            plan["restarted"].append("kube-scheduler")
        self.version = target_version
        return plan

    def reset(self) -> None:
        """`kubeadm reset` (ref: cmd/kubeadm/app/cmd/reset.go): stop
        everything, then tear down the node-local state this init laid
        down — pki, WAL, audit log — leaving a clean data dir a fresh
        init can reuse."""
        self.stop()
        data_dir = os.path.dirname(self.pki["ca_cert"])  # <data>/pki
        _wipe_dir(os.path.dirname(data_dir))


def discover_cluster_info(server_url: str, token: str,
                          ca_cert_hash: Optional[str] = None,
                          timeout: float = 30.0) -> bytes:
    """kubeadm join's token discovery (ref: cmd/kubeadm/app/discovery/
    token): fetch the kube-public cluster-info ConfigMap ANONYMOUSLY over
    an unverified channel, then establish trust cryptographically —
    (a) the per-token JWS signature proves the cluster knows our token,
    (b) the CA public-key hash (when given) pins the CA against a
    token-compromised MITM. Returns the verified CA PEM."""
    import time as _t
    from ..apiserver.bootstrap import jws_verify
    from ..apiserver.httpclient import HTTPClient
    from ..utils import certs as certutil
    anon = HTTPClient(server_url, insecure_skip_tls_verify=True)
    tid = token.split(".", 1)[0]
    deadline = _t.time() + timeout
    while True:
        cm = anon.config_maps("kube-public").get("cluster-info")
        payload = cm.data.get("kubeconfig", "")
        jws = cm.data.get(f"jws-kubeconfig-{tid}", "")
        if payload and jws:
            break
        # the bootstrapsigner may not have signed yet; poll
        if _t.time() > deadline:
            raise TimeoutError(
                "cluster-info was never signed for this token")
        _t.sleep(0.25)
    if not jws_verify(jws, payload, token):
        raise ValueError("cluster-info JWS verification failed "
                         "(token mismatch or tampered discovery document)")
    info = json.loads(payload)
    ca_pem = base64.b64decode(info["certificate-authority-data"])
    if ca_cert_hash is not None and \
            certutil.ca_cert_hash(ca_pem) != ca_cert_hash:
        raise ValueError("discovered CA does not match the supplied "
                         "--discovery-token-ca-cert-hash")
    return ca_pem


def join_node(server_url: str, token: str, node_name: str,
              work_dir: str, ca_file: Optional[str] = None,
              ca_cert_hash: Optional[str] = None,
              timeout: float = 60.0):
    """The kubelet TLS bootstrap (ref: kubeadm join + kubelet
    certificate.Manager): discover + verify the cluster CA from only the
    bootstrap token (and optional CA hash) when no ca_file is pre-shared,
    then CSR with the node identity, wait for the signed cert, and start
    the agent with it. Returns the running NodeAgent."""
    from ..api.certificates import (SIGNER_KUBELET_CLIENT,
                                    CertificateSigningRequest,
                                    CertificateSigningRequestSpec)
    from ..api.meta import ObjectMeta
    from ..apiserver.httpclient import HTTPClient
    from ..utils import certs as certutil
    os.makedirs(work_dir, exist_ok=True)
    if ca_file is None:
        ca_pem = discover_cluster_info(server_url, token,
                                       ca_cert_hash=ca_cert_hash,
                                       timeout=min(30.0, timeout))
        ca_file = _write(os.path.join(work_dir, "discovered-ca.crt"),
                         ca_pem)
    csr_pem, key_pem = certutil.new_csr(
        f"system:node:{node_name}", organizations=("system:nodes",))
    key_file = _write(os.path.join(work_dir, f"{node_name}.key"), key_pem)
    boot = HTTPClient(server_url, token=token, ca_file=ca_file,
                      insecure_skip_tls_verify=ca_file is None)
    rc = boot.certificate_signing_requests()
    name = f"node-csr-{node_name}"
    rc.create(CertificateSigningRequest(
        metadata=ObjectMeta(name=name),
        spec=CertificateSigningRequestSpec(
            request=base64.b64encode(csr_pem).decode(),
            signer_name=SIGNER_KUBELET_CLIENT,
            usages=["digital signature", "client auth"],
            username=f"system:node:{node_name}",
            groups=["system:nodes"])))
    deadline = time.time() + timeout
    cert_b64 = ""
    while time.time() < deadline:
        csr = rc.get(name)
        if csr.status.certificate:
            cert_b64 = csr.status.certificate
            break
        time.sleep(0.2)
    if not cert_b64:
        raise TimeoutError(f"CSR {name} was never signed")
    cert_file = _write(os.path.join(work_dir, f"{node_name}.crt"),
                       base64.b64decode(cert_b64))
    client = HTTPClient(server_url, ca_file=ca_file,
                        cert_file=cert_file, key_file=key_file,
                        insecure_skip_tls_verify=ca_file is None)
    return JoinedNode(client, node_name)


class JoinedNode:
    """A kubelet running under its CSR-issued x509 identity."""

    def __init__(self, client, node_name: str):
        from ..node.agent import NodeAgent
        from ..state.informer import SharedInformerFactory
        self.client = client
        self.informers = SharedInformerFactory(client)
        self.agent = NodeAgent(client, node_name, self.informers)

    def start(self) -> "JoinedNode":
        self.informers.start()
        self.informers.wait_for_cache_sync()
        self.agent.start()
        return self

    def stop(self) -> None:
        self.agent.stop()
        self.informers.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubeadm")
    sub = p.add_subparsers(dest="cmd", required=True)
    i = sub.add_parser("init")
    i.add_argument("--data-dir", required=True)
    i.add_argument("--port", type=int, default=6443)
    i.add_argument("--bind-address", default="127.0.0.1")
    j = sub.add_parser("join")
    j.add_argument("server")
    j.add_argument("--token", required=True)
    j.add_argument("--node-name", required=True)
    j.add_argument("--work-dir", required=True)
    j.add_argument("--ca-file", default=None)
    u = sub.add_parser("upgrade")
    u.add_argument("action", choices=["plan", "apply"])
    u.add_argument("version", nargs="?", default=None)
    u.add_argument("--server", required=True)
    u.add_argument("--ca-file", required=True)
    u.add_argument("--cert-file", required=True)
    u.add_argument("--key-file", required=True)
    r = sub.add_parser("reset")
    r.add_argument("--data-dir", required=True)
    args = p.parse_args(argv)

    if args.cmd == "init":
        cp = ControlPlane(args.data_dir, port=args.port,
                          host=args.bind_address).start()
        print(json.dumps({
            "server": cp.server.address,
            "token": cp.bootstrap_token,
            "ca_file": cp.pki["ca_cert"],
            "admin_cert": cp.pki["admin_cert"],
            "admin_key": cp.pki["admin_key"]}), flush=True)
        stop = threading.Event()
        import signal
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        cp.stop()
        return 0
    if args.cmd == "join":
        node = join_node(args.server, args.token, args.node_name,
                         args.work_dir, ca_file=args.ca_file).start()
        print(f"node {args.node_name} joined", flush=True)
        stop = threading.Event()
        import signal
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        stop.wait()
        node.stop()
        return 0
    if args.cmd == "upgrade":
        # the out-of-process form of the upgrade phases: plan reads the
        # uploaded ClusterConfiguration; apply CAS-es the re-rendered one
        # (the owning init process restarts components via
        # ControlPlane.upgrade — ref: upgrade.go's apply flow)
        from ..apiserver.httpclient import HTTPClient
        client = HTTPClient(args.server, ca_file=args.ca_file,
                            cert_file=args.cert_file,
                            key_file=args.key_file)
        cm = client.config_maps("kube-system").get("kubeadm-config")
        cfg = json.loads(cm.data["ClusterConfiguration"])
        if args.action == "plan":
            print(json.dumps({"current": cfg["kubernetesVersion"],
                              "target": args.version or "(none given)"}))
            return 0
        if not args.version:
            print("error: upgrade apply needs a version", file=sys.stderr)
            return 1
        try:
            cur = ControlPlane._version_tuple(cfg["kubernetesVersion"])
            newer = ControlPlane._version_tuple(args.version) > cur
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if not newer:
            print(f"error: {args.version} is not newer than "
                  f"{cfg['kubernetesVersion']}", file=sys.stderr)
            return 1
        cfg["kubernetesVersion"] = args.version
        cm.data["ClusterConfiguration"] = json.dumps(cfg)
        client.config_maps("kube-system").update(cm)
        print(f"upgraded cluster configuration to {args.version}",
              flush=True)
        return 0
    if args.cmd == "reset":
        # node-local teardown (ref: reset.go): wipe pki/WAL/audit so a
        # fresh init starts clean. Refuses nothing — reset is the
        # "I mean it" command, exactly like the reference
        _wipe_dir(args.data_dir)
        print(f"reset: {args.data_dir} cleaned", flush=True)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
