"""kubeadm — cluster bootstrap.

Ref: cmd/kubeadm/app (init: PKI + control-plane bring-up + bootstrap
tokens + RBAC; join: TLS bootstrap via CSR). Here init generates the
cluster PKI, writes kubeconfigs, and runs the whole control plane
(apiserver with TLS + x509/token authn + stored-RBAC authz, controller
manager incl. the CSR approver/signer, scheduler) in one process; join
performs the reference's kubelet TLS bootstrap: authenticate with the
bootstrap token, POST a CertificateSigningRequest
(CN=system:node:<name>, O=system:nodes), wait for the auto-approved +
signed certificate, then run the node agent with its x509 identity.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import secrets
import sys
import threading
import time
from typing import Optional, Tuple


def _write(path: str, data: bytes) -> str:
    # key material must never be world-readable (the reference's
    # keyutil.WriteKey uses 0600); harmless extra strictness for certs
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    return path


def generate_pki(pki_dir: str, server_sans=("127.0.0.1", "localhost")):
    """CA + apiserver serving cert + admin client cert (ref: kubeadm's
    certs phase). Returns a dict of paths."""
    from ..utils import certs as certutil
    os.makedirs(pki_dir, exist_ok=True)
    ca_cert, ca_key = certutil.new_ca()
    srv_cert, srv_key = certutil.issue_cert(
        ca_cert, ca_key, "kube-apiserver", sans=tuple(server_sans),
        server=True, client=False)
    adm_cert, adm_key = certutil.issue_cert(
        ca_cert, ca_key, "kubernetes-admin",
        organizations=("system:masters",))
    paths = {
        "ca_cert": _write(os.path.join(pki_dir, "ca.crt"), ca_cert),
        "ca_key": _write(os.path.join(pki_dir, "ca.key"), ca_key),
        "server_cert": _write(os.path.join(pki_dir, "apiserver.crt"),
                              srv_cert),
        "server_key": _write(os.path.join(pki_dir, "apiserver.key"),
                             srv_key),
        "admin_cert": _write(os.path.join(pki_dir, "admin.crt"), adm_cert),
        "admin_key": _write(os.path.join(pki_dir, "admin.key"), adm_key),
    }
    return paths


class ControlPlane:
    """Everything `kubeadm init` brings up, embeddable for tests."""

    def __init__(self, data_dir: str, port: int = 0,
                 host: str = "127.0.0.1"):
        from ..apiserver.auth import (CertAuthenticator, RBACAuthorizer,
                                      TokenAuthenticator, UserInfo)
        from ..apiserver.server import APIServer
        from ..state.store import Store
        os.makedirs(data_dir, exist_ok=True)
        self.pki = generate_pki(os.path.join(data_dir, "pki"),
                                server_sans=(host, "localhost",
                                             "127.0.0.1"))
        store = Store(wal_path=os.path.join(data_dir, "store.wal"))
        self.server = APIServer(
            store=store, host=host, port=port,
            tls_cert_file=self.pki["server_cert"],
            tls_key_file=self.pki["server_key"],
            client_ca_file=self.pki["ca_cert"],
            audit_log_path=os.path.join(data_dir, "audit.log"))
        self._store = store
        # bootstrap token (ref: kubeadm token): lets joiners create CSRs
        self.bootstrap_token = secrets.token_hex(8)
        tokens = TokenAuthenticator()
        tokens.add(self.bootstrap_token, UserInfo(
            "system:bootstrap:kubeadm", ("system:bootstrappers",)))
        authz = RBACAuthorizer()
        authz.grant("group:system:masters", ["*"], ["*"])
        # bootstrappers may create and read CSRs, nothing else
        authz.grant("group:system:bootstrappers",
                    ["create", "get", "list", "watch"],
                    ["certificatesigningrequests"])
        # node identities run kubelets (ref: the Node authorizer's scope,
        # expressed as RBAC here)
        authz.grant("group:system:nodes",
                    ["get", "list", "watch", "create", "update", "patch",
                     "delete"],
                    ["nodes", "nodes/status", "pods", "pods/status",
                     "leases", "events"])
        authz.use_store(self.server.client)
        self.server.authenticator = CertAuthenticator(fallback=tokens)
        self.server.authorizer = authz
        self.manager = None
        self.scheduler = None

    def start(self) -> "ControlPlane":
        from ..apiserver.httpclient import HTTPClient
        from ..controllers import ControllerManager
        from ..scheduler import Scheduler
        self.server.start()
        ca = (open(self.pki["ca_cert"], "rb").read(),
              open(self.pki["ca_key"], "rb").read())
        self.admin_client = HTTPClient(
            self.server.address, ca_file=self.pki["ca_cert"],
            cert_file=self.pki["admin_cert"],
            key_file=self.pki["admin_key"])
        self.manager = ControllerManager(self.admin_client, cluster_ca=ca)
        self.manager.start()
        self.scheduler = Scheduler(self.admin_client)
        self.scheduler.start()
        return self

    def stop(self) -> None:
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.manager is not None:
            self.manager.stop()
        self.server.stop()
        self._store.close()


def join_node(server_url: str, token: str, node_name: str,
              work_dir: str, ca_file: Optional[str] = None,
              timeout: float = 60.0):
    """The kubelet TLS bootstrap (ref: kubeadm join + kubelet
    certificate.Manager): CSR with the node identity, wait for the signed
    cert, start the agent with it. Returns the running NodeAgent."""
    from ..api.certificates import (SIGNER_KUBELET_CLIENT,
                                    CertificateSigningRequest,
                                    CertificateSigningRequestSpec)
    from ..api.meta import ObjectMeta
    from ..apiserver.httpclient import HTTPClient
    from ..utils import certs as certutil
    os.makedirs(work_dir, exist_ok=True)
    csr_pem, key_pem = certutil.new_csr(
        f"system:node:{node_name}", organizations=("system:nodes",))
    key_file = _write(os.path.join(work_dir, f"{node_name}.key"), key_pem)
    boot = HTTPClient(server_url, token=token, ca_file=ca_file,
                      insecure_skip_tls_verify=ca_file is None)
    rc = boot.certificate_signing_requests()
    name = f"node-csr-{node_name}"
    rc.create(CertificateSigningRequest(
        metadata=ObjectMeta(name=name),
        spec=CertificateSigningRequestSpec(
            request=base64.b64encode(csr_pem).decode(),
            signer_name=SIGNER_KUBELET_CLIENT,
            usages=["digital signature", "client auth"],
            username=f"system:node:{node_name}",
            groups=["system:nodes"])))
    deadline = time.time() + timeout
    cert_b64 = ""
    while time.time() < deadline:
        csr = rc.get(name)
        if csr.status.certificate:
            cert_b64 = csr.status.certificate
            break
        time.sleep(0.2)
    if not cert_b64:
        raise TimeoutError(f"CSR {name} was never signed")
    cert_file = _write(os.path.join(work_dir, f"{node_name}.crt"),
                       base64.b64decode(cert_b64))
    client = HTTPClient(server_url, ca_file=ca_file,
                        cert_file=cert_file, key_file=key_file,
                        insecure_skip_tls_verify=ca_file is None)
    return JoinedNode(client, node_name)


class JoinedNode:
    """A kubelet running under its CSR-issued x509 identity."""

    def __init__(self, client, node_name: str):
        from ..node.agent import NodeAgent
        from ..state.informer import SharedInformerFactory
        self.client = client
        self.informers = SharedInformerFactory(client)
        self.agent = NodeAgent(client, node_name, self.informers)

    def start(self) -> "JoinedNode":
        self.informers.start()
        self.informers.wait_for_cache_sync()
        self.agent.start()
        return self

    def stop(self) -> None:
        self.agent.stop()
        self.informers.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubeadm")
    sub = p.add_subparsers(dest="cmd", required=True)
    i = sub.add_parser("init")
    i.add_argument("--data-dir", required=True)
    i.add_argument("--port", type=int, default=6443)
    i.add_argument("--bind-address", default="127.0.0.1")
    j = sub.add_parser("join")
    j.add_argument("server")
    j.add_argument("--token", required=True)
    j.add_argument("--node-name", required=True)
    j.add_argument("--work-dir", required=True)
    j.add_argument("--ca-file", default=None)
    args = p.parse_args(argv)

    if args.cmd == "init":
        cp = ControlPlane(args.data_dir, port=args.port,
                          host=args.bind_address).start()
        print(json.dumps({
            "server": cp.server.address,
            "token": cp.bootstrap_token,
            "ca_file": cp.pki["ca_cert"],
            "admin_cert": cp.pki["admin_cert"],
            "admin_key": cp.pki["admin_key"]}), flush=True)
        stop = threading.Event()
        import signal
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        cp.stop()
        return 0
    if args.cmd == "join":
        node = join_node(args.server, args.token, args.node_name,
                         args.work_dir, ca_file=args.ca_file).start()
        print(f"node {args.node_name} joined", flush=True)
        stop = threading.Event()
        import signal
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        stop.wait()
        node.stop()
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
