"""Component entry points — the cmd/ analog.

Ref: cmd/kube-scheduler, cmd/kube-controller-manager, cmd/kube-apiserver.
Each module exposes main(argv) and runs as `python -m
kubernetes_tpu.cmd.<component>`; flags > config file > defaults, matching
the reference's precedence (component-base/cli/flag).
"""
