"""kubernetes_tpu — a TPU-native cluster control plane.

A from-scratch re-design of the capabilities of Kubernetes (reference:
wt351/kubernetes) built TPU-first: the scheduler's Filter/Score/assignment hot
loop runs as batched JAX/XLA kernels over dense pods x nodes tensors (sharded
across a device mesh with shard_map), while the control plane around it — typed
API objects, a versioned watchable store, informers, controllers, a node agent,
and a CLI — is pure Python designed to feed those kernels incrementally.

Layer map (mirrors SURVEY.md section 1):
  api/         typed object model + validation/defaulting/serde   (ref: pkg/apis, staging/src/k8s.io/api)
  runtime/     scheme & codec machinery                           (ref: staging/src/k8s.io/apimachinery)
  state/       versioned store, watch, informers, workqueue       (ref: etcd3/store.go, client-go/tools/cache)
  apiserver/   REST + watch HTTP surface, admission, registry     (ref: staging/src/k8s.io/apiserver)
  scheduler/   batched TPU scheduler: queue, cache, kernels       (ref: pkg/scheduler)
  serving/     open-loop churn loadgen + latency-SLO harness      (ref: perf-tests/clusterloader2 shape)
  controllers/ async reconcilers                                  (ref: pkg/controller)
  nodeagent/   kubelet-equivalent node agent (hollow-capable)     (ref: pkg/kubelet, pkg/kubemark)
  cli/         kubectl-subset command line                        (ref: pkg/kubectl)
  ops/         pallas/XLA kernels for the hot ops
  parallel/    device mesh + sharding helpers
  utils/, metrics/, events/, config/  cross-cutting support
"""

__version__ = "0.1.0"
