"""ChaosHarness — a seeded, virtual-clock chaos soak over an in-process cluster.

The harness stands up the full control plane against one store — batch
scheduler (gang gates included), NodeLifecycleController, PodGroup
controller, pod GC — plus VIRTUAL kubelets (the harness itself heartbeats
nodes and marks bound pods Running), all on a shared FakeClock. A run is
driven by a schedule of chaos actions derived purely from the seed:
workload creation (gangs + singletons), node crashes and restarts,
heartbeat suppression, node deletion, apiserver write partitions, and a
background injected API error rate on every control-plane write.

WIRE mode (`http=True`): a real `APIServer` serves the store and every
control-plane component talks to it over actual HTTP — informers included
— through `ChaosHTTPClient`, so the injector's wire fault classes
(request latency, connection resets, watch-stream drops) hit the real
transport: sockets reset, watch streams die mid-flight and RESUME at
last_sync_rv, exactly the failure surface a remote hub has.

COMPONENT RESTARTS (`with_restarts=True` adds them to the schedule; the
methods are also directly callable): `restart_scheduler` crash-replaces
the scheduler — its cache, assumed pods, and gang permit reservations die
with it and must be rebuilt from informers; `restart_controller_manager`
does the same for the controllers; `restart_store` WAL-replays the store
in place (the etcd-restart analog), severing every live watch stream so
clients must relist or resume.

Determinism contract: the schedule is pregenerated from `seed` before the
run; every control loop is stepped SYNCHRONOUSLY from the single driver
thread; after each step the harness settles (waits until each informer's
indexer matches the store) so informer-thread timing cannot change which
calls the next step issues. Two runs with the same seed therefore produce
identical FaultInjector event logs — `report.events`. (Read-path wire
faults fire on informer threads and are deliberately excluded from the
step-ordered log — see injector.py.)

After the scheduled events, the run quiesces (faults off, dead nodes stay
dead) long enough for eviction timeouts, permit timeouts, and gang
resubmissions to converge, then sweeps the InvariantChecker. A green
report means: no PodGroup partially bound, no cache assume or permit
reservation on a dead node, and the WAL replays to the live store.
`report.store_state` is the run's SEMANTIC end state (which objects
exist, each pod's phase and boundness — not which node, not rv): a
faulted run must converge to the same store_state as a fault-free run of
the same schedule, or the faults leaked into outcomes.
"""

from __future__ import annotations

import json
import random
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest

from ..api.core import Node, NodeCondition, Pod
from ..api.meta import ObjectMeta
from ..api.quantity import Quantity
from ..api.scheduling import PodGroup, PodGroupSpec
from ..controllers.nodelifecycle import NodeLifecycleController
from ..controllers.podgc import PodGCController
from ..controllers.podgroup import PodGroupController
from ..scheduler.scheduler import Scheduler
from ..state.client import Client
from ..state.informer import SharedInformerFactory
from ..state.store import NotFoundError, Store
from ..utils.clock import Clock, FakeClock, REAL_CLOCK, now_iso
from ..utils.metrics import RobustnessMetrics
from .injector import ChaosClient, ChaosHTTPClient, FaultInjector
from .invariants import InvariantChecker

SLICE_LABEL = "tpu/slice"

#: (action, weight) — the seed-derived schedule draws from these
_ACTIONS = (("create_gang", 0.26), ("create_singleton", 0.14),
            ("kill_node", 0.12), ("restart_node", 0.10),
            ("drop_heartbeat", 0.08), ("resume_heartbeat", 0.05),
            ("delete_node", 0.06), ("partition", 0.04), ("heal", 0.05),
            ("noop", 0.10))

#: appended when with_restarts=True — component crash/restart as
#: first-class chaos actions (rng.choices renormalizes the weights)
_RESTART_ACTIONS = (("restart_scheduler", 0.05),
                    ("restart_controllers", 0.04),
                    ("restart_store", 0.03))

#: appended when with_tears=True — durable-state loss: the store
#: restarts having LOST the last N journal records (rv clock regresses)
_TEAR_ACTIONS = (("tear_wal", 0.04),)

#: appended when ha=True — control-plane failover faults: crash the
#: current lease holder, or suppress Lease writes so the holder fences
#: itself at renew_deadline and a standby takes over at lease expiry
_HA_ACTIONS = (("kill_leader", 0.05), ("suppress_lease", 0.04),
               ("resume_lease", 0.06))

#: appended when overload>0 — a burst tenant's client storm (N real
#: threads hammering LIST/create at the hub) opens for a drawn number
#: of ticks; overlapping storms extend the window
_OVERLOAD_ACTIONS = (("client_storm", 0.16),)


def informers_current(admin, factories, classes) -> bool:
    """True when every ALREADY-CREATED informer for `classes` in each
    factory mirrors the store exactly. Informers a factory never created
    are skipped — probing with informer_for would lazily CREATE and
    START streams the owning component never reads (and re-create them
    after every restart), enlarging the wire fault surface."""
    store = admin.store
    for fac in factories:
        with fac._lock:
            informers = dict(fac._informers)
        for cls in classes:
            inf = informers.get(cls)
            if inf is None:
                continue
            resource = admin.scheme.resource_for(cls)
            items, _ = store.list(resource)
            want = {o.metadata.key(): o.metadata.resource_version
                    for o in items}
            have = {o.metadata.key(): o.metadata.resource_version
                    for o in inf.indexer.list()}
            if want != have:
                return False
    return True


def settle_informers(admin, factories, classes, injector,
                     timeout: float = 10.0, logger_name: str = "chaos",
                     step=None, clock: Clock = REAL_CLOCK) -> bool:
    """Wait until informers_current holds twice in a row — the second
    check lets the last event's handler dispatch finish, so control-loop
    inputs are identical across same-seed runs. On timeout the next
    control loop runs on stale indexers and the run's event log may
    diverge; the log is stamped so a determinism failure points at the
    starved informer thread, not the harness logic.

    `clock` defaults to REAL time on purpose: informer threads pump
    events in real time even while the harness's event clock is a
    FakeClock, and sleeping on the SHARED virtual clock would step it
    from the settle loop and fork the event-log contract (the
    StoreReplica._sleep lesson from PR 8)."""
    deadline = clock.now() + timeout
    streak = 0
    while clock.now() < deadline:
        if informers_current(admin, factories, classes):
            streak += 1
            if streak >= 2:
                return True
            clock.sleep(0.002)
        else:
            streak = 0
            clock.sleep(0.002)
    import logging
    logging.getLogger(logger_name).warning(
        "informers failed to settle within %.1fs at step %s",
        timeout, step)
    injector.record("settle_timeout")
    return False


class _BindStampingPods:
    """Proxy over a PodClient that stamps every successful bind with the
    owning scheduler replica's identity: the harness's double-bind
    invariant needs to know WHO bound, not just that a bind landed. Bind
    verbs report (identity, committed slots) to the harness — which
    records them in the step-ordered event log against the current lease
    holder — then everything else passes through untouched."""

    _BIND_VERBS = frozenset({"bind", "bind_bulk", "bind_bulk_pairs"})

    def __init__(self, inner, harness, identity: str):
        self._inner = inner
        self._harness = harness
        self._identity = identity

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in self._BIND_VERBS or not callable(attr):
            return attr
        harness, identity = self._harness, self._identity

        def wrapped(*args, **kwargs):
            out = attr(*args, **kwargs)
            if isinstance(out, list):
                n = sum(1 for o in out if not isinstance(o, Exception))
            else:
                n = 1
            if n:
                harness._note_bind(identity, n)
            return out
        wrapped.__name__ = name
        return wrapped


class _HAClient:
    """A scheduler replica's client in HA mode: pod bind verbs are
    identity-stamped (see _BindStampingPods); every other accessor —
    informer resource() handles, lease writes, node reads — delegates to
    the shared (fault-injected) inner client. `inner` is mutable so a
    replica-promote drill can fail every component over to the standby
    store without rebuilding the components."""

    def __init__(self, inner, harness, identity: str):
        self.inner = inner
        self._harness = harness
        self.identity = identity

    def pods(self, namespace=None):
        return _BindStampingPods(self.inner.pods(namespace),
                                 self._harness, self.identity)

    def __getattr__(self, name: str):
        return getattr(self.inner, name)


@dataclass
class ChaosReport:
    seed: int
    steps: int
    #: the injector's event log — identical across runs with one seed
    events: List[Tuple] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    pods_bound: int = 0
    gangs_created: int = 0
    resubmissions: int = 0
    nodes_killed: int = 0
    nodes_deleted: int = 0
    scheduler_restarts: int = 0
    controller_restarts: int = 0
    store_restarts: int = 0
    #: torn-WAL restarts (restart_store(torn=N)) and the records chopped
    wal_tears: int = 0
    records_torn: int = 0
    #: HA failover accounting
    leader_kills: int = 0
    lease_suppressions: int = 0
    #: (election, virtual seconds) per completed failover — lease loss
    #: to the standby's first bind (scheduler) / first acquire (others)
    failovers: List[Tuple] = field(default_factory=list)
    #: containers virtual kubelets GCed for pods the store lost
    orphans_gced: int = 0
    promoted: bool = False
    #: replication follower accounting (replica=True): the final and
    #: worst-observed primary-vs-replica rv gap, and how many times the
    #: replication stream had to reconnect through the chaos proxy
    replication_lag_records: int = 0
    replication_max_lag_records: int = 0
    replication_reconnects: int = 0
    #: injected-fault totals by kind (wire_reset, wire_reset_replication,
    #: watch_drop, ...) — the proof faults actually fired
    fault_counts: dict = field(default_factory=dict)
    #: per-class SLO report (slo=True): the SLOTracker's bind/startup
    #: percentiles for the "gang"/"solo" classes
    slo: dict = field(default_factory=dict)
    #: client-storm accounting (overload>0). REAL-TIME racy by design
    #: (storm threads race the driver), so these are excluded from the
    #: same-seed determinism surface — events/store_state carry that.
    storm_windows: int = 0
    storm_requests: int = 0
    storm_ok: int = 0
    storm_rejected: int = 0
    storm_errors: int = 0
    #: the semantic end state — sorted (resource, namespace, name,
    #: phase, bound) tuples; node choice and resourceVersions excluded.
    #: Comparable between a faulted and a fault-free run of one schedule.
    store_state: List[Tuple] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


class ChaosHarness:
    def __init__(self, seed: int = 0, nodes: int = 8,
                 nodes_per_slice: int = 4, error_rate: float = 0.05,
                 wal_path: Optional[str] = None,
                 clock_step: float = 5.0,
                 grace_period: float = 12.0,
                 eviction_timeout: float = 30.0,
                 gang_timeout: int = 60,
                 http: bool = False,
                 reset_rate: float = 0.0,
                 latency_rate: float = 0.0,
                 latency_max: float = 0.005,
                 watch_drop_rate: float = 0.0,
                 with_restarts: bool = False,
                 enable_restarts: bool = True,
                 with_tears: bool = False,
                 ha: bool = False,
                 replica: bool = False,
                 replica_reads: bool = False,
                 mesh=None,
                 autoscaler: bool = False,
                 autoscaler_cooldown: float = 60.0,
                 autoscaler_max_nodes: int = 64,
                 preempt_storm: bool = False,
                 slo: bool = False,
                 overload: int = 0,
                 enable_storms: bool = True,
                 apf: Optional[bool] = None):
        self.seed = seed
        #: jax.sharding.Mesh for the scheduler's drain (None = single
        #: device). The determinism contract must survive sharding: the
        #: sharded kernel's decisions are bit-identical by construction,
        #: so same seed => identical event logs with the mesh on
        #: (pinned by tests/test_sharded.py)
        self.mesh = mesh
        self.n_nodes = nodes
        self.nodes_per_slice = max(1, nodes_per_slice)
        self.clock_step = clock_step
        self.gang_timeout = gang_timeout
        self.wal_path = wal_path
        self.grace_period = grace_period
        self.eviction_timeout = eviction_timeout
        self.http = http
        #: with_restarts puts restart actions IN the schedule;
        #: enable_restarts=False executes them as noops — a control run
        #: keeps the identical schedule while skipping the restarts
        self.with_restarts = with_restarts
        self.enable_restarts = enable_restarts
        #: with_tears adds torn-WAL restarts (durable-state LOSS, not
        #: just a crash) to the schedule; requires wal_path
        self.with_tears = with_tears
        #: ha runs scheduler + controller-manager PAIRS gated by leader
        #: election on the shared FakeClock; kill_leader/suppress_lease
        #: join the schedule
        self.ha = ha
        #: preempt_storm draws a priority band per created workload, so
        #: an overcommitted run exercises victim pricing + whole-gang
        #: preemption; flag-conditional draws keep flag-off schedules
        #: byte-identical to earlier PRs'
        self.preempt_storm = preempt_storm
        #: overload drill (ISSUE 19): N real storm threads drive a burst
        #: tenant's LIST/create traffic straight at the hub (requires
        #: http=True), self-declared workload-low via the APF priority
        #: hint. The storm rides a RAW HTTPClient — NOT the injector's
        #: proxy, which would perturb per-signature attempt counters and
        #: break the same-seed event-log identity; its outcome counters
        #: (storm_ok/storm_rejected) are real-time racy by nature and
        #: deliberately excluded from the determinism surface.
        #: enable_storms=False keeps the identical schedule but executes
        #: storms as noops — the storm-free baseline leg, like
        #: enable_restarts for the restart actions. apf=None leaves the
        #: hub on its KTPU_APF env default; True/False pins it.
        self.overload = int(overload)
        self.enable_storms = enable_storms
        self.apf = apf
        if self.overload and not http:
            raise ValueError("overload drill needs http=True (the storm "
                             "hammers the real hub over the wire)")
        self._storm_until = -1
        self._storm_threads: List = []
        self._storm_gen = 0
        self._storm_lock = threading.Lock()
        self._storm_requests = 0
        self._storm_ok = 0
        self._storm_rejected = 0
        self._storm_errors = 0
        self.clock = FakeClock()
        #: the WALL clock for settle/promote barriers (informer and
        #: follower threads pump in real time regardless of the virtual
        #: event clock above); injectable so tests can bound the waits
        self.wall_clock: Clock = REAL_CLOCK
        self.metrics = RobustnessMetrics()
        # span tracer on the SHARED FakeClock, sampling every pod: the
        # determinism contract extends to traces — same seed => byte-
        # identical span logs (span_log()). In HA mode each scheduler
        # replica keeps its own default tracer instead: two replicas'
        # informer threads would interleave writes into one component
        # buffer and break the byte-identity contract.
        from ..observability import SpanTracer
        self.tracer = SpanTracer(clock=self.clock, pod_sample=1)
        self.injector = FaultInjector(
            seed=seed, error_rate=error_rate, metrics=self.metrics,
            reset_rate=reset_rate, latency_rate=latency_rate,
            latency_max=latency_max, watch_drop_rate=watch_drop_rate)
        self._base_error_rate = error_rate
        store = Store(wal_path=wal_path, metrics=self.metrics)
        #: the control plane's (faulted) client vs the harness's own
        #: admin view of the same store — workload creation and virtual
        #: kubelet writes stay fault-free so the run's INPUT is stable
        #: and only the control plane's handling of faults is under test
        self.admin = Client(store)
        self._server = None
        if http:
            # wire mode: a real hub over the store; the control plane's
            # client speaks actual HTTP through the injector's wire hook.
            # The hub's /metrics aggregates the robustness families
            # (replication lag, slow renews, injected faults) beside its
            # own request counters — the scrape surface under test.
            from ..apiserver.server import APIServer
            from ..apiserver.httpclient import HTTPClient
            srv_kwargs = {}
            if self.apf is not None:
                srv_kwargs["apf"] = self.apf
            if self.overload:
                # a hub SMALL enough for `overload` threads to saturate:
                # tiny read/write pools, short fair queues so overflow
                # 429s actually fire, a sub-second queue timeout so
                # rejected storm threads turn around fast, and the run's
                # seed as the shuffle-shard seed (reproducible hands)
                srv_kwargs.update(
                    max_nonmutating_inflight=6,
                    max_mutating_inflight=2,
                    flow_queue_length=2,
                    flow_queue_timeout=0.25,
                    flow_seed=seed,
                    # system gets the FULL pool as its assured share
                    # (the reference gives leader-election and node
                    # heartbeats the highest assured concurrency): one
                    # shared seat would serialize binds behind lease
                    # renews and node status and charge every collision
                    # a thread wakeup
                    flow_shares={"system": 1.0, "workload-high": 0.3,
                                 "workload-low": 0.2, "catch-all": 0.1})
            self._server = APIServer(
                store=store, metrics=self._make_server_metrics(),
                **srv_kwargs).start()
            self.client = ChaosHTTPClient(
                self.injector,
                HTTPClient(self._server.address,
                           wire_hook=self.injector.make_wire_hook()))
        else:
            self.client = ChaosClient(self.injector, store=store)
        #: virtual-kubelet container tracking: node -> set of pod keys a
        #: kubelet "started". A container whose pod the store no longer
        #: binds HERE (lost to a torn journal tail, or rescheduled away)
        #: is orphan-GCed each tick — the kubelet half of torn-WAL
        #: recovery.
        self._containers = {}
        self._orphans_gced = 0
        #: replica-promote drill state (replica=True)
        self._replica = None
        self._promote_violations: List[str] = []
        self._promoted = False
        #: replica read fan-out (replica_reads=True): a STANDBY hub over
        #: the follower store serves every informer's LIST/watch while
        #: writes keep hitting the primary; a ReadRouter rotates reads
        #: back to the primary when replication lag crosses threshold
        self._read_server = None
        self._read_client = None
        self._read_router = None
        if replica_reads:
            if not (http and replica):
                raise ValueError("replica_reads needs http=True and "
                                 "replica=True (reads are served by a "
                                 "standby hub over the follower store)")
            if ha:
                raise ValueError("replica_reads with ha is not wired "
                                 "(HA replicas own their factories)")
        if replica:
            if wal_path is None:
                raise ValueError("replica drill needs wal_path (the "
                                 "standby journals what it applies)")
            from ..state.replication import ReadOnlyStore, StoreReplica
            if http:
                # the wire replica: the follower LISTs and watches the
                # primary hub over actual HTTP, through its OWN faulted
                # client — the replication stream itself takes resets,
                # latency, and watch drops, tagged per-stream so the run
                # can prove the follower (not just the control plane)
                # rode through them
                from ..apiserver.httpclient import HTTPClient
                follower_client = HTTPClient(
                    self._server.address,
                    wire_hook=self.injector.make_wire_hook(
                        stream="replication"))
            else:
                follower_client = Client(store)
            self._replica = StoreReplica(
                follower_client,
                store=ReadOnlyStore(wal_path=wal_path + ".replica",
                                    metrics=self.metrics),
                seed=seed, metrics=self.metrics)
            if http:
                # lag/promote attribution in /debug/pending; a
                # replication-lag check gates the hub's /readyz
                self._server.attach_replica(self._replica)
            if replica_reads:
                # the read path: a standby hub OVER the follower's
                # read-only store (writes 503 until promote), reached
                # through the same faulted transport as the primary —
                # replica reads take wire faults too
                from ..apiserver.server import APIServer
                from ..apiserver.httpclient import HTTPClient
                self._read_server = APIServer(
                    store=self._replica.store,
                    metrics=self._make_server_metrics()).start()
                self._read_server.attach_replica(self._replica)
                self._read_client = ChaosHTTPClient(
                    self.injector,
                    HTTPClient(self._read_server.address,
                               wire_hook=self.injector.make_wire_hook()))
        #: per-class SLO observation under chaos (slo=True): created
        #: pods carry the serving class label ("gang"/"solo") and a
        #: scan-driven SLOTracker on the shared FakeClock stamps their
        #: lifecycle each tick — deterministic, so the resilience bench
        #: can compare per-class bind p99 faulted-vs-control
        self.slo = None
        if slo:
            from ..serving.slo import SLOTracker
            self.slo = SLOTracker(clock=self.clock)
        self._gang_counter = 0
        self._pod_counter = 0
        self._started = False
        if ha:
            # scheduler + controller-manager PAIRS, each replica with its
            # own informer factory (a crash takes its caches with it),
            # gated by step()-driven leader election on the FakeClock.
            # Lease timing in clock_step units: an attempt every tick, a
            # holder fences after missing ~2 ticks of renewals, a standby
            # acquires once the lease expires ~5 ticks after the last
            # renewal — the fencing window (expiry - deadline) is > 0,
            # which is what the zero-double-bind invariant rests on.
            self._lease_duration = 5.0 * clock_step
            self._renew_deadline = 2.0 * clock_step
            self._ha_gen = 0
            self._sched_instances = {}   # identity -> (factory, Scheduler)
            self._cm_instances = {}      # identity -> (factory, nlc, pg, gc)
            self._electors = {}          # identity -> LeaderElector
            self._sched_leader: Optional[str] = None
            self._cm_leader: Optional[str] = None
            #: election -> (clock time leadership was lost, lost holder)
            self._failover_start = {}
            #: the harness-side bind log: (step, identity, n, holder)
            self.bind_log: List[Tuple] = []
            for _ in range(2):
                self._spawn_sched_instance()
            for _ in range(2):
                self._spawn_cm_instance()
            # self.scheduler / controller attrs track the CURRENT leader
            # (the invariant sweep's view); until the first election the
            # first replica stands in
            first_s = next(iter(self._sched_instances))
            self._sched_factory, self.scheduler = \
                self._sched_instances[first_s]
            first_c = next(iter(self._cm_instances))
            (self.factory, self.nodelifecycle, self.podgroups,
             self.podgc) = self._cm_instances[first_c]
        else:
            #: controllers' factory; the scheduler runs its OWN factory
            #: so a scheduler crash can take its informers down with it
            self.factory = SharedInformerFactory(
                self.client, read_client=self._read_client)
            self._sched_factory = SharedInformerFactory(
                self.client, read_client=self._read_client)
            self.scheduler = self._build_scheduler(self._sched_factory)
            self._build_controllers(self.factory)
        #: gang-aware capacity management under chaos: the autoscaler
        #: consumes the CURRENT scheduler's parked-gang demand (late
        #: bound — restart_scheduler swaps the instance), provisions
        #: slices through the faulted client (informers and the fault
        #: oracle see real node adds), and steps deterministically on
        #: the shared FakeClock inside _tick
        self.autoscaler = None
        self._ca_factory = None
        if autoscaler:
            from ..autoscaler import ClusterAutoscaler, \
                scheduler_demand_source
            # its own factory: controller-manager restarts replace
            # self.factory, but the autoscaler (like a separate
            # cluster-autoscaler deployment) survives them
            self._ca_factory = SharedInformerFactory(
                self.client, read_client=self._read_client)
            self.autoscaler = ClusterAutoscaler(
                self.client, self._ca_factory,
                demand_source=scheduler_demand_source(
                    lambda: self.scheduler),
                clock=self.clock, cooldown=autoscaler_cooldown,
                max_nodes=autoscaler_max_nodes,
                node_pods=110, robustness=self.metrics,
                # the virtual kubelets own heartbeats here — and the
                # injector's node kills must stay authoritative
                maintain_heartbeats=False)
        if self._read_client is not None:
            # driver-ticked rotation gate (no router thread — rotation
            # instants must be schedule-deterministic); _factories is
            # passed as a CALLABLE so restart-replaced factories rotate
            from ..state.replication import ReadRouter
            self._read_router = ReadRouter(
                self._replica, self._read_client, self._factories,
                metrics=self.metrics)

    def _make_server_metrics(self):
        """A hub MetricsRegistry with the harness's robustness families
        attached: GET /metrics on the (primary or promoted-standby)
        apiserver exposes replication_lag_records,
        leaderelection_slow_renews_total, and the injected-fault counters
        beside the hub's own request families."""
        from ..observability import MetricsRegistry
        m = MetricsRegistry()
        m.add_registry("robustness", self.metrics.registry)
        return m

    def _build_scheduler(self, factory: SharedInformerFactory,
                         client=None) -> Scheduler:
        # async_bind=False: the driver steps everything synchronously —
        # a binder thread would commit binds at wall-clock-dependent
        # times and break the identical-event-log contract in wire mode
        return Scheduler(client if client is not None else self.client,
                         informer_factory=factory,
                         batch_size=64, clock=self.clock,
                         async_bind=False, mesh=self.mesh,
                         tracer=None if self.ha else self.tracer)

    def _make_controllers(self, factory: SharedInformerFactory,
                          client=None) -> Tuple:
        client = client if client is not None else self.client
        nlc = NodeLifecycleController(
            client, factory, grace_period=self.grace_period,
            eviction_timeout=self.eviction_timeout, clock=self.clock,
            metrics=self.metrics)
        pg = PodGroupController(client, factory, metrics=self.metrics,
                                clock=self.clock)
        gc = PodGCController(client, factory, clock=self.clock)
        return nlc, pg, gc

    def _build_controllers(self, factory: SharedInformerFactory) -> None:
        self.nodelifecycle, self.podgroups, self.podgc = \
            self._make_controllers(factory)

    def _current_read_client(self):
        """The read client a crash-replaced factory should come up on:
        the follower while it is in read rotation, the primary while the
        router has it gated out (or replica reads are off)."""
        if self._read_router is not None and self._read_router.on_replica:
            return self._read_client
        return None

    def _factories(self) -> List[SharedInformerFactory]:
        extra = [self._ca_factory] if self._ca_factory is not None else []
        if self.ha:
            return [f for f, *_ in self._cm_instances.values()] + \
                   [f for f, _ in self._sched_instances.values()] + extra
        return [self.factory, self._sched_factory] + extra

    # --------------------------------------------------------- ha wiring

    def _next_identity(self, base: str) -> str:
        """Generation-suffixed replica identities: a crash-replaced
        replica must NOT inherit its predecessor's identity, or it would
        read the stale lease as its own and 'renew' straight back into
        leadership without waiting out the expiry."""
        self._ha_gen += 1
        return f"{base}-g{self._ha_gen}"

    def _make_elector(self, election: str, identity: str, client):
        from ..state.leaderelection import LeaderElector
        return LeaderElector(
            client, election, identity,
            lease_duration=self._lease_duration,
            renew_deadline=self._renew_deadline,
            retry_period=self.clock_step,
            on_started_leading=lambda: self._on_leader_started(
                election, identity),
            on_stopped_leading=lambda: self._on_leader_stopped(
                election, identity),
            clock=self.clock, metrics=self.metrics)

    def _spawn_sched_instance(self) -> str:
        identity = self._next_identity("sched")
        client = _HAClient(self.client, self, identity)
        factory = SharedInformerFactory(client)
        sched = self._build_scheduler(factory, client)
        self._sched_instances[identity] = (factory, sched)
        self._electors[identity] = self._make_elector(
            "kube-scheduler", identity, client)
        if self._started:
            factory.start()
            factory.wait_for_cache_sync()
        return identity

    def _spawn_cm_instance(self) -> str:
        identity = self._next_identity("cm")
        factory = SharedInformerFactory(self.client)
        nlc, pg, gc = self._make_controllers(factory)
        self._cm_instances[identity] = (factory, nlc, pg, gc)
        self._electors[identity] = self._make_elector(
            "kube-controller-manager", identity, self.client)
        if self._started:
            factory.start()
            factory.wait_for_cache_sync()
        return identity

    def _on_leader_started(self, election: str, identity: str) -> None:
        self.injector.record("leader_acquired", election, identity)
        if election == "kube-scheduler":
            self._sched_leader = identity
            self._sched_factory, self.scheduler = \
                self._sched_instances[identity]
        else:
            self._cm_leader = identity
            (self.factory, self.nodelifecycle, self.podgroups,
             self.podgc) = self._cm_instances[identity]
        pending = self._failover_start.get(election)
        if pending is not None:
            lost_at, lost_holder = pending
            if lost_holder == identity:
                # the deposed holder re-acquired its own (never-expired)
                # lease: leadership lapsed locally but never moved
                self._failover_start.pop(election, None)
            elif election != "kube-scheduler":
                # controllers: failover completes at acquisition (there
                # is no bind to anchor on)
                self._complete_failover(election)

    def _on_leader_stopped(self, election: str, identity: str) -> None:
        """The holder fenced itself (renew deadline missed) or released.
        This event PRECEDING the standby's leader_acquired in the
        step-ordered log is the provable stop-before-takeover the
        double-bind invariant asserts."""
        self.injector.record("leader_deposed", election, identity)
        # a NEW loss restarts the failover clock: a pending measurement
        # that never saw a bind (nothing to schedule during the gap) must
        # not inflate the next failover's timing
        self._failover_start[election] = (self.clock.now(), identity)
        if election == "kube-scheduler" and self._sched_leader == identity:
            self._sched_leader = None
        elif election == "kube-controller-manager" \
                and self._cm_leader == identity:
            self._cm_leader = None

    def _complete_failover(self, election: str) -> None:
        lost_at, _holder = self._failover_start.pop(election)
        seconds = self.clock.now() - lost_at
        self.injector.record("leader_failover", election, seconds)
        self.metrics.leader_failover_seconds.observe(
            seconds, name=election)

    def _note_bind(self, identity: str, n: int) -> None:
        """A scheduler replica committed `n` binds. Stamped into the
        step-ordered event log with the CURRENT holder so the double-bind
        sweep can prove no deposed replica ever bound after losing the
        lease; the first bind by a NEW leader closes the pending
        failover-timing measurement."""
        holder = self._sched_leader
        self.injector.record("bind", identity, n)
        self.bind_log.append((self.injector.step, identity, n, holder))
        if "kube-scheduler" in self._failover_start \
                and identity == holder:
            self._complete_failover("kube-scheduler")

    def check_ha_binds(self) -> List[str]:
        """The zero-double-bind sweep over the event log: every bind must
        come from the identity holding the scheduler lease AT THAT POINT
        IN THE LOG — a deposed leader binding after the standby acquired
        (or after its own fencing) is the split-brain this invariant
        exists to catch."""
        out: List[str] = []
        holder = None
        for ev in self.injector.events:
            kind = ev[1]
            if kind == "leader_acquired" and ev[2] == "kube-scheduler":
                holder = ev[3]
            elif kind == "leader_deposed" and ev[2] == "kube-scheduler":
                if holder == ev[3]:
                    holder = None
            elif kind == "kill_leader" and ev[2] == "kube-scheduler":
                if holder == ev[3]:
                    holder = None
            elif kind == "bind":
                identity = ev[2]
                if identity != holder:
                    out.append(
                        f"ha-double-bind: step {ev[0]}: {identity} bound "
                        f"{ev[3]} pod(s) while the scheduler lease "
                        f"holder was {holder!r}")
        return out

    # ------------------------------------------------------ overload storm

    def _storm_live(self) -> bool:
        return self.injector.step < self._storm_until

    def _ensure_storm_threads(self) -> None:
        """(Re)spawn the burst tenant's worker pool for a storm window.
        Workers die on their own once the window passes; a later
        client_storm event spawns a fresh generation."""
        self._storm_threads = [t for t in self._storm_threads
                               if t.is_alive()]
        if self._storm_threads:
            return  # window extended; the live generation keeps going
        self._storm_gen += 1
        gen = self._storm_gen
        for i in range(self.overload):
            t = threading.Thread(target=self._storm_worker,
                                 args=(gen, i), daemon=True,
                                 name=f"storm-{gen}-{i}")
            t.start()
            self._storm_threads.append(t)

    def _storm_worker(self, gen: int, idx: int) -> None:
        """One burst-tenant client: alternately LIST the default
        namespace's pods (the dashboard-hammering read) and create
        ConfigMaps in the "abuse" namespace (the bulk-write side),
        self-declared workload-low via the APF priority hint. ConfigMaps
        on purpose: they are invisible to the informers, controllers,
        and store_state, so the storm's writes cannot perturb scheduling
        outcomes — only contend for hub capacity. A ~1ms think time per
        request stands in for client-side RTT: without it the workers
        busy-spin the GIL and the bench measures interpreter scheduling,
        not hub overload (the offered load still far exceeds the 2-slot
        write pool)."""
        from ..apiserver.flowcontrol import PRIORITY_HINT_HEADER
        base = self._server.address
        hint = {PRIORITY_HINT_HEADER: "workload-low"}
        n = 0
        while self._storm_live():
            n += 1
            self.wall_clock.sleep(0.001)
            try:
                if n % 2:
                    req = urlrequest.Request(
                        f"{base}/api/v1/namespaces/default/pods",
                        headers=dict(hint))
                else:
                    body = json.dumps({
                        "apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {
                            "name": f"storm-g{gen}-t{idx}-{n}",
                            "namespace": "abuse"},
                        "data": {"k": "v" * 64}}).encode()
                    req = urlrequest.Request(
                        f"{base}/api/v1/namespaces/abuse/configmaps",
                        data=body, method="POST",
                        headers={"Content-Type": "application/json",
                                 **hint})
                with urlrequest.urlopen(req, timeout=5) as resp:
                    resp.read()
                with self._storm_lock:
                    self._storm_requests += 1
                    self._storm_ok += 1
            except urlerror.HTTPError as e:
                try:
                    e.read()  # drain the error body, as a real client would
                except OSError:
                    pass
                with self._storm_lock:
                    self._storm_requests += 1
                    if e.code == 429:
                        self._storm_rejected += 1
                    else:
                        self._storm_errors += 1
            except Exception:
                with self._storm_lock:
                    self._storm_requests += 1
                    self._storm_errors += 1

    def _stop_storms(self) -> None:
        self._storm_until = -1
        for t in self._storm_threads:
            t.join(timeout=10)
        self._storm_threads = []

    def check_overload(self) -> List[str]:
        """The overload drill's invariants, valid for an APF-on run
        whose only scheduled faults are client storms: the system flow's
        isolation must keep leader leases entirely healthy. Any
        leader_deposed event is a spurious self-fence (nobody killed a
        leader), any leader_failover a spurious failover, and any slow
        renew means a lease write sat behind tenant traffic past half
        its renew deadline. check_ha_binds covers double-binds."""
        out: List[str] = []
        deposed = [ev for ev in self.injector.events
                   if ev[1] == "leader_deposed"]
        if deposed:
            out.append(f"overload-spurious-fence: {len(deposed)} "
                       f"leader_deposed under client storm "
                       f"(first: {deposed[0]})")
        failovers = [ev for ev in self.injector.events
                     if ev[1] == "leader_failover"]
        if failovers:
            out.append(f"overload-spurious-failover: {len(failovers)} "
                       f"failover(s) under client storm")
        slow = sum(
            self.metrics.slow_renews.value(name=e)
            for e in ("kube-scheduler", "kube-controller-manager"))
        if slow:
            out.append(f"overload-starved-renew: {int(slow)} lease "
                       f"renew(s) landed past half the renew deadline "
                       f"under client storm")
        return out

    # ------------------------------------------------------------- setup

    def _slice_of(self, i: int) -> str:
        return f"s{i // self.nodes_per_slice}"

    def start(self) -> None:
        if self._started:
            return
        for i in range(self.n_nodes):
            self._register_node(i)
        if self.overload:
            # the burst tenant's namespace, labeled so the hub's flow
            # key resolves to the tenant ("burst"), not the namespace
            from ..api.core import Namespace
            from ..state.store import AlreadyExistsError
            from ..tenancy import TENANT_LABEL
            try:
                self.admin.namespaces().create(Namespace(
                    metadata=ObjectMeta(name="abuse",
                                        labels={TENANT_LABEL: "burst"})))
            except AlreadyExistsError:
                pass  # WAL replay already restored it
        if self._replica is not None and self._read_client is not None:
            # replica reads: the follower must finish its initial sync
            # BEFORE informers list through the standby hub, or their
            # first LIST would see an empty follower store
            self._replica.start()
            self._replica.wait_synced()
        for fac in self._factories():
            fac.start()
            fac.wait_for_cache_sync()
        if self._replica is not None and self._read_client is None:
            self._replica.start()
            self._replica.wait_synced()
        self._settle()
        self._started = True

    def _register_node(self, i: int) -> None:
        alloc = {"cpu": Quantity("4"), "memory": Quantity("32Gi"),
                 "pods": Quantity("110")}
        node = Node(metadata=ObjectMeta(
            name=f"node-{i}", labels={SLICE_LABEL: self._slice_of(i)}))
        node.status.capacity = dict(alloc)
        node.status.allocatable = dict(alloc)
        node.status.conditions = [NodeCondition(
            type="Ready", status="True", reason="KubeletReady",
            last_heartbeat_time=now_iso(self.clock))]
        self.admin.nodes().create(node)

    def close(self) -> None:
        self._stop_storms()
        for fac in self._factories():
            fac.stop()
        if self._read_server is not None:
            self._read_server.stop()
        if self._replica is not None:
            self._replica.stop()
            if not self._promoted:
                self._replica.store.close()
        if self._server is not None:
            self._server.stop()
        self.admin.store.close()

    # ---------------------------------------------------------- restarts

    def restart_scheduler(self) -> bool:
        """Crash-replace the scheduler: its informers stop, and its
        cache, in-flight assumed pods, and gang permit-gate reservations
        die with the process. The replacement rebuilds every bit of that
        from a fresh informer sync — unbound members requeue, gangs
        re-reserve — which is exactly the recovery under test. In HA
        mode a scheduler 'restart' IS a leader kill: the holder crashes
        and the standby takes over at lease expiry. Returns False only
        when nothing was crashed (HA mid-failover: nobody holds the
        lease, so there is no process to kill)."""
        if self.ha:
            return self.kill_leader("kube-scheduler") is not None
        self.injector.record("restart_scheduler")
        self._sched_factory.stop()
        self.scheduler.crash()
        self._sched_factory = SharedInformerFactory(
            self.client, read_client=self._current_read_client())
        self.scheduler = self._build_scheduler(self._sched_factory)
        self._sched_factory.start()
        self._sched_factory.wait_for_cache_sync()
        self._settle()
        return True

    def restart_controller_manager(self) -> bool:
        """Crash-replace the controller manager's loops (nodelifecycle,
        podgroup, podgc) and their shared informers. Controller-side soft
        state — eviction timers, resubmission rate limits — is lost and
        re-derived from observations, so recovery may converge LATER but
        must still converge."""
        if self.ha:
            return self.kill_leader("kube-controller-manager") is not None
        self.injector.record("restart_controllers")
        self.factory.stop()
        self.factory = SharedInformerFactory(
            self.client, read_client=self._current_read_client())
        self._build_controllers(self.factory)
        self.factory.start()
        self.factory.wait_for_cache_sync()
        self._settle()
        return True

    def restart_store(self, torn: int = 0) -> int:
        """WAL-replay the store in place mid-run (the etcd/apiserver
        restart analog). Every live watch stream is severed; informers
        must resume or relist against the replayed state. No-op without
        a wal_path — a journal-less restart would be data loss, which is
        a different (unrecoverable) fault class.

        `torn=N` makes it durable-state LOSS, not just a crash: the last
        N journal records vanish before the replay (state/wal.tear_wal),
        the rv clock REGRESSES, and the recovery machinery under test is

          - the store answering 410 to any resume at a now-future rv,
          - informers relisting and pruning ghosts their caches hold but
            the store lost,
          - the scheduler forgetting/requeueing regressed binds (gangs
            whole-group),
          - virtual kubelets orphan-GCing containers for pods the store
            no longer knows.

        Returns the number of records actually torn (the journal may
        hold fewer than requested; 0 for a plain restart)."""
        if self.wal_path is None:
            return 0
        actual = self.admin.store.restart(torn=torn)
        if torn > 0:
            # recorded with the ACTUAL count — the report's data-loss
            # accounting must not overstate a tear the journal could
            # only partially honor
            self.injector.tear_wal(actual)
        self.injector.record("restart_store")
        self._settle()
        return actual

    def kill_leader(self, election: str) -> Optional[str]:
        """Crash the election's current holder WITHOUT a release — the
        lease stays stamped with a dead identity and the standby must
        wait out the full lease duration before acquiring (the crash
        failover path, vs suppress_lease's fencing path). The crashed
        replica is replaced by a fresh standby under a NEW identity.
        Returns the killed identity, or None when nobody held the lease
        (already mid-failover)."""
        assert self.ha, "kill_leader requires ChaosHarness(ha=True)"
        holder = self._sched_leader if election == "kube-scheduler" \
            else self._cm_leader
        if holder is None:
            return None
        self.injector.record("kill_leader", election, holder)
        self._failover_start[election] = (self.clock.now(), holder)
        self._electors.pop(holder, None)
        if election == "kube-scheduler":
            factory, sched = self._sched_instances.pop(holder)
            factory.stop()
            sched.crash()
            self._sched_leader = None
            self._spawn_sched_instance()
        else:
            factory, *_ = self._cm_instances.pop(holder)
            factory.stop()
            self._cm_leader = None
            self._spawn_cm_instance()
        self._settle()
        return holder

    # ----------------------------------------------------- promote drill

    def _replica_barrier(self, timeout: float = 15.0) -> None:
        """Wall-clock catch-up barrier against a STATIC primary (post-
        quiesce, or pre-promote with the drill's schedule paused): wait
        until the follower's contents match the primary's. On timeout the
        replication sweep that follows reports the divergence — the
        barrier only bounds how long we give the follower to drain its
        stream, it never hides a loss."""
        if self._replica is None:
            return
        want = self.admin.store.contents()
        deadline = self.wall_clock.now() + timeout
        while self.wall_clock.now() < deadline:
            if self._replica.store.contents() == want:
                return
            self.wall_clock.sleep(0.01)

    def promote_replica(self, timeout: float = 30.0) -> List[str]:
        """The replica-promote drill (replica=True): kill the primary
        store FOR GOOD, gate on the follower being fully synced, promote
        it, and fail every client and informer over to the standby.
        Components keep their caches — informers reconnect at
        last_sync_rv against the standby (the StoreReplica preserved the
        primary's rv timeline, so where the rvs allow, failover costs a
        reconnect, not a relist).

        Returns (and remembers, for the report) the drill's violations:
        an rv timeline that regressed across the promote, or an
        acknowledged write below the replication horizon that the
        standby lost."""
        assert self._replica is not None, "ChaosHarness(replica=True)"
        assert not self._promoted, "promote is one-way"
        primary = self.admin.store
        primary.flush_wal()
        target_rv = primary.resource_version
        horizon = primary.contents()
        self.injector.record("kill_primary", target_rv)
        # barrier: an etcd learner refuses promotion until caught up —
        # wait (wall_clock: follower threads pump frames in real time)
        # for the standby to hold exactly the primary's final state
        deadline = self.wall_clock.now() + timeout
        while self.wall_clock.now() < deadline:
            if self._replica.store.contents() == horizon \
                    and self._replica.store.resource_version >= target_rv:
                break
            self.wall_clock.sleep(0.01)
        promoted = self._replica.promote()
        violations: List[str] = []
        if promoted.resource_version < target_rv:
            violations.append(
                f"promote: rv timeline regressed "
                f"({promoted.resource_version} < {target_rv})")
        got = promoted.contents()
        for key, rv in sorted(horizon.items()):
            if got.get(key) != rv:
                violations.append(
                    f"promote: acknowledged write {key}@{rv} below the "
                    f"replication horizon lost (standby has "
                    f"{got.get(key)})")
        # the primary dies for good; every component fails over
        old_server = None
        if self.http:
            # wire mode: a STANDBY hub comes up over the promoted store
            # and every component's HTTP client is rebuilt against its
            # address (wire faults and all); the old hub — and the
            # primary store under it — die only after the repoint, so
            # in-flight streams sever into a reconnect, not a hang
            from ..apiserver.server import APIServer
            from ..apiserver.httpclient import HTTPClient
            old_server = self._server
            self._server = APIServer(
                store=promoted,
                metrics=self._make_server_metrics()).start()
            self._server.attach_replica(self._replica)
            new_client = ChaosHTTPClient(
                self.injector,
                HTTPClient(self._server.address,
                           wire_hook=self.injector.make_wire_hook()))
        else:
            new_client = ChaosClient(self.injector, store=promoted)
        self.admin = Client(promoted)
        self.client = new_client
        if self.ha:
            for identity, (factory, sched) in self._sched_instances.items():
                sched.client.inner = new_client  # _HAClient
                factory.repoint(sched.client)
            for identity, (factory, nlc, pg, gc) in \
                    self._cm_instances.items():
                nlc.client = new_client
                pg.client = new_client
                gc.client = new_client
                factory.repoint(new_client)
            for el in self._electors.values():
                el.client = new_client
        else:
            self.scheduler.client = new_client
            self._sched_factory.repoint(new_client)
            self.nodelifecycle.client = new_client
            self.podgroups.client = new_client
            self.podgc.client = new_client
            self.factory.repoint(new_client)
        if self.autoscaler is not None:
            self.autoscaler.client = new_client
            self._ca_factory.repoint(new_client)
        if self._read_server is not None:
            # the promoted store is now the PRIMARY (served by the new
            # hub above); the standby read hub over it retires, and the
            # router with it — factory.repoint already collapsed every
            # informer's read path onto the promoted client
            self._read_server.stop()
            self._read_server = None
            self._read_client = None
            self._read_router = None
        if old_server is not None:
            old_server.stop()
        primary.close()
        # the standby journals what it applied: the WAL-replay invariant
        # now checks the promoted store against ITS OWN journal
        self.wal_path = self.wal_path + ".replica"
        self._promoted = True
        self._promote_violations = violations
        self.injector.record("promote", promoted.resource_version)
        self._settle()
        return violations

    # ---------------------------------------------------------- schedule

    def make_schedule(self, n_events: int) -> List[dict]:
        """The run's chaos script: a pure function of (seed, n_events).
        Every parameter an action needs is drawn here, so applying the
        schedule consumes no randomness — cluster state can influence
        WHAT an action amounts to (killing an already-dead node is a
        no-op) but never the script itself."""
        # string seeding is process-stable (sha512), tuple seeding is not
        rng = random.Random(f"chaos-schedule:{self.seed}")
        table = _ACTIONS
        if self.with_restarts:
            table = table + _RESTART_ACTIONS
        if self.with_tears:
            table = table + _TEAR_ACTIONS
        if self.ha:
            table = table + _HA_ACTIONS
        if self.overload:
            table = table + _OVERLOAD_ACTIONS
        names = [a for a, _ in table]
        weights = [w for _, w in table]
        out = []
        for _ in range(n_events):
            action = rng.choices(names, weights=weights)[0]
            # every event draws every parameter its flag set can consume
            # (whether or not THIS action uses it), so the schedule is a
            # pure function of (seed, n_events, flags) — and with the
            # tear/ha flags off, byte-identical to earlier PRs' schedules
            ev = {"action": action,
                  "node": rng.randrange(self.n_nodes),
                  "size": rng.randint(2, self.nodes_per_slice),
                  "cpu_m": rng.choice((250, 500, 750, 1000))}
            if self.with_tears:
                ev["torn"] = rng.randint(1, 8)
            if self.ha:
                ev["election"] = rng.choice(("kube-scheduler",
                                             "kube-controller-manager"))
            if self.preempt_storm:
                ev["priority"] = rng.choice((0, 10, 100, 1000))
            if self.overload:
                ev["storm_ticks"] = rng.randint(2, 4)
            out.append(ev)
        return out

    # -------------------------------------------------------------- run

    def run(self, n_events: int = 100, quiesce_steps: int = 30,
            promote_at_step: Optional[int] = None) -> ChaosReport:
        self.start()
        report = ChaosReport(seed=self.seed, steps=n_events)
        for step, ev in enumerate(self.make_schedule(n_events)):
            self.injector.advance(step)
            if promote_at_step == step and self._replica is not None \
                    and not self._promoted:
                # the drill rides the schedule at a FIXED step, so the
                # event log stays a pure function of (seed, args)
                self.promote_replica()
            self._apply(ev, report)
            self._tick()
        # quiesce: faults stop, dead nodes STAY dead — eviction timeouts,
        # permit rollbacks, and resubmissions must now converge on their
        # own; the invariants are checked against this settled state
        self._stop_storms()
        self.injector.error_rate = 0.0
        if self.injector.partitioned:
            self.injector.partition(False)
        if self.injector.lease_suppressed:
            self.injector.suppress_lease(False)  # a leader must re-emerge
        for step in range(n_events, n_events + quiesce_steps):
            self.injector.advance(step)
            self._tick()
        # final housekeeping pass: the last tick's PodGroup syncs may have
        # orphaned permit reservations (resubmission deleting a waiting
        # member); one more scheduling cycle drains them before the sweep
        # (in HA mode only the lease holder may run it — and after the
        # unsuppressed quiesce one always has re-emerged)
        if not self.ha or self._sched_leader is not None:
            self.scheduler.schedule_pending(timeout=0)
            self.scheduler.cache.cleanup_expired_assumed_pods()
        self._settle()
        from ..api.core import Node as NodeCls, Pod as PodCls
        checker = InvariantChecker(self.admin, scheduler=self.scheduler,
                                   wal_path=self.wal_path,
                                   factories=self._factories(),
                                   informer_classes=(PodCls, NodeCls,
                                                     PodGroup))
        report.violations = checker.check()
        if self.ha:
            report.violations += self.check_ha_binds()
            report.failovers = [
                (ev[2], ev[3]) for ev in self.injector.events
                if ev[1] == "leader_failover"]
        if (self.overload and self.ha and self.enable_storms
                and not self.enable_restarts
                and self._base_error_rate == 0.0
                and self._server is not None and self._server.apf):
            # the strict overload invariants hold only when client
            # storms are the ONLY fault in play (restarts off, no
            # injected API errors — an injected lease-patch failure
            # causes a legitimate slow renew) and APF is actually on —
            # the KTPU_APF=0 control leg is EXPECTED to starve and must
            # not be flagged
            report.violations += self.check_overload()
        report.violations += self._promote_violations
        if self._replica is not None and not self._promoted:
            # the quiesced primary is static: the follower must converge
            # to EXACTLY its contents (a wall-clock catch-up barrier,
            # then the replication sweep — every acknowledged record at
            # the same rv, no forks)
            self._replica_barrier()
            from .invariants import check_replication
            report.violations += check_replication(self.admin.store,
                                                   self._replica.store)
        if self._replica is not None:
            report.replication_lag_records = self._replica.last_lag_records
            report.replication_max_lag_records = \
                self._replica.max_lag_records
            report.replication_reconnects = self._replica.reconnects
        if self.slo is not None:
            report.slo = self.slo.report()
        with self._storm_lock:
            report.storm_requests = self._storm_requests
            report.storm_ok = self._storm_ok
            report.storm_rejected = self._storm_rejected
            report.storm_errors = self._storm_errors
        report.fault_counts = dict(self.injector.fault_counts)
        report.promoted = self._promoted
        report.orphans_gced = self._orphans_gced
        report.events = list(self.injector.events)
        report.pods_bound = sum(
            1 for p in self.admin.pods().list(namespace=None)
            if p.spec.node_name)
        report.resubmissions = sum(
            pg.status.resubmissions
            for pg in self.admin.pod_groups().list(namespace=None))
        report.store_state = self.store_state()
        return report

    def span_log(self) -> str:
        """The run's span trail as deterministic JSONL (virtual-clock
        timestamps, store-counter UIDs, canonical ordering): the
        surface the same-seed byte-identity test compares."""
        return self.tracer.recorder.export_jsonl()

    def store_state(self) -> List[Tuple]:
        """The run's semantic end state: which objects exist, each pod's
        phase and whether it is bound — NOT which node (fault-driven
        retries may legitimately land a pod elsewhere) and NOT rvs. The
        surface on which a faulted run is compared to a fault-free run
        of the same schedule."""
        out: List[Tuple] = []
        for n in self.admin.nodes().list():
            out.append(("nodes", "", n.metadata.name, "", False))
        for p in self.admin.pods().list(namespace=None):
            out.append(("pods", p.metadata.namespace, p.metadata.name,
                        p.status.phase or "", bool(p.spec.node_name)))
        for pg in self.admin.pod_groups().list(namespace=None):
            out.append(("podgroups", pg.metadata.namespace,
                        pg.metadata.name, pg.status.phase or "", False))
        return sorted(out)

    def _apply(self, ev: dict, report: ChaosReport) -> None:
        action = ev["action"]
        node = f"node-{ev['node']}"
        if action == "create_gang":
            self._create_gang(ev["size"], ev["cpu_m"],
                              priority=ev.get("priority"))
            report.gangs_created += 1
        elif action == "create_singleton":
            self._create_pod(self._next_pod_name("solo"), ev["cpu_m"],
                             priority=ev.get("priority"))
        elif action == "kill_node":
            if self._node_exists(node) and self.injector.node_alive(node):
                self.injector.kill_node(node)
                report.nodes_killed += 1
        elif action == "restart_node":
            if self._node_exists(node):
                self.injector.restart_node(node)
        elif action == "drop_heartbeat":
            if self._node_exists(node) and self.injector.node_alive(node):
                self.injector.suppress_heartbeat(node)
        elif action == "resume_heartbeat":
            self.injector.resume_heartbeat(node)
        elif action == "delete_node":
            if self._node_exists(node):
                self.injector.kill_node(node)
                try:
                    self.admin.nodes().delete(node)
                except NotFoundError:
                    pass
                self.injector.record("delete_node", node)
                report.nodes_deleted += 1
        elif action == "partition":
            # overload drills keep the client storm as the ONLY fault: a
            # scheduled write partition would fence leaders on its own
            # and confound the starved-renew attribution (the schedule
            # keeps the partition events so flag-off runs stay
            # byte-identical; they just don't fire)
            if not self.injector.partitioned and not self.overload:
                self.injector.partition(True)
        elif action == "heal":
            if self.injector.partitioned:
                self.injector.partition(False)
        elif action == "restart_scheduler":
            if self.enable_restarts and self.restart_scheduler():
                report.scheduler_restarts += 1
        elif action == "restart_controllers":
            if self.enable_restarts and self.restart_controller_manager():
                report.controller_restarts += 1
        elif action == "restart_store":
            if self.enable_restarts and self.wal_path is not None:
                self.restart_store()
                report.store_restarts += 1
        elif action == "tear_wal":
            if self.enable_restarts and self.wal_path is not None \
                    and not self._promoted:
                report.records_torn += self.restart_store(torn=ev["torn"])
                report.store_restarts += 1
                report.wal_tears += 1
        elif action == "kill_leader":
            if self.enable_restarts and self.ha:
                if self.kill_leader(ev["election"]) is not None:
                    report.leader_kills += 1
        elif action == "suppress_lease":
            # gated like the restart actions: a fault-free control run
            # (enable_restarts=False) keeps the identical schedule but
            # never actually suppresses the election lock
            if self.ha and self.enable_restarts \
                    and not self.injector.lease_suppressed:
                self.injector.suppress_lease(True)
                report.lease_suppressions += 1
        elif action == "resume_lease":
            if self.ha and self.injector.lease_suppressed:
                self.injector.suppress_lease(False)
        elif action == "client_storm":
            # gated like the restart actions: the storm-free baseline
            # (enable_storms=False) keeps the identical schedule but
            # never opens a storm window
            if self.overload and self.enable_storms:
                self._storm_until = max(
                    self._storm_until,
                    self.injector.step + ev["storm_ticks"])
                self.injector.record("client_storm", ev["storm_ticks"])
                report.storm_windows += 1
                self._ensure_storm_threads()

    def _node_exists(self, name: str) -> bool:
        try:
            self.admin.nodes().get(name)
            return True
        except NotFoundError:
            return False

    def _next_pod_name(self, prefix: str) -> str:
        self._pod_counter += 1
        return f"{prefix}-{self._pod_counter}"

    def _create_gang(self, size: int, cpu_m: int,
                     priority: Optional[int] = None) -> None:
        self._gang_counter += 1
        gname = f"gang-{self._gang_counter}"
        self.admin.pod_groups("default").create(PodGroup(
            metadata=ObjectMeta(name=gname, namespace="default"),
            spec=PodGroupSpec(min_member=size, topology_key=SLICE_LABEL,
                              schedule_timeout_seconds=self.gang_timeout)))
        for i in range(size):
            self._create_pod(f"{gname}-w{i}", cpu_m, group=gname,
                             priority=priority)
        self.injector.record("create_gang", gname, size)

    def _create_pod(self, name: str, cpu_m: int,
                    group: Optional[str] = None,
                    priority: Optional[int] = None) -> None:
        from ..api.core import (Container, PodSpec, ResourceRequirements)
        labels = {}
        if group is not None:
            from ..api.wellknown import LABEL_POD_GROUP
            labels[LABEL_POD_GROUP] = group
        if self.slo is not None:
            # the serving class the SLO tracker buckets by: gang members
            # vs singletons — two latency populations worth separating
            # (gangs wait at the permit gate; solos don't)
            from ..serving.loadgen import CLASS_LABEL
            labels[CLASS_LABEL] = "gang" if group is not None else "solo"
        pod = Pod(
            metadata=ObjectMeta(name=name, namespace="default",
                                labels=labels),
            spec=PodSpec(priority=priority, containers=[Container(
                name="c", image="img",
                resources=ResourceRequirements(
                    requests={"cpu": Quantity(f"{cpu_m}m"),
                              "memory": Quantity("256Mi")}))]))
        self.admin.pods("default").create(pod)

    # -------------------------------------------------------------- tick

    def _tick(self) -> None:
        """One control-plane step: virtual kubelets beat and report, each
        control loop runs once, virtual time advances, informers settle.
        In HA mode the elections step first and only the CURRENT lease
        holders' loops run — a replica that fenced itself (or never
        acquired) is provably idle, which is the double-bind invariant's
        mechanism under test."""
        self._virtual_kubelets()
        self._settle()
        if self.ha:
            # sorted order: elector stepping must be deterministic
            for identity in sorted(self._electors):
                self._electors[identity].step()
        cm_active = not self.ha or self._cm_leader is not None
        sched_active = not self.ha or self._sched_leader is not None
        if cm_active:
            try:
                self.nodelifecycle.monitor_once()
            except Exception:
                pass  # a partitioned monitor pass retries next tick
            try:
                self.podgc.gc_once()
            except Exception:
                pass
            self._settle()
        if sched_active:
            try:
                self.scheduler.schedule_pending(timeout=0)
            except Exception:
                pass
            self.scheduler.cache.cleanup_expired_assumed_pods()
            self._settle()
        if self.autoscaler is not None:
            # after the scheduler's cycle so demand reflects this tick's
            # failed attempts; step() swallows-and-counts its own API
            # faults, so a faulted pass retries next tick
            self.autoscaler.step()
            self._settle()
        if cm_active:
            for pg in self.admin.pod_groups().list(namespace=None):
                try:
                    self.podgroups.sync(pg.metadata.key())
                except Exception:
                    pass  # chaos mid-resubmit: the next tick re-syncs
                self._settle()
        if self._replica is not None and not self._promoted:
            # one lag sample per tick: primary rv vs the follower's
            # high-water mark (sets the replication_lag_records gauge).
            # With replica reads on, the router samples — and rotates a
            # follower past the lag threshold out of read rotation.
            if self._read_router is not None:
                self._read_router.tick(self.admin.store.resource_version)
            else:
                self._replica.observe_lag(self.admin.store.resource_version)
        if self.slo is not None:
            # settled pod listing, sorted-key order, shared FakeClock —
            # the per-class bind/startup stamps are deterministic
            self.slo.scan(self.admin.pods().list(namespace=None))
        self.clock.step(self.clock_step)

    def _virtual_kubelets(self) -> None:
        """The hollow node fleet: every live node heartbeats (unless the
        injector silenced it) and reports its non-terminal bound pods
        Running — through the ADMIN client, so kubelet-side writes are
        not part of the injected fault surface.

        Container tracking: a kubelet that marked a pod Running holds a
        "container" for it. Each pass ORPHAN-GCs containers whose pod
        the store no longer binds to this node — after a torn-WAL
        restart the store may have forgotten a pod entirely (its create
        was in the lost tail) while the kubelet still runs its workload;
        a real kubelet's syncLoop kills exactly these."""
        nodes = sorted(n.metadata.name for n in self.admin.nodes().list())
        alive = {n for n in nodes if self.injector.node_alive(n)}
        placed = {}
        for pod in self.admin.pods().list(namespace=None):
            if pod.spec.node_name:
                placed.setdefault(pod.spec.node_name, set()).add(
                    pod.metadata.key())
        for node in sorted(self._containers):
            if node in alive:
                orphans = self._containers[node] - placed.get(node, set())
                if not orphans:
                    continue
                self._containers[node] -= orphans
                self._orphans_gced += len(orphans)
                self.metrics.kubelet_orphans_gced.inc(len(orphans))
                self.injector.record("kubelet_orphan_gc", node,
                                     len(orphans))
            if not self._containers[node]:
                del self._containers[node]
        for name in nodes:
            if not self.injector.allow_heartbeat(name):
                continue

            def beat(cur):
                for cond in cur.status.conditions:
                    if cond.type == "Ready":
                        cond.status = "True"
                        cond.reason = "KubeletReady"
                        cond.last_heartbeat_time = now_iso(self.clock)
                        return cur
                cur.status.conditions.append(NodeCondition(
                    type="Ready", status="True", reason="KubeletReady",
                    last_heartbeat_time=now_iso(self.clock)))
                return cur
            try:
                self.admin.nodes().patch(name, beat)
            except NotFoundError:
                pass
        for pod in self.admin.pods().list(namespace=None):
            if not pod.spec.node_name or pod.spec.node_name not in alive:
                continue
            if pod.status.phase in ("Running", "Succeeded", "Failed"):
                continue

            def run_status(cur):
                if cur.status.phase in ("Succeeded", "Failed"):
                    return cur  # never resurrect a terminal pod
                cur.status.phase = "Running"
                return cur
            try:
                self.admin.pods(pod.metadata.namespace).patch(
                    pod.metadata.name, run_status)
                self._containers.setdefault(
                    pod.spec.node_name, set()).add(pod.metadata.key())
                # the kubelet-Running leg of the pod's trace (driver
                # thread, sorted pod order — deterministic)
                self.tracer.pod_event("kubelet", "running", pod,
                                      node=pod.spec.node_name)
            except NotFoundError:
                pass

    # ------------------------------------------------------------ settle

    def _settle(self, timeout: float = 10.0) -> None:
        """The shared settling contract (see settle_informers) over the
        chaos harness's resource classes — control-loop inputs must be
        identical across runs so the fault oracle sees identical call
        streams."""
        from ..api.core import Node as NodeCls, Pod as PodCls
        settle_informers(self.admin, self._factories(),
                         (PodCls, NodeCls, PodGroup), self.injector,
                         timeout=timeout, logger_name="chaos",
                         step=self.injector.step, clock=self.wall_clock)
