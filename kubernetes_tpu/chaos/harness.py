"""ChaosHarness — a seeded, virtual-clock chaos soak over an in-process cluster.

The harness stands up the full control plane against one store — batch
scheduler (gang gates included), NodeLifecycleController, PodGroup
controller, pod GC — plus VIRTUAL kubelets (the harness itself heartbeats
nodes and marks bound pods Running), all on a shared FakeClock. A run is
driven by a schedule of chaos actions derived purely from the seed:
workload creation (gangs + singletons), node crashes and restarts,
heartbeat suppression, node deletion, apiserver write partitions, and a
background injected API error rate on every control-plane write.

WIRE mode (`http=True`): a real `APIServer` serves the store and every
control-plane component talks to it over actual HTTP — informers included
— through `ChaosHTTPClient`, so the injector's wire fault classes
(request latency, connection resets, watch-stream drops) hit the real
transport: sockets reset, watch streams die mid-flight and RESUME at
last_sync_rv, exactly the failure surface a remote hub has.

COMPONENT RESTARTS (`with_restarts=True` adds them to the schedule; the
methods are also directly callable): `restart_scheduler` crash-replaces
the scheduler — its cache, assumed pods, and gang permit reservations die
with it and must be rebuilt from informers; `restart_controller_manager`
does the same for the controllers; `restart_store` WAL-replays the store
in place (the etcd-restart analog), severing every live watch stream so
clients must relist or resume.

Determinism contract: the schedule is pregenerated from `seed` before the
run; every control loop is stepped SYNCHRONOUSLY from the single driver
thread; after each step the harness settles (waits until each informer's
indexer matches the store) so informer-thread timing cannot change which
calls the next step issues. Two runs with the same seed therefore produce
identical FaultInjector event logs — `report.events`. (Read-path wire
faults fire on informer threads and are deliberately excluded from the
step-ordered log — see injector.py.)

After the scheduled events, the run quiesces (faults off, dead nodes stay
dead) long enough for eviction timeouts, permit timeouts, and gang
resubmissions to converge, then sweeps the InvariantChecker. A green
report means: no PodGroup partially bound, no cache assume or permit
reservation on a dead node, and the WAL replays to the live store.
`report.store_state` is the run's SEMANTIC end state (which objects
exist, each pod's phase and boundness — not which node, not rv): a
faulted run must converge to the same store_state as a fault-free run of
the same schedule, or the faults leaked into outcomes.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..api.core import Node, NodeCondition, Pod
from ..api.meta import ObjectMeta
from ..api.quantity import Quantity
from ..api.scheduling import PodGroup, PodGroupSpec
from ..controllers.nodelifecycle import NodeLifecycleController
from ..controllers.podgc import PodGCController
from ..controllers.podgroup import PodGroupController
from ..scheduler.scheduler import Scheduler
from ..state.client import Client
from ..state.informer import SharedInformerFactory
from ..state.store import NotFoundError, Store
from ..utils.clock import FakeClock, now_iso
from ..utils.metrics import RobustnessMetrics
from .injector import ChaosClient, ChaosHTTPClient, FaultInjector
from .invariants import InvariantChecker

SLICE_LABEL = "tpu/slice"

#: (action, weight) — the seed-derived schedule draws from these
_ACTIONS = (("create_gang", 0.26), ("create_singleton", 0.14),
            ("kill_node", 0.12), ("restart_node", 0.10),
            ("drop_heartbeat", 0.08), ("resume_heartbeat", 0.05),
            ("delete_node", 0.06), ("partition", 0.04), ("heal", 0.05),
            ("noop", 0.10))

#: appended when with_restarts=True — component crash/restart as
#: first-class chaos actions (rng.choices renormalizes the weights)
_RESTART_ACTIONS = (("restart_scheduler", 0.05),
                    ("restart_controllers", 0.04),
                    ("restart_store", 0.03))


def informers_current(admin, factories, classes) -> bool:
    """True when every ALREADY-CREATED informer for `classes` in each
    factory mirrors the store exactly. Informers a factory never created
    are skipped — probing with informer_for would lazily CREATE and
    START streams the owning component never reads (and re-create them
    after every restart), enlarging the wire fault surface."""
    store = admin.store
    for fac in factories:
        with fac._lock:
            informers = dict(fac._informers)
        for cls in classes:
            inf = informers.get(cls)
            if inf is None:
                continue
            resource = admin.scheme.resource_for(cls)
            items, _ = store.list(resource)
            want = {o.metadata.key(): o.metadata.resource_version
                    for o in items}
            have = {o.metadata.key(): o.metadata.resource_version
                    for o in inf.indexer.list()}
            if want != have:
                return False
    return True


def settle_informers(admin, factories, classes, injector,
                     timeout: float = 10.0, logger_name: str = "chaos",
                     step=None) -> bool:
    """Wait (REAL time) until informers_current holds twice in a row —
    the second check lets the last event's handler dispatch finish, so
    control-loop inputs are identical across same-seed runs. On timeout
    the next control loop runs on stale indexers and the run's event log
    may diverge; the log is stamped so a determinism failure points at
    the starved informer thread, not the harness logic."""
    deadline = time.time() + timeout
    streak = 0
    while time.time() < deadline:
        if informers_current(admin, factories, classes):
            streak += 1
            if streak >= 2:
                return True
            time.sleep(0.002)
        else:
            streak = 0
            time.sleep(0.002)
    import logging
    logging.getLogger(logger_name).warning(
        "informers failed to settle within %.1fs at step %s",
        timeout, step)
    injector.record("settle_timeout")
    return False


@dataclass
class ChaosReport:
    seed: int
    steps: int
    #: the injector's event log — identical across runs with one seed
    events: List[Tuple] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    pods_bound: int = 0
    gangs_created: int = 0
    resubmissions: int = 0
    nodes_killed: int = 0
    nodes_deleted: int = 0
    scheduler_restarts: int = 0
    controller_restarts: int = 0
    store_restarts: int = 0
    #: the semantic end state — sorted (resource, namespace, name,
    #: phase, bound) tuples; node choice and resourceVersions excluded.
    #: Comparable between a faulted and a fault-free run of one schedule.
    store_state: List[Tuple] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


class ChaosHarness:
    def __init__(self, seed: int = 0, nodes: int = 8,
                 nodes_per_slice: int = 4, error_rate: float = 0.05,
                 wal_path: Optional[str] = None,
                 clock_step: float = 5.0,
                 grace_period: float = 12.0,
                 eviction_timeout: float = 30.0,
                 gang_timeout: int = 60,
                 http: bool = False,
                 reset_rate: float = 0.0,
                 latency_rate: float = 0.0,
                 latency_max: float = 0.005,
                 watch_drop_rate: float = 0.0,
                 with_restarts: bool = False,
                 enable_restarts: bool = True):
        self.seed = seed
        self.n_nodes = nodes
        self.nodes_per_slice = max(1, nodes_per_slice)
        self.clock_step = clock_step
        self.gang_timeout = gang_timeout
        self.wal_path = wal_path
        self.grace_period = grace_period
        self.eviction_timeout = eviction_timeout
        self.http = http
        #: with_restarts puts restart actions IN the schedule;
        #: enable_restarts=False executes them as noops — a control run
        #: keeps the identical schedule while skipping the restarts
        self.with_restarts = with_restarts
        self.enable_restarts = enable_restarts
        self.clock = FakeClock()
        self.metrics = RobustnessMetrics()
        self.injector = FaultInjector(
            seed=seed, error_rate=error_rate, metrics=self.metrics,
            reset_rate=reset_rate, latency_rate=latency_rate,
            latency_max=latency_max, watch_drop_rate=watch_drop_rate)
        self._base_error_rate = error_rate
        store = Store(wal_path=wal_path)
        #: the control plane's (faulted) client vs the harness's own
        #: admin view of the same store — workload creation and virtual
        #: kubelet writes stay fault-free so the run's INPUT is stable
        #: and only the control plane's handling of faults is under test
        self.admin = Client(store)
        self._server = None
        if http:
            # wire mode: a real hub over the store; the control plane's
            # client speaks actual HTTP through the injector's wire hook
            from ..apiserver.server import APIServer
            from ..apiserver.httpclient import HTTPClient
            self._server = APIServer(store=store).start()
            self.client = ChaosHTTPClient(
                self.injector,
                HTTPClient(self._server.address,
                           wire_hook=self.injector.make_wire_hook()))
        else:
            self.client = ChaosClient(self.injector, store=store)
        #: controllers' factory; the scheduler runs its OWN factory so a
        #: scheduler crash can take its informers down with it
        self.factory = SharedInformerFactory(self.client)
        self._sched_factory = SharedInformerFactory(self.client)
        self.scheduler = self._build_scheduler(self._sched_factory)
        self._build_controllers(self.factory)
        self._gang_counter = 0
        self._pod_counter = 0
        self._started = False

    def _build_scheduler(self, factory: SharedInformerFactory) -> Scheduler:
        # async_bind=False: the driver steps everything synchronously —
        # a binder thread would commit binds at wall-clock-dependent
        # times and break the identical-event-log contract in wire mode
        return Scheduler(self.client, informer_factory=factory,
                         batch_size=64, clock=self.clock,
                         async_bind=False)

    def _build_controllers(self, factory: SharedInformerFactory) -> None:
        self.nodelifecycle = NodeLifecycleController(
            self.client, factory, grace_period=self.grace_period,
            eviction_timeout=self.eviction_timeout, clock=self.clock,
            metrics=self.metrics)
        self.podgroups = PodGroupController(
            self.client, factory, metrics=self.metrics,
            clock=self.clock)
        self.podgc = PodGCController(self.client, factory,
                                     clock=self.clock)

    def _factories(self) -> List[SharedInformerFactory]:
        return [self.factory, self._sched_factory]

    # ------------------------------------------------------------- setup

    def _slice_of(self, i: int) -> str:
        return f"s{i // self.nodes_per_slice}"

    def start(self) -> None:
        if self._started:
            return
        for i in range(self.n_nodes):
            self._register_node(i)
        for fac in self._factories():
            fac.start()
            fac.wait_for_cache_sync()
        self._settle()
        self._started = True

    def _register_node(self, i: int) -> None:
        alloc = {"cpu": Quantity("4"), "memory": Quantity("32Gi"),
                 "pods": Quantity("110")}
        node = Node(metadata=ObjectMeta(
            name=f"node-{i}", labels={SLICE_LABEL: self._slice_of(i)}))
        node.status.capacity = dict(alloc)
        node.status.allocatable = dict(alloc)
        node.status.conditions = [NodeCondition(
            type="Ready", status="True", reason="KubeletReady",
            last_heartbeat_time=now_iso(self.clock))]
        self.admin.nodes().create(node)

    def close(self) -> None:
        for fac in self._factories():
            fac.stop()
        if self._server is not None:
            self._server.stop()
        self.admin.store.close()

    # ---------------------------------------------------------- restarts

    def restart_scheduler(self) -> None:
        """Crash-replace the scheduler: its informers stop, and its
        cache, in-flight assumed pods, and gang permit-gate reservations
        die with the process. The replacement rebuilds every bit of that
        from a fresh informer sync — unbound members requeue, gangs
        re-reserve — which is exactly the recovery under test."""
        self.injector.record("restart_scheduler")
        self._sched_factory.stop()
        self.scheduler.crash()
        self._sched_factory = SharedInformerFactory(self.client)
        self.scheduler = self._build_scheduler(self._sched_factory)
        self._sched_factory.start()
        self._sched_factory.wait_for_cache_sync()
        self._settle()

    def restart_controller_manager(self) -> None:
        """Crash-replace the controller manager's loops (nodelifecycle,
        podgroup, podgc) and their shared informers. Controller-side soft
        state — eviction timers, resubmission rate limits — is lost and
        re-derived from observations, so recovery may converge LATER but
        must still converge."""
        self.injector.record("restart_controllers")
        self.factory.stop()
        self.factory = SharedInformerFactory(self.client)
        self._build_controllers(self.factory)
        self.factory.start()
        self.factory.wait_for_cache_sync()
        self._settle()

    def restart_store(self) -> None:
        """WAL-replay the store in place mid-run (the etcd/apiserver
        restart analog). Every live watch stream is severed; informers
        must resume or relist against the replayed state. No-op without
        a wal_path — a journal-less restart would be data loss, which is
        a different (unrecoverable) fault class."""
        if self.wal_path is None:
            return
        self.injector.record("restart_store")
        self.admin.store.restart()
        self._settle()

    # ---------------------------------------------------------- schedule

    def make_schedule(self, n_events: int) -> List[dict]:
        """The run's chaos script: a pure function of (seed, n_events).
        Every parameter an action needs is drawn here, so applying the
        schedule consumes no randomness — cluster state can influence
        WHAT an action amounts to (killing an already-dead node is a
        no-op) but never the script itself."""
        # string seeding is process-stable (sha512), tuple seeding is not
        rng = random.Random(f"chaos-schedule:{self.seed}")
        table = _ACTIONS + _RESTART_ACTIONS if self.with_restarts \
            else _ACTIONS
        names = [a for a, _ in table]
        weights = [w for _, w in table]
        out = []
        for _ in range(n_events):
            action = rng.choices(names, weights=weights)[0]
            ev = {"action": action,
                  "node": rng.randrange(self.n_nodes),
                  "size": rng.randint(2, self.nodes_per_slice),
                  "cpu_m": rng.choice((250, 500, 750, 1000))}
            out.append(ev)
        return out

    # -------------------------------------------------------------- run

    def run(self, n_events: int = 100, quiesce_steps: int = 30
            ) -> ChaosReport:
        self.start()
        report = ChaosReport(seed=self.seed, steps=n_events)
        for step, ev in enumerate(self.make_schedule(n_events)):
            self.injector.advance(step)
            self._apply(ev, report)
            self._tick()
        # quiesce: faults stop, dead nodes STAY dead — eviction timeouts,
        # permit rollbacks, and resubmissions must now converge on their
        # own; the invariants are checked against this settled state
        self.injector.error_rate = 0.0
        if self.injector.partitioned:
            self.injector.partition(False)
        for step in range(n_events, n_events + quiesce_steps):
            self.injector.advance(step)
            self._tick()
        # final housekeeping pass: the last tick's PodGroup syncs may have
        # orphaned permit reservations (resubmission deleting a waiting
        # member); one more scheduling cycle drains them before the sweep
        self.scheduler.schedule_pending(timeout=0)
        self.scheduler.cache.cleanup_expired_assumed_pods()
        self._settle()
        checker = InvariantChecker(self.admin, scheduler=self.scheduler,
                                   wal_path=self.wal_path)
        report.violations = checker.check()
        report.events = list(self.injector.events)
        report.pods_bound = sum(
            1 for p in self.admin.pods().list(namespace=None)
            if p.spec.node_name)
        report.resubmissions = sum(
            pg.status.resubmissions
            for pg in self.admin.pod_groups().list(namespace=None))
        report.store_state = self.store_state()
        return report

    def store_state(self) -> List[Tuple]:
        """The run's semantic end state: which objects exist, each pod's
        phase and whether it is bound — NOT which node (fault-driven
        retries may legitimately land a pod elsewhere) and NOT rvs. The
        surface on which a faulted run is compared to a fault-free run
        of the same schedule."""
        out: List[Tuple] = []
        for n in self.admin.nodes().list():
            out.append(("nodes", "", n.metadata.name, "", False))
        for p in self.admin.pods().list(namespace=None):
            out.append(("pods", p.metadata.namespace, p.metadata.name,
                        p.status.phase or "", bool(p.spec.node_name)))
        for pg in self.admin.pod_groups().list(namespace=None):
            out.append(("podgroups", pg.metadata.namespace,
                        pg.metadata.name, pg.status.phase or "", False))
        return sorted(out)

    def _apply(self, ev: dict, report: ChaosReport) -> None:
        action = ev["action"]
        node = f"node-{ev['node']}"
        if action == "create_gang":
            self._create_gang(ev["size"], ev["cpu_m"])
            report.gangs_created += 1
        elif action == "create_singleton":
            self._create_pod(self._next_pod_name("solo"), ev["cpu_m"])
        elif action == "kill_node":
            if self._node_exists(node) and self.injector.node_alive(node):
                self.injector.kill_node(node)
                report.nodes_killed += 1
        elif action == "restart_node":
            if self._node_exists(node):
                self.injector.restart_node(node)
        elif action == "drop_heartbeat":
            if self._node_exists(node) and self.injector.node_alive(node):
                self.injector.suppress_heartbeat(node)
        elif action == "resume_heartbeat":
            self.injector.resume_heartbeat(node)
        elif action == "delete_node":
            if self._node_exists(node):
                self.injector.kill_node(node)
                try:
                    self.admin.nodes().delete(node)
                except NotFoundError:
                    pass
                self.injector.record("delete_node", node)
                report.nodes_deleted += 1
        elif action == "partition":
            if not self.injector.partitioned:
                self.injector.partition(True)
        elif action == "heal":
            if self.injector.partitioned:
                self.injector.partition(False)
        elif action == "restart_scheduler":
            if self.enable_restarts:
                self.restart_scheduler()
                report.scheduler_restarts += 1
        elif action == "restart_controllers":
            if self.enable_restarts:
                self.restart_controller_manager()
                report.controller_restarts += 1
        elif action == "restart_store":
            if self.enable_restarts and self.wal_path is not None:
                self.restart_store()
                report.store_restarts += 1

    def _node_exists(self, name: str) -> bool:
        try:
            self.admin.nodes().get(name)
            return True
        except NotFoundError:
            return False

    def _next_pod_name(self, prefix: str) -> str:
        self._pod_counter += 1
        return f"{prefix}-{self._pod_counter}"

    def _create_gang(self, size: int, cpu_m: int) -> None:
        self._gang_counter += 1
        gname = f"gang-{self._gang_counter}"
        self.admin.pod_groups("default").create(PodGroup(
            metadata=ObjectMeta(name=gname, namespace="default"),
            spec=PodGroupSpec(min_member=size, topology_key=SLICE_LABEL,
                              schedule_timeout_seconds=self.gang_timeout)))
        for i in range(size):
            self._create_pod(f"{gname}-w{i}", cpu_m, group=gname)
        self.injector.record("create_gang", gname, size)

    def _create_pod(self, name: str, cpu_m: int,
                    group: Optional[str] = None) -> None:
        from ..api.core import (Container, PodSpec, ResourceRequirements)
        labels = {}
        if group is not None:
            from ..api.wellknown import LABEL_POD_GROUP
            labels[LABEL_POD_GROUP] = group
        pod = Pod(
            metadata=ObjectMeta(name=name, namespace="default",
                                labels=labels),
            spec=PodSpec(containers=[Container(
                name="c", image="img",
                resources=ResourceRequirements(
                    requests={"cpu": Quantity(f"{cpu_m}m"),
                              "memory": Quantity("256Mi")}))]))
        self.admin.pods("default").create(pod)

    # -------------------------------------------------------------- tick

    def _tick(self) -> None:
        """One control-plane step: virtual kubelets beat and report, each
        control loop runs once, virtual time advances, informers settle."""
        self._virtual_kubelets()
        self._settle()
        try:
            self.nodelifecycle.monitor_once()
        except Exception:
            pass  # a partitioned monitor pass retries next tick
        try:
            self.podgc.gc_once()
        except Exception:
            pass
        self._settle()
        try:
            self.scheduler.schedule_pending(timeout=0)
        except Exception:
            pass
        self.scheduler.cache.cleanup_expired_assumed_pods()
        self._settle()
        for pg in self.admin.pod_groups().list(namespace=None):
            try:
                self.podgroups.sync(pg.metadata.key())
            except Exception:
                pass  # chaos mid-resubmit: the next tick re-syncs
            self._settle()
        self.clock.step(self.clock_step)

    def _virtual_kubelets(self) -> None:
        """The hollow node fleet: every live node heartbeats (unless the
        injector silenced it) and reports its non-terminal bound pods
        Running — through the ADMIN client, so kubelet-side writes are
        not part of the injected fault surface."""
        nodes = sorted(n.metadata.name for n in self.admin.nodes().list())
        alive = {n for n in nodes if self.injector.node_alive(n)}
        for name in nodes:
            if not self.injector.allow_heartbeat(name):
                continue

            def beat(cur):
                for cond in cur.status.conditions:
                    if cond.type == "Ready":
                        cond.status = "True"
                        cond.reason = "KubeletReady"
                        cond.last_heartbeat_time = now_iso(self.clock)
                        return cur
                cur.status.conditions.append(NodeCondition(
                    type="Ready", status="True", reason="KubeletReady",
                    last_heartbeat_time=now_iso(self.clock)))
                return cur
            try:
                self.admin.nodes().patch(name, beat)
            except NotFoundError:
                pass
        for pod in self.admin.pods().list(namespace=None):
            if not pod.spec.node_name or pod.spec.node_name not in alive:
                continue
            if pod.status.phase in ("Running", "Succeeded", "Failed"):
                continue

            def run_status(cur):
                if cur.status.phase in ("Succeeded", "Failed"):
                    return cur  # never resurrect a terminal pod
                cur.status.phase = "Running"
                return cur
            try:
                self.admin.pods(pod.metadata.namespace).patch(
                    pod.metadata.name, run_status)
            except NotFoundError:
                pass

    # ------------------------------------------------------------ settle

    def _settle(self, timeout: float = 10.0) -> None:
        """The shared settling contract (see settle_informers) over the
        chaos harness's resource classes — control-loop inputs must be
        identical across runs so the fault oracle sees identical call
        streams."""
        from ..api.core import Node as NodeCls, Pod as PodCls
        settle_informers(self.admin, self._factories(),
                         (PodCls, NodeCls, PodGroup), self.injector,
                         timeout=timeout, logger_name="chaos",
                         step=self.injector.step)
