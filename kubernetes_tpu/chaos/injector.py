"""FaultInjector + ChaosClient — seeded, reproducible fault injection.

The injector is the single fault oracle for a chaos run. Determinism
contract: every decision is a pure function of `(seed, step, call
signature, attempt)` — NOT of wall clock, thread timing, or call count
across signatures — so two runs that issue the same calls at the same
steps inject the same faults and produce identical event logs. Hashing
uses sha1, not `hash()` (which is salted per process).

`ChaosClient` is a drop-in `state.client.Client`: reads pass straight
through (informers stay healthy — a watch outage is a different fault
class, modeled as a partition of WRITES), while every mutating verb
consults the injector first and raises `ChaosError` when the oracle says
so. Components under test see the same exception surface a flaky
apiserver would give them.

WIRE faults extend the same contract to the real HTTP transport
(`apiserver/httpclient.py`'s injectable wire hook):

  - request latency (`latency_rate`): a deterministic pre-send sleep;
  - connection resets (`reset_rate`): the request dies with
    `ChaosResetError` before any byte leaves the process;
  - watch drops (`watch_drop_rate`): a watch stream is severed after a
    deterministic number of events — keyed by the stream's per-resource
    CONNECTION index, not the step, because reconnects happen on
    informer threads whose timing the driver does not control. The
    per-resource drop plans (`wire_watch_plans`) are therefore a pure
    function of the seed and are comparable across runs even though
    their wall-clock interleaving is not.

Read-path wire faults (GET/WATCH) are deliberately kept out of the
step-ordered event log: they fire on informer threads at nondeterministic
times, and logging them would break the identical-event-log contract.
They are still deterministic per signature and counted in metrics.

`ChaosHTTPClient` mirrors ChaosClient over an `HTTPClient`: mutating
verbs consult the injector (API-error faults) while the wire hook below
them injects transport faults — both fault surfaces on the real wire.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..state.client import Client

#: ResourceClient/PodClient verbs that mutate cluster state; reads and
#: watches bypass injection (see module docstring)
MUTATING_VERBS = frozenset({
    "create", "create_bulk", "update", "update_status", "patch",
    "merge_patch", "delete", "evict", "bind", "bind_bulk",
    "bind_bulk_pairs", "update_scale"})


class ChaosError(Exception):
    """An injected API failure (transient-server-error analog). Callers
    are expected to treat it like any other transient store error —
    retry with backoff or requeue."""


class ChaosResetError(ConnectionResetError):
    """An injected wire-level connection reset: the request never reached
    the server. Transport-shaped (ConnectionResetError) so callers'
    generic retry machinery treats it exactly like a real RST."""


class FaultInjector:
    """Seeded fault oracle + chaos event log.

    The harness calls `advance(step)` once per scheduled event, then
    applies node-level actions (`kill_node`, `suppress_heartbeat`, ...);
    the ChaosClient calls `before(op, resource, name)` on every mutating
    API verb. Each (step, signature) retries independently: attempt 0
    may fail while attempt 1 succeeds, so backoff-retried writes make
    progress even at high error rates.
    """

    def __init__(self, seed: int = 0, error_rate: float = 0.0,
                 metrics=None, reset_rate: float = 0.0,
                 latency_rate: float = 0.0, latency_max: float = 0.02,
                 watch_drop_rate: float = 0.0,
                 watch_drop_horizon: int = 12):
        self.seed = seed
        self.error_rate = error_rate
        #: wire fault classes (see module docstring)
        self.reset_rate = reset_rate
        self.latency_rate = latency_rate
        self.latency_max = latency_max
        self.watch_drop_rate = watch_drop_rate
        self.watch_drop_horizon = max(1, watch_drop_horizon)
        self.metrics = metrics
        self.step = 0
        self.partitioned = False
        #: while True, every mutating verb against Lease objects fails —
        #: the renew-deadline fencing fault (a holder that cannot renew
        #: must stop leading BEFORE the lease expires for a standby)
        self.lease_suppressed = False
        self._lock = threading.Lock()
        #: resource -> number of watch streams opened (the per-resource
        #: connection index that keys drop decisions)
        self._watch_conns: Dict[str, int] = {}
        #: resource -> the drop plan of each connection in open order
        #: (None = stream lives; K = severed after K events) — a pure
        #: function of (seed, resource, connection index), comparable
        #: across runs
        self.wire_watch_plans: Dict[str, List[Optional[int]]] = {}
        #: nodes whose "kubelet process" is down (no heartbeats; cleared
        #: by restart_node)
        self._down: set = set()
        #: nodes with heartbeats suppressed but the process alive (a
        #: network blip, not a crash)
        self._muted: set = set()
        #: (step, op, resource, name) -> attempts seen this step
        self._attempts: Dict[Tuple, int] = {}
        #: the run's event log: (step, kind, *detail) tuples, identical
        #: across runs with the same (seed, schedule)
        self.events: List[Tuple] = []
        #: kind -> total faults fired (the metrics counter's plain-dict
        #: twin, so reports can quote counts without a registry scrape;
        #: stream-tagged kinds like wire_reset_replication prove the
        #: replication stream itself took faults)
        self.fault_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ driver

    def advance(self, step: int) -> None:
        with self._lock:
            self.step = step
            self._attempts.clear()

    def record(self, kind: str, *detail) -> None:
        with self._lock:
            self.events.append((self.step, kind) + tuple(detail))

    # ------------------------------------------------------- node faults

    def kill_node(self, name: str) -> None:
        """Crash the node's virtual kubelet: heartbeats stop until
        restart_node. The Node object stays — the control plane must
        notice via staleness, exactly like a real dead host."""
        with self._lock:
            self._down.add(name)
        self._count("kill_node")
        self.record("kill_node", name)

    def restart_node(self, name: str) -> None:
        with self._lock:
            self._down.discard(name)
            self._muted.discard(name)
        self.record("restart_node", name)

    def suppress_heartbeat(self, name: str) -> None:
        with self._lock:
            self._muted.add(name)
        self._count("suppress_heartbeat")
        self.record("suppress_heartbeat", name)

    def resume_heartbeat(self, name: str) -> None:
        with self._lock:
            self._muted.discard(name)
        self.record("resume_heartbeat", name)

    def partition(self, on: bool = True) -> None:
        """Partition the apiserver for WRITES: every mutating verb fails
        until healed."""
        self.partitioned = on
        if on:
            self._count("partition")
        self.record("partition" if on else "heal")

    def suppress_lease(self, on: bool = True) -> None:
        """Fail every Lease write until resumed — a partition scoped to
        the election lock. The current holder misses renewals, fences
        itself at renew_deadline, and (once resumed) a standby acquires
        after lease expiry: the failover path without killing anyone."""
        self.lease_suppressed = on
        if on:
            self._count("suppress_lease")
        self.record("suppress_lease" if on else "resume_lease")

    def tear_wal(self, n: int) -> None:
        """Record + count a torn-tail fault: the harness chops the last
        `n` journal records (state/wal.tear_wal) before a store restart.
        The surgery itself is the harness's — it owns the wal_path."""
        self._count("tear_wal")
        self.record("tear_wal", n)

    def node_alive(self, name: str) -> bool:
        with self._lock:
            return name not in self._down

    def allow_heartbeat(self, name: str) -> bool:
        with self._lock:
            return name not in self._down and name not in self._muted

    def down_nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._down)

    # --------------------------------------------------------- API layer

    def before(self, op: str, resource: str, name: str) -> None:
        """Consulted by ChaosClient ahead of every mutating verb; raises
        ChaosError when this (step, signature, attempt) draws a fault."""
        if self.partitioned:
            self.record("api_partition_drop", op, resource, name)
            self._count("api_error")
            raise ChaosError(
                f"injected partition: {op} {resource}/{name}")
        if self.lease_suppressed and resource == "leases":
            self.record("lease_write_drop", op, name)
            self._count("api_error")
            raise ChaosError(
                f"injected lease suppression: {op} {resource}/{name}")
        if self.error_rate <= 0.0:
            return
        with self._lock:
            sig = (self.step, op, resource, name)
            attempt = self._attempts.get(sig, 0)
            self._attempts[sig] = attempt + 1
        digest = hashlib.sha1(
            f"{self.seed}:{self.step}:{op}:{resource}:{name}:{attempt}"
            .encode()).digest()
        draw = int.from_bytes(digest[:8], "big") / float(1 << 64)
        if draw < self.error_rate:
            self.record("api_error", op, resource, name, attempt)
            self._count("api_error")
            raise ChaosError(
                f"injected API error: {op} {resource}/{name} "
                f"(attempt {attempt})")

    def _count(self, kind: str) -> None:
        with self._lock:
            self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
        if self.metrics is not None:
            self.metrics.faults_injected.inc(kind=kind)

    # -------------------------------------------------------- wire layer

    def _draw(self, *sig) -> float:
        """One uniform [0,1) draw, a pure function of (seed, *sig)."""
        digest = hashlib.sha1(
            ":".join(str(s) for s in (self.seed,) + sig).encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64)

    def _wire_attempt(self, method: str, resource: str, path: str) -> int:
        with self._lock:
            sig = (self.step, "wire", method, resource, path)
            attempt = self._attempts.get(sig, 0)
            self._attempts[sig] = attempt + 1
            return attempt

    def wire_request(self, method: str, resource: str, path: str) -> None:
        """Transport faults for one HTTP request: an independent reset
        draw and latency draw per (step, signature, attempt). Mutating
        requests come off the driver thread and are recorded in the
        step-ordered event log; reads (GET) fire from informer threads
        and are counted in metrics only (see module docstring)."""
        if self.reset_rate <= 0.0 and self.latency_rate <= 0.0:
            return
        mutating = method not in ("GET", "WATCH")
        attempt = self._wire_attempt(method, resource, path)
        if self.latency_rate > 0.0:
            d = self._draw(self.step, "latency", method, resource, path,
                           attempt)
            if d < self.latency_rate:
                # the draw's sub-rate position scales the delay, so one
                # signature yields both the decision and the magnitude
                delay = (d / self.latency_rate) * self.latency_max
                self._count("wire_latency")
                if mutating:
                    self.record("wire_latency", method, resource, path,
                                attempt)
                time.sleep(delay)
        if self.reset_rate > 0.0:
            d = self._draw(self.step, "reset", method, resource, path,
                           attempt)
            if d < self.reset_rate:
                self._count("wire_reset")
                if mutating:
                    self.record("wire_reset", method, resource, path,
                                attempt)
                raise ChaosResetError(
                    f"injected connection reset: {method} {path} "
                    f"(attempt {attempt})")

    def watch_plan(self, resource: str) -> Optional[int]:
        """Drop decision for the next watch stream of `resource`: None to
        let it live, or the number of events after which the transport
        severs it. Keyed by the per-resource connection index so the plan
        sequence is a pure function of the seed regardless of WHEN (on
        which informer-thread schedule) each reconnect happens."""
        with self._lock:
            conn = self._watch_conns.get(resource, 0)
            self._watch_conns[resource] = conn + 1
        plan: Optional[int] = None
        if self.watch_drop_rate > 0.0:
            d = self._draw("watchdrop", resource, conn)
            if d < self.watch_drop_rate:
                plan = int(self._draw("watchdrop-k", resource, conn)
                           * self.watch_drop_horizon)
                self._count("watch_drop")
        with self._lock:
            self.wire_watch_plans.setdefault(resource, []).append(plan)
        return plan

    def make_wire_hook(self, stream: Optional[str] = None):
        """The `HTTPClient(wire_hook=...)` adapter: one callable serving
        both hook kinds (request faults; watch-stream drop budgets).
        `stream` tags this client's faults with an extra per-stream count
        (wire_reset_<stream> / wire_drop_<stream>) so a dedicated client
        — the replication follower's — can PROVE its own stream took
        faults, separate from the control plane's totals. The tag never
        touches the draw signatures, so flag-off runs stay byte-identical."""
        def hook(kind: str, op: str, resource: str, path: str):
            if kind == "watch":
                try:
                    self.wire_request("WATCH", resource, path)
                except ChaosResetError:
                    if stream is not None:
                        self._count(f"wire_reset_{stream}")
                    raise
                plan = self.watch_plan(resource)
                if stream is not None and plan is not None:
                    self._count(f"wire_drop_{stream}")
                return plan
            try:
                self.wire_request(op, resource, path)
            except ChaosResetError:
                if stream is not None:
                    self._count(f"wire_reset_{stream}")
                raise
            return None
        return hook


def _target_name(args, kwargs) -> str:
    """Best-effort object name from a verb's arguments (for the fault
    signature; collisions only blur per-object independence)."""
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, str):
            return v
        meta = getattr(v, "metadata", None)
        if meta is not None:
            return meta.name or meta.generate_name or ""
        if isinstance(v, (list, tuple)) and v:
            return f"bulk[{len(v)}]"
    return ""


class _FaultyResourceClient:
    """Proxy over a ResourceClient/PodClient: mutating verbs consult the
    injector first; everything else (reads, watch, attributes) passes
    through untouched."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    @property
    def _SLIM_WATCH(self):
        """Slim-frame negotiation is a TRANSPORT concern: forward it to
        the inner client so informers over this proxy negotiate exactly
        as they would against the bare transport (the chaos wire soak
        must exercise the production slim-bind path)."""
        return getattr(self._inner, "_SLIM_WATCH", None)

    @_SLIM_WATCH.setter
    def _SLIM_WATCH(self, value):
        self._inner._SLIM_WATCH = value

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in MUTATING_VERBS or not callable(attr):
            return attr
        injector = self._injector
        resource = self._inner._resource

        def wrapped(*args, **kwargs):
            injector.before(name, resource, _target_name(args, kwargs))
            return attr(*args, **kwargs)
        wrapped.__name__ = name
        return wrapped


class ChaosClient(Client):
    """A Client whose resource accessors hand out fault-wrapped views.

    Components built on this client (scheduler, controllers, virtual
    kubelets) experience the injector's API faults on every write while
    their informers keep watching the store directly — the fault surface
    of a flaky apiserver, not a corrupted one.
    """

    def __init__(self, injector: FaultInjector, store=None, **kwargs):
        super().__init__(store=store, **kwargs)
        self.injector = injector

    def resource(self, cls, namespace=None):
        return _FaultyResourceClient(
            super().resource(cls, namespace), self.injector)


class ChaosHTTPClient:
    """ChaosClient's shape over the REAL wire: wraps an HTTPClient whose
    transport already carries the injector's wire hook (latency, resets,
    watch drops), and layers the same mutating-verb API-error oracle on
    top. Components handed this client experience BOTH fault surfaces on
    an actual HTTP connection to a live hub."""

    def __init__(self, injector: FaultInjector, http):
        self._inner = http
        self.injector = injector
        self.scheme = http.scheme
        self.base_url = http.base_url

    def resource(self, cls, namespace=None):
        return _FaultyResourceClient(
            self._inner.resource(cls, namespace), self.injector)

    def __getattr__(self, name):
        """Accessor delegation (pods(), nodes(), ...) through Client's
        resource table, same shim trick as HTTPClient."""
        template = getattr(Client, name, None)
        if template is None or not callable(template):
            raise AttributeError(name)

        def accessor(*args, **kwargs):
            return template(self, *args, **kwargs)
        return accessor
