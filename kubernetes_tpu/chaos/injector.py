"""FaultInjector + ChaosClient — seeded, reproducible fault injection.

The injector is the single fault oracle for a chaos run. Determinism
contract: every decision is a pure function of `(seed, step, call
signature, attempt)` — NOT of wall clock, thread timing, or call count
across signatures — so two runs that issue the same calls at the same
steps inject the same faults and produce identical event logs. Hashing
uses sha1, not `hash()` (which is salted per process).

`ChaosClient` is a drop-in `state.client.Client`: reads pass straight
through (informers stay healthy — a watch outage is a different fault
class, modeled as a partition of WRITES), while every mutating verb
consults the injector first and raises `ChaosError` when the oracle says
so. Components under test see the same exception surface a flaky
apiserver would give them.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..state.client import Client

#: ResourceClient/PodClient verbs that mutate cluster state; reads and
#: watches bypass injection (see module docstring)
MUTATING_VERBS = frozenset({
    "create", "create_bulk", "update", "update_status", "patch",
    "merge_patch", "delete", "evict", "bind", "bind_bulk",
    "bind_bulk_pairs", "update_scale"})


class ChaosError(Exception):
    """An injected API failure (transient-server-error analog). Callers
    are expected to treat it like any other transient store error —
    retry with backoff or requeue."""


class FaultInjector:
    """Seeded fault oracle + chaos event log.

    The harness calls `advance(step)` once per scheduled event, then
    applies node-level actions (`kill_node`, `suppress_heartbeat`, ...);
    the ChaosClient calls `before(op, resource, name)` on every mutating
    API verb. Each (step, signature) retries independently: attempt 0
    may fail while attempt 1 succeeds, so backoff-retried writes make
    progress even at high error rates.
    """

    def __init__(self, seed: int = 0, error_rate: float = 0.0,
                 metrics=None):
        self.seed = seed
        self.error_rate = error_rate
        self.metrics = metrics
        self.step = 0
        self.partitioned = False
        self._lock = threading.Lock()
        #: nodes whose "kubelet process" is down (no heartbeats; cleared
        #: by restart_node)
        self._down: set = set()
        #: nodes with heartbeats suppressed but the process alive (a
        #: network blip, not a crash)
        self._muted: set = set()
        #: (step, op, resource, name) -> attempts seen this step
        self._attempts: Dict[Tuple, int] = {}
        #: the run's event log: (step, kind, *detail) tuples, identical
        #: across runs with the same (seed, schedule)
        self.events: List[Tuple] = []

    # ------------------------------------------------------------ driver

    def advance(self, step: int) -> None:
        with self._lock:
            self.step = step
            self._attempts.clear()

    def record(self, kind: str, *detail) -> None:
        with self._lock:
            self.events.append((self.step, kind) + tuple(detail))

    # ------------------------------------------------------- node faults

    def kill_node(self, name: str) -> None:
        """Crash the node's virtual kubelet: heartbeats stop until
        restart_node. The Node object stays — the control plane must
        notice via staleness, exactly like a real dead host."""
        with self._lock:
            self._down.add(name)
        self._count("kill_node")
        self.record("kill_node", name)

    def restart_node(self, name: str) -> None:
        with self._lock:
            self._down.discard(name)
            self._muted.discard(name)
        self.record("restart_node", name)

    def suppress_heartbeat(self, name: str) -> None:
        with self._lock:
            self._muted.add(name)
        self._count("suppress_heartbeat")
        self.record("suppress_heartbeat", name)

    def resume_heartbeat(self, name: str) -> None:
        with self._lock:
            self._muted.discard(name)
        self.record("resume_heartbeat", name)

    def partition(self, on: bool = True) -> None:
        """Partition the apiserver for WRITES: every mutating verb fails
        until healed."""
        self.partitioned = on
        if on:
            self._count("partition")
        self.record("partition" if on else "heal")

    def node_alive(self, name: str) -> bool:
        with self._lock:
            return name not in self._down

    def allow_heartbeat(self, name: str) -> bool:
        with self._lock:
            return name not in self._down and name not in self._muted

    def down_nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._down)

    # --------------------------------------------------------- API layer

    def before(self, op: str, resource: str, name: str) -> None:
        """Consulted by ChaosClient ahead of every mutating verb; raises
        ChaosError when this (step, signature, attempt) draws a fault."""
        if self.partitioned:
            self.record("api_partition_drop", op, resource, name)
            self._count("api_error")
            raise ChaosError(
                f"injected partition: {op} {resource}/{name}")
        if self.error_rate <= 0.0:
            return
        with self._lock:
            sig = (self.step, op, resource, name)
            attempt = self._attempts.get(sig, 0)
            self._attempts[sig] = attempt + 1
        digest = hashlib.sha1(
            f"{self.seed}:{self.step}:{op}:{resource}:{name}:{attempt}"
            .encode()).digest()
        draw = int.from_bytes(digest[:8], "big") / float(1 << 64)
        if draw < self.error_rate:
            self.record("api_error", op, resource, name, attempt)
            self._count("api_error")
            raise ChaosError(
                f"injected API error: {op} {resource}/{name} "
                f"(attempt {attempt})")

    def _count(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.faults_injected.inc(kind=kind)


def _target_name(args, kwargs) -> str:
    """Best-effort object name from a verb's arguments (for the fault
    signature; collisions only blur per-object independence)."""
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, str):
            return v
        meta = getattr(v, "metadata", None)
        if meta is not None:
            return meta.name or meta.generate_name or ""
        if isinstance(v, (list, tuple)) and v:
            return f"bulk[{len(v)}]"
    return ""


class _FaultyResourceClient:
    """Proxy over a ResourceClient/PodClient: mutating verbs consult the
    injector first; everything else (reads, watch, attributes) passes
    through untouched."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in MUTATING_VERBS or not callable(attr):
            return attr
        injector = self._injector
        resource = self._inner._resource

        def wrapped(*args, **kwargs):
            injector.before(name, resource, _target_name(args, kwargs))
            return attr(*args, **kwargs)
        wrapped.__name__ = name
        return wrapped


class ChaosClient(Client):
    """A Client whose resource accessors hand out fault-wrapped views.

    Components built on this client (scheduler, controllers, virtual
    kubelets) experience the injector's API faults on every write while
    their informers keep watching the store directly — the fault surface
    of a flaky apiserver, not a corrupted one.
    """

    def __init__(self, injector: FaultInjector, store=None, **kwargs):
        super().__init__(store=store, **kwargs)
        self.injector = injector

    def resource(self, cls, namespace=None):
        return _FaultyResourceClient(
            super().resource(cls, namespace), self.injector)
