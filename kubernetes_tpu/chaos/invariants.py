"""InvariantChecker — what failure handling must never leave behind.

Swept after (and during) chaos runs over live cluster state:

  1. Gang atomicity: no PodGroup is PARTIALLY bound — its non-terminal
     members with a node number either zero or at least minMember. A
     2-of-4 slice is wedged capacity; the whole point of gang-aware
     failure propagation is that this state never survives quiescence.
  2. No scheduler-cache assume references a deleted node, and every
     assumed pod still exists in the store (an orphaned assume holds
     phantom capacity until the TTL fires — at quiescence there must be
     none).
  3. No permit-gate reservation sits on a deleted or NoExecute-dead
     node (GangManager.node_gone's contract).
  4. The WAL replays to exactly the live store: reconstructing
     {(resource, ns, name): rv} from the journal records matches
     Store.contents() — a crash at this instant would lose nothing.

Each violation is a human-readable string; an empty list is green.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import wellknown
from ..api.scheduling import pod_group_name

#: taints that mean "this node is dead to the scheduler" for invariant 3
_DEAD_TAINTS = (wellknown.TAINT_NODE_NOT_READY,
                wellknown.TAINT_NODE_UNREACHABLE)


def wal_digest(path: str) -> dict:
    """Reconstruct {(resource, namespace, name): rv} by replaying the
    journal records WITHOUT opening a second writer on the live file
    (Store(wal_path=...) would). Mirrors Store._replay_wal's effect on
    the key space: PUT upserts, DELETE drops, BIND restamps, META is
    clock-only."""
    from ..state.wal import load_wal
    records, _ = load_wal(path)
    state: dict = {}
    for rec in records:
        op = rec.get("op")
        if op == "META":
            continue
        resource = rec.get("resource", "")
        obj = rec.get("object") or {}
        if op == "BIND":
            key = (resource, obj.get("namespace", ""), obj.get("name", ""))
            if key in state:
                state[key] = rec["rv"]
            continue
        if op == "BINDS":
            # group-commit bind transaction: per-entry rv restamps
            for b in obj.get("binds", ()):
                key = (resource, b.get("namespace", ""), b.get("name", ""))
                if key in state:
                    state[key] = b["rv"]
            continue
        md = obj.get("metadata") or {}
        key = (resource, md.get("namespace", ""), md.get("name", ""))
        if op == "DELETE":
            state.pop(key, None)
        else:
            state[key] = rec["rv"]
    return state


class InvariantChecker:
    def __init__(self, client, scheduler=None,
                 wal_path: Optional[str] = None):
        self.client = client
        self.scheduler = scheduler
        self.wal_path = wal_path

    # ------------------------------------------------------------ sweeps

    def check(self) -> List[str]:
        out: List[str] = []
        out += self.check_gang_atomicity()
        if self.scheduler is not None:
            out += self.check_cache_assumes()
            out += self.check_gang_reservations()
        if self.wal_path is not None:
            out += self.check_wal_replay()
        return out

    def _live_nodes(self) -> dict:
        return {n.metadata.name: n for n in self.client.nodes().list()}

    def check_gang_atomicity(self) -> List[str]:
        out: List[str] = []
        pods = self.client.pods().list(namespace=None)
        for pg in self.client.pod_groups().list(namespace=None):
            ns, name = pg.metadata.namespace, pg.metadata.name
            members = [p for p in pods
                       if p.metadata.namespace == ns
                       and pod_group_name(p) == name]
            bound = [p for p in members
                     if p.spec.node_name
                     and p.status.phase not in ("Succeeded", "Failed")]
            mm = max(1, pg.spec.min_member)
            if 0 < len(bound) < mm:
                out.append(
                    f"gang-atomicity: PodGroup {ns}/{name} partially "
                    f"bound ({len(bound)}/{mm}): "
                    f"{sorted(p.metadata.name for p in bound)}")
        return out

    def check_cache_assumes(self) -> List[str]:
        out: List[str] = []
        nodes = self._live_nodes()
        from ..state.store import NotFoundError
        for pod in self.scheduler.cache.assumed_pods():
            key = pod.metadata.key()
            if pod.spec.node_name not in nodes:
                out.append(f"cache-assume: pod {key} assumed on deleted "
                           f"node {pod.spec.node_name}")
            try:
                self.client.pods(pod.metadata.namespace).get(
                    pod.metadata.name)
            except NotFoundError:
                out.append(f"cache-assume: pod {key} assumed but no "
                           f"longer exists in the store")
        return out

    def check_gang_reservations(self) -> List[str]:
        gang = getattr(self.scheduler, "gang", None)
        if gang is None:
            return []
        out: List[str] = []
        nodes = self._live_nodes()
        for gkey, pod_key, node_name in gang.reservations():
            node = nodes.get(node_name)
            if node is None:
                out.append(f"gang-reservation: {gkey} member {pod_key} "
                           f"reserved on deleted node {node_name}")
                continue
            dead = [t.key for t in node.spec.taints
                    if t.key in _DEAD_TAINTS and t.effect == "NoExecute"]
            if dead:
                out.append(f"gang-reservation: {gkey} member {pod_key} "
                           f"reserved on dead node {node_name} "
                           f"(taints: {dead})")
        return out

    def check_wal_replay(self) -> List[str]:
        store = self.client.store
        store.flush_wal()  # deferred records must be on disk first
        want = store.contents()
        got = wal_digest(self.wal_path)
        out: List[str] = []
        for key in sorted(set(want) | set(got)):
            if key not in got:
                out.append(f"wal-replay: live object {key} missing from "
                           f"the journal")
            elif key not in want:
                out.append(f"wal-replay: journal resurrects deleted "
                           f"object {key} at rv {got[key]}")
            elif want[key] != got[key]:
                out.append(f"wal-replay: {key} at rv {got[key]} in the "
                           f"journal vs {want[key]} live")
        return out
