"""InvariantChecker — what failure handling must never leave behind.

Swept after (and during) chaos runs over live cluster state:

  1. Gang atomicity: no PodGroup is PARTIALLY bound — its non-terminal
     members with a node number either zero or at least minMember. A
     2-of-4 slice is wedged capacity; the whole point of gang-aware
     failure propagation is that this state never survives quiescence.
  2. No scheduler-cache assume references a deleted node, and every
     assumed pod still exists in the store (an orphaned assume holds
     phantom capacity until the TTL fires — at quiescence there must be
     none).
  3. No permit-gate reservation sits on a deleted or NoExecute-dead
     node (GangManager.node_gone's contract).
  4. The WAL replays to exactly the live store: reconstructing
     {(resource, ns, name): rv} from the journal records matches
     Store.contents() — a crash at this instant would lose nothing.

Each violation is a human-readable string; an empty list is green.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import wellknown
from ..api.scheduling import pod_group_name

#: taints that mean "this node is dead to the scheduler" for invariant 3
_DEAD_TAINTS = (wellknown.TAINT_NODE_NOT_READY,
                wellknown.TAINT_NODE_UNREACHABLE)


def wal_digest(path: str) -> dict:
    """Reconstruct {(resource, namespace, name): rv} by replaying the
    journal records WITHOUT opening a second writer on the live file
    (Store(wal_path=...) would). Mirrors Store._replay_wal's effect on
    the key space: PUT upserts, DELETE drops, BIND restamps, META is
    clock-only."""
    from ..state.wal import load_wal
    records, _ = load_wal(path)
    state: dict = {}
    for rec in records:
        op = rec.get("op")
        if op == "META":
            continue
        resource = rec.get("resource", "")
        obj = rec.get("object") or {}
        if op == "BIND":
            key = (resource, obj.get("namespace", ""), obj.get("name", ""))
            if key in state:
                state[key] = rec["rv"]
            continue
        if op == "BINDS":
            # group-commit bind transaction: per-entry rv restamps
            for b in obj.get("binds", ()):
                key = (resource, b.get("namespace", ""), b.get("name", ""))
                if key in state:
                    state[key] = b["rv"]
            continue
        md = obj.get("metadata") or {}
        key = (resource, md.get("namespace", ""), md.get("name", ""))
        if op == "DELETE":
            state.pop(key, None)
        else:
            state[key] = rec["rv"]
    return state


def check_replication(primary, replica_store) -> List[str]:
    """The replication-horizon sweep: against a QUIESCED primary, the
    follower must hold every acknowledged record at the SAME rv — a key
    the primary has that the replica lacks is an acknowledged write below
    the replication horizon lost; a key only the replica has is a forked
    history; an rv mismatch is a stale or reordered apply. Call only
    after a catch-up barrier (ChaosHarness._replica_barrier) — mid-stream
    the follower legitimately trails."""
    want = primary.contents()
    got = replica_store.contents()
    out: List[str] = []
    for key in sorted(set(want) | set(got)):
        if key not in got:
            out.append(
                f"replication: acknowledged write {key}@{want[key]} "
                f"missing at the replica")
        elif key not in want:
            out.append(
                f"replication: replica forked — holds {key}@{got[key]} "
                f"which the primary never acknowledged")
        elif want[key] != got[key]:
            out.append(
                f"replication: {key} at rv {got[key]} on the replica "
                f"vs {want[key]} on the primary")
    return out


class InvariantChecker:
    def __init__(self, client, scheduler=None,
                 wal_path: Optional[str] = None,
                 factories=None, informer_classes=None):
        self.client = client
        self.scheduler = scheduler
        self.wal_path = wal_path
        #: SharedInformerFactory list + resource classes for the
        #: post-settle convergence sweep (check_convergence) — the
        #: torn-WAL recovery contract: after a regressed restart settles,
        #: store == informer caches == scheduler cache and no pod is
        #: invisible to the scheduler
        self.factories = list(factories) if factories is not None else []
        self.informer_classes = tuple(informer_classes or ())

    # ------------------------------------------------------------ sweeps

    def check(self) -> List[str]:
        out: List[str] = []
        out += self.check_gang_atomicity()
        if self.scheduler is not None:
            out += self.check_cache_assumes()
            out += self.check_gang_reservations()
        if self.factories:
            out += self.check_convergence()
        if self.wal_path is not None:
            out += self.check_wal_replay()
        return out

    def check_convergence(self) -> List[str]:
        """The recovery convergence sweep: after quiescence, every layer
        of derived state agrees with the store.

          a. Informer caches mirror the store exactly — no ghost object a
             relist should have pruned, no missing object, no stale rv.
          b. The scheduler cache charges exactly the store's bound,
             non-terminal pods (same node); phantom capacity from a
             regressed bind must be gone.
          c. No pod is INVISIBLE to the scheduler: every non-terminal,
             unbound, undeleted pod it is responsible for sits in its
             queue (active, backoff, unschedulable, or gang-parked) or is
             assumed mid-bind — a pod in neither place would be stuck
             Pending forever with nothing ever retrying it.
        """
        out: List[str] = []
        store = self.client.store
        scheme = self.client.scheme
        for fac in self.factories:
            with fac._lock:
                informers = dict(fac._informers)
            for cls in self.informer_classes:
                inf = informers.get(cls)
                if inf is None:
                    continue  # this component never watched the class
                resource = scheme.resource_for(cls)
                items, _ = store.list(resource)
                want = {o.metadata.key(): o.metadata.resource_version
                        for o in items}
                have = {o.metadata.key(): o.metadata.resource_version
                        for o in inf.indexer.list()}
                for key in sorted(set(want) | set(have)):
                    if key not in have:
                        out.append(f"convergence: {resource} {key} in the "
                                   f"store but missing from an informer "
                                   f"cache")
                    elif key not in want:
                        out.append(f"convergence: informer cache holds "
                                   f"ghost {resource} {key} the store "
                                   f"does not")
                    elif want[key] != have[key]:
                        out.append(f"convergence: {resource} {key} at rv "
                                   f"{have[key]} in an informer cache vs "
                                   f"{want[key]} in the store")
        if self.scheduler is None:
            return out
        pods = self.client.pods().list(namespace=None)
        bound = {p.metadata.key(): p.spec.node_name for p in pods
                 if p.spec.node_name
                 and p.status.phase not in ("Succeeded", "Failed")}
        cache = self.scheduler.cache
        with cache.lock:
            cached = {k: p.spec.node_name
                      for k, p in cache._pod_states.items()}
            assumed = set(cache._assumed)
        for key in sorted(set(bound) | set(cached)):
            if key not in cached:
                out.append(f"convergence: bound pod {key} (node "
                           f"{bound[key]}) missing from the scheduler "
                           f"cache")
            elif key not in bound:
                if key in assumed:
                    continue  # in-flight assume; check_cache_assumes rules
                out.append(f"convergence: scheduler cache charges {key} "
                           f"to node {cached[key]} but the store has no "
                           f"such bind")
            elif bound[key] != cached[key]:
                out.append(f"convergence: {key} bound to {bound[key]} in "
                           f"the store vs {cached[key]} in the scheduler "
                           f"cache")
        queued = {p.metadata.key()
                  for p in self.scheduler.queue.pending_pods()}
        responsible = getattr(self.scheduler, "_responsible",
                              lambda p: True)
        for p in pods:
            if p.spec.node_name or p.status.phase in ("Succeeded", "Failed"):
                continue
            if p.metadata.deletion_timestamp is not None:
                continue
            if not responsible(p):
                continue
            key = p.metadata.key()
            if key not in queued and key not in assumed:
                out.append(f"convergence: pod {key} is Pending but "
                           f"invisible to the scheduler (not queued, not "
                           f"assumed) — permanently stuck")
        return out

    def _live_nodes(self) -> dict:
        return {n.metadata.name: n for n in self.client.nodes().list()}

    def check_gang_atomicity(self) -> List[str]:
        out: List[str] = []
        pods = self.client.pods().list(namespace=None)
        for pg in self.client.pod_groups().list(namespace=None):
            ns, name = pg.metadata.namespace, pg.metadata.name
            members = [p for p in pods
                       if p.metadata.namespace == ns
                       and pod_group_name(p) == name]
            bound = [p for p in members
                     if p.spec.node_name
                     and p.status.phase not in ("Succeeded", "Failed")]
            mm = max(1, pg.spec.min_member)
            if 0 < len(bound) < mm:
                out.append(
                    f"gang-atomicity: PodGroup {ns}/{name} partially "
                    f"bound ({len(bound)}/{mm}): "
                    f"{sorted(p.metadata.name for p in bound)}")
        return out

    def check_cache_assumes(self) -> List[str]:
        out: List[str] = []
        nodes = self._live_nodes()
        from ..state.store import NotFoundError
        for pod in self.scheduler.cache.assumed_pods():
            key = pod.metadata.key()
            if pod.spec.node_name not in nodes:
                out.append(f"cache-assume: pod {key} assumed on deleted "
                           f"node {pod.spec.node_name}")
            try:
                self.client.pods(pod.metadata.namespace).get(
                    pod.metadata.name)
            except NotFoundError:
                out.append(f"cache-assume: pod {key} assumed but no "
                           f"longer exists in the store")
        return out

    def check_gang_reservations(self) -> List[str]:
        gang = getattr(self.scheduler, "gang", None)
        if gang is None:
            return []
        out: List[str] = []
        nodes = self._live_nodes()
        for gkey, pod_key, node_name in gang.reservations():
            node = nodes.get(node_name)
            if node is None:
                out.append(f"gang-reservation: {gkey} member {pod_key} "
                           f"reserved on deleted node {node_name}")
                continue
            dead = [t.key for t in node.spec.taints
                    if t.key in _DEAD_TAINTS and t.effect == "NoExecute"]
            if dead:
                out.append(f"gang-reservation: {gkey} member {pod_key} "
                           f"reserved on dead node {node_name} "
                           f"(taints: {dead})")
        return out

    def check_wal_replay(self) -> List[str]:
        store = self.client.store
        store.flush_wal()  # deferred records must be on disk first
        want = store.contents()
        got = wal_digest(self.wal_path)
        out: List[str] = []
        for key in sorted(set(want) | set(got)):
            if key not in got:
                out.append(f"wal-replay: live object {key} missing from "
                           f"the journal")
            elif key not in want:
                out.append(f"wal-replay: journal resurrects deleted "
                           f"object {key} at rv {got[key]}")
            elif want[key] != got[key]:
                out.append(f"wal-replay: {key} at rv {got[key]} in the "
                           f"journal vs {want[key]} live")
        return out
