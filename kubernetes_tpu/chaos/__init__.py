"""Chaos engineering for the control plane — deterministic fault injection.

The subsystem has three layers:

  - injector.py: `FaultInjector` (the seeded fault oracle + event log) and
    `ChaosClient` (a state.client.Client whose mutating verbs consult the
    injector before touching the store) — API errors, apiserver
    partitions, node crashes, and heartbeat suppression, every decision a
    pure function of `(seed, step, call signature)`. Wire fault classes
    (request latency, connection resets, watch-stream drops) ride the
    httpclient's injectable transport hook, and `ChaosHTTPClient` layers
    the API-error oracle over a real HTTP connection.
  - invariants.py: `InvariantChecker` — sweeps live cluster state for the
    things failure handling must never leave behind: half-bound gangs,
    scheduler-cache assumes or permit reservations referencing dead
    nodes, and a WAL that no longer replays to the live store.
  - harness.py: `ChaosHarness` — an in-process cluster (store + scheduler
    + nodelifecycle + podgroup controller + virtual kubelets) on a
    FakeClock, driven through a seed-derived schedule of chaos actions.
    Two runs with the same seed produce identical event logs.
"""

from .injector import (ChaosClient, ChaosError, ChaosHTTPClient,
                       ChaosResetError, FaultInjector)
from .invariants import InvariantChecker
from .harness import ChaosHarness, ChaosReport

__all__ = ["ChaosClient", "ChaosError", "ChaosHTTPClient",
           "ChaosResetError", "FaultInjector", "InvariantChecker",
           "ChaosHarness", "ChaosReport"]
