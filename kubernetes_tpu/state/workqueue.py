"""Work queues for controllers.

Ref: staging/src/k8s.io/client-go/util/workqueue — Type (dedup + in-flight
tracking), DelayingQueue (time-ordered heap), RateLimitingQueue (per-item
exponential backoff + overall token bucket).
"""

from __future__ import annotations

import heapq
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils.clock import Clock, REAL_CLOCK


class WorkQueue:
    """Dedup FIFO with dirty/processing sets (ref: workqueue/queue.go): an item
    re-added while being processed is re-queued once processing finishes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[Any] = []
        self._dirty = set()
        self._processing = set()
        self._shutting_down = False

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._cond.notify()

    def get(self, block: bool = True, timeout: Optional[float] = None
            ) -> Tuple[Optional[Any], bool]:
        """Returns (item, shutdown)."""
        with self._cond:
            while not self._queue and not self._shutting_down:
                if not block or not self._cond.wait(timeout):
                    if not self._queue and not self._shutting_down:
                        return None, False
            if not self._queue:
                return None, True
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item, False

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def len(self) -> int:
        with self._lock:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._lock:
            return self._shutting_down


class DelayingQueue(WorkQueue):
    """add_after support via a waiting heap drained by a background thread
    (ref: workqueue/delaying_queue.go waitingLoop)."""

    def __init__(self, clock: Clock = REAL_CLOCK):
        super().__init__()
        self._clock = clock
        self._waiting: List[Tuple[float, int, Any]] = []
        self._seq = 0
        self._wait_cond = threading.Condition()
        self._thread = threading.Thread(target=self._waiting_loop, daemon=True)
        self._thread.start()

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._wait_cond:
            self._seq += 1
            heapq.heappush(self._waiting, (self._clock.now() + delay, self._seq, item))
            self._wait_cond.notify()

    def _waiting_loop(self) -> None:
        while True:
            with self._wait_cond:
                if self.shutting_down:
                    return
                now = self._clock.now()
                ready = []
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    ready.append(item)
                timeout = (self._waiting[0][0] - now) if self._waiting else 0.2
            for item in ready:
                self.add(item)
            with self._wait_cond:
                if self.shutting_down:
                    return
                self._wait_cond.wait(min(max(timeout, 0.001), 0.2))

    def shutdown(self) -> None:
        super().shutdown()
        with self._wait_cond:
            self._wait_cond.notify_all()


class RateLimiter:
    """Per-item exponential backoff (ref: workqueue/default_rate_limiters.go
    ItemExponentialFailureRateLimiter: base*2^failures capped)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def retries(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue(DelayingQueue):
    def __init__(self, rate_limiter: Optional[RateLimiter] = None,
                 clock: Clock = REAL_CLOCK):
        super().__init__(clock)
        self.rate_limiter = rate_limiter or RateLimiter()

    def add_rate_limited(self, item: Any) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Any) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self.rate_limiter.retries(item)
