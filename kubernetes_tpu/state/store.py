"""Versioned, watchable object store — the L0/L3 storage collapsed in-process.

Semantics follow the reference's etcd3 store + watch cache:
  - one monotonically increasing cluster-wide resourceVersion (etcd revision)
    stamped on every write (ref: etcd3/store.go Create/GuaranteedUpdate)
  - optimistic concurrency: update/delete may require the caller's
    resourceVersion to match (CAS, ref: GuaranteedUpdate preconditions)
  - watches resume from any resourceVersion held in the bounded event history
    window (ref: storage/cacher/cacher.go watchCache), delivered in order
  - per-(resource, namespace) keying like etcd key paths

Thread-safe; watchers receive events on their own unbounded queues so a slow
consumer never blocks writers (the reference's buffered watch channels +
terminate-slow-watcher policy is unnecessary in-process).

Copy discipline (the client-go contract, shared_informer.go doc: "objects
returned from the store MUST be treated as read-only"): the store keeps one
canonical frozen object per key. Writes deep-copy IN (the caller keeps
ownership of what it passed); reads, watch events, and returns share the
canonical object WITHOUT copying. Mutating anything the store handed out is
a bug — mutate a deepcopy_obj() and write it back.

A C++ MVCC backend (native/) can replace the dict storage behind the same
interface; this python implementation is the semantic reference.
"""

from __future__ import annotations

import queue
import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..api import serde

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


class ConflictError(Exception):
    """resourceVersion precondition failed (HTTP 409 analog)."""


class NotFoundError(KeyError):
    """object does not exist (HTTP 404 analog)."""


class AlreadyExistsError(Exception):
    """create of an existing key (HTTP 409 AlreadyExists analog)."""


class ExpiredError(Exception):
    """watch resourceVersion fell out of the history window (HTTP 410 Gone)."""


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | BOOKMARK
    object: Any
    resource_version: int = 0
    #: optional compact form of a known-shape mutation (today: binds —
    #: {"namespace","name","node","ts"}). In-process consumers ignore it
    #: (object is always the full canonical); the HTTP watch serves it to
    #: clients that negotiated slim frames, the way the reference
    #: negotiates protobuf instead of JSON per Accept header
    slim: Any = None


@dataclass
class SlimBindRef:
    """Placeholder object in a WatchEvent decoded from a negotiated slim
    bind frame: the consumer (SharedInformer) materializes the full pod by
    applying `apply_bind_fields` to its cached copy at the previous
    revision. Only ever produced by the HTTP watch client — store-level
    watches always carry full canonical objects."""
    namespace: str
    name: str
    node: str
    ts: Optional[str]
    rv: int


class Watch:
    """A single watch subscription; iterate or poll via queue."""

    def __init__(self, store: "Store", wid: int):
        self._store = store
        self._id = wid
        self.events: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = False

    def stop(self):
        if not self._stopped:
            self._stopped = True
            self._store._remove_watch(self._id)
            self.events.put(None)

    def __iter__(self):
        while True:
            ev = self.events.get()
            if ev is None:
                return
            yield ev


class Store:
    """The cluster state store. Keys are (resource, namespace, name).

    `wal_path` enables durability: every committed mutation is journaled
    to a write-ahead log (state/wal.py; native append path in
    native/walcore.cc) and replayed on construction — the etcd analog of
    L0 persistence. `wal_sync=True` fdatasyncs per transaction."""

    HISTORY_WINDOW = 4096  # retained events for watch resume (watchCache capacity)

    def __init__(self, wal_path: Optional[str] = None,
                 wal_sync: bool = False, metrics=None):
        self._lock = threading.RLock()
        self._rv = 0
        # resource -> {(namespace, name) -> (obj, rv)}
        self._data: Dict[str, Dict[Tuple[str, str], Tuple[Any, int]]] = {}
        # ring of (rv, resource, WatchEvent); trimmed to HISTORY_WINDOW at
        # publish (O(1) popleft, honors runtime window changes)
        self._history: Deque[Tuple[int, str, WatchEvent]] = deque()
        self._watches: Dict[int, Tuple[str, Optional[str], Watch]] = {}
        self._next_watch_id = 0
        self._uid_counter = 0
        self._wal = None
        #: RobustnessMetrics (optional): WAL append-error and replay
        #: recovery accounting ride the owner's registry
        self.metrics = metrics
        #: the last replay's accounting (state/wal.WalRecovery), None
        #: until a WAL-backed store has replayed at least once
        self.wal_recovery = None
        if wal_path is not None:
            self._replay_wal(wal_path)
            from .wal import WalWriter
            # deferred mode (sync off): record encoding + file writes run
            # on the WAL worker, off the write path's latency. wal_sync
            # keeps the synchronous writer so flush() can fdatasync per txn
            self._wal = WalWriter(wal_path, sync=wal_sync,
                                  deferred=not wal_sync,
                                  encoder=serde.encode_cached,
                                  metrics=metrics)

    # ---------------------------------------------------------------- wal

    def _replay_wal(self, path: str) -> None:
        from ..runtime.scheme import SCHEME
        from .wal import load_wal_ex
        recovery = load_wal_ex(path)
        self.wal_recovery = recovery
        if self.metrics is not None:
            self.metrics.wal_recovery_records_replayed.inc(
                recovery.records_replayed)
            self.metrics.wal_recovery_records_dropped.inc(
                recovery.records_dropped)
            self.metrics.wal_recovery_truncated_bytes.inc(
                recovery.truncated_bytes)
        records, clean_offset = recovery.records, recovery.clean_offset
        for rec in records:
            if rec["op"] == "META":
                # compaction high-water marker: restores the true _rv even
                # when the highest-rv writes were deletes or compacted away
                # (etcd revisions never regress across snapshot+restart)
                self._rv = max(self._rv, rec["rv"])
                self._uid_counter = max(self._uid_counter, rec.get("uc", 0))
                continue
            if rec["op"] in ("BIND", "BINDS"):
                # slim bind record(s): re-derive the bound pods from the
                # state the log built so far (their PUTs necessarily
                # precede) — byte-identical to the originals via
                # apply_bind_fields. "BINDS" is the group-commit form: one
                # record per bind transaction, each entry carrying its own
                # rv; "BIND" is the legacy one-record-per-pod shape.
                from .client import apply_bind_fields
                bucket = self._data.setdefault(rec["resource"], {})
                if rec["op"] == "BIND":
                    entries = [dict(rec["object"], rv=rec["rv"])]
                else:
                    entries = rec["object"]["binds"]
                for b in entries:
                    key = (b.get("namespace", ""), b["name"])
                    cur = bucket.get(key)
                    if cur is not None:
                        new = serde.shallow_bind_clone(cur[0])
                        apply_bind_fields(new, b["node"], b.get("ts"))
                        new.metadata.resource_version = str(b["rv"])
                        bucket[key] = (new, b["rv"])
                self._rv = max(self._rv, rec["rv"])
                continue
            cls = SCHEME.type_for_resource(rec["resource"])
            if cls is None:
                if rec["op"] == "DELETE":
                    # tombstone for an unregistered kind (CRD cascade
                    # writes instance deletes AFTER the CRD's own DELETE):
                    # removal needs only the record's metadata, not a type
                    md = (rec.get("object") or {}).get("metadata", {})
                    bucket = self._data.get(rec["resource"])
                    if bucket is not None:
                        bucket.pop((md.get("namespace", ""),
                                    md.get("name", "")), None)
                    self._rv = max(self._rv, rec["rv"])
                continue
            obj = serde.decode(cls, rec["object"])
            if rec["resource"] == "customresourcedefinitions":
                # keep the dynamic type table in step with the log: CR
                # instance records only decode while their CRD's PUT has
                # been seen and its DELETE has not (the server cascades
                # instance deletes before the CRD's, preserving order)
                from ..runtime.crd import register_crd, unregister_crd
                try:
                    if rec["op"] == "DELETE":
                        unregister_crd(obj)
                    else:
                        register_crd(obj)
                except ValueError:
                    pass
            key = (obj.metadata.namespace, obj.metadata.name)
            bucket = self._data.setdefault(rec["resource"], {})
            if rec["op"] == "DELETE":
                bucket.pop(key, None)
            else:
                bucket[key] = (obj, rec["rv"])
            self._rv = max(self._rv, rec["rv"])
            self._uid_counter = max(self._uid_counter, rec.get("uc", 0))
        # drop any torn tail BEFORE the writer opens in append mode, or
        # post-restart records hide behind the torn bytes and the next
        # replay loses them
        if os.path.exists(path) and os.path.getsize(path) > clean_offset:
            with open(path, "rb+") as f:
                f.truncate(clean_offset)

    def _journal(self, op: str, resource: str, obj: Any, rv: int) -> None:
        """Called under the lock after a committed mutation. The frozen
        object is handed to the writer as-is; encoding (serde.encode_cached
        — shared with the watch/list fan-out for the same revision) runs on
        the WAL worker in deferred mode, immediately otherwise."""
        if self._wal is not None:
            self._wal.append(op, resource, rv, obj,
                             uid_counter=self._uid_counter)

    def _wal_commit(self) -> None:
        if self._wal is not None:
            self._wal.flush()

    def flush_wal(self) -> None:
        """Wait until every journaled record is in the file. In deferred
        mode the worker lags the write path by design (a process crash can
        lose that tail, same class as the OS buffer in non-sync mode);
        graceful shutdown, compaction, and tests drain through here."""
        if self._wal is not None:
            self._wal.drain()

    def compact(self) -> None:
        """Rewrite the log as one PUT per live object (snapshot analog)."""
        if self._wal is None:
            return
        from .wal import WalWriter
        with self._lock:
            path = self._wal.path
            sync = self._wal.sync
            self._wal.close()
            tmp = path + ".compact"
            if os.path.exists(tmp):
                os.remove(tmp)
            w = WalWriter(tmp, sync=True)
            # persist the resourceVersion high-water mark FIRST: the live
            # objects' max rv undercounts whenever the newest writes were
            # deletes, and a regressed counter would reissue rvs that
            # watchers/CAS callers already observed
            w.append("META", "", self._rv, None,
                     uid_counter=self._uid_counter)
            for resource, bucket in self._data.items():
                for (ns, name), (obj, rv) in bucket.items():
                    w.append("PUT", resource, rv, serde.encode_cached(obj),
                             uid_counter=self._uid_counter)
            w.flush()
            w.close()
            os.replace(tmp, path)
            self._wal = WalWriter(path, sync=sync, deferred=not sync,
                                  encoder=serde.encode_cached,
                                  metrics=self.metrics)

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                self._wal.close()
                self._wal = None

    def restart(self, torn: int = 0) -> int:
        """Crash-restart the store process in place: drain and close the
        journal, drop ALL in-memory state (objects, watch history, live
        watch subscriptions), and rebuild by replaying the WAL — the
        etcd-restart analog the chaos harness drives mid-run.

        Every live watcher's stream ends (a clean close, no error): store
        clients must reconnect, and because the event history dies with
        the process, a resume at any rv below the replayed head answers
        ExpiredError — exactly the relist storm a real apiserver restart
        causes. Requires a wal_path'd store; a WAL-less restart would be
        data loss, not recovery, and raises instead.

        `torn=N` chops the last N journal records between the close and
        the replay (state/wal.tear_wal) — the disk lost the tail, the
        replayed rv clock REGRESSES below what watchers and caches have
        observed, and any resume at a now-future rv answers ExpiredError
        so clients relist and prune ghosts (watch() enforces this for
        every regressed store). torn=0 keeps the drained-tail guarantee
        of the wal_sync deployment. Returns the number of records
        actually torn (the journal may hold fewer than requested)."""
        with self._lock:
            if self._wal is None:
                raise RuntimeError(
                    "store restart without a WAL would lose everything; "
                    "construct the Store with wal_path to use restart()")
            path = self._wal.path
            sync = self._wal.sync
            self._wal.flush()
            self._wal.close()
            self._wal = None
            actually_torn = 0
            if torn > 0:
                from .wal import tear_wal
                actually_torn = tear_wal(path, torn)
            # sever every live stream: each watcher sees its queue end
            watches = list(self._watches.values())
            self._watches.clear()
            for _res, _ns, w in watches:
                w._stopped = True
                w.events.put(None)
            self._data.clear()
            self._history.clear()
            self._rv = 0
            self._uid_counter = 0
            self._replay_wal(path)
            from .wal import WalWriter
            self._wal = WalWriter(path, sync=sync, deferred=not sync,
                                  encoder=serde.encode_cached,
                                  metrics=self.metrics)
            return actually_torn

    # ------------------------------------------------------------- writes

    def create(self, resource: str, obj: Any) -> Any:
        with self._lock:
            stored = self._create_locked(resource, obj)
            self._wal_commit()
            self._publish(resource,
                          WatchEvent(ADDED, stored,
                                     int(stored.metadata.resource_version)))
            return stored

    def _create_locked(self, resource: str, obj: Any) -> Any:
        """One create under the held lock — journaled but NOT wal-committed
        or published; the caller batches those."""
        # copy BEFORE any stamping: the caller may be holding a canonical
        # object from get()/list(), which must never be written through
        stored = serde.deepcopy_obj(obj)
        meta = stored.metadata
        if meta.generate_name and not meta.name:
            self._uid_counter += 1
            meta.name = f"{meta.generate_name}{self._uid_counter:x}"
        key = (meta.namespace, meta.name)
        bucket = self._data.setdefault(resource, {})
        # an object pending finalization still owns its key (ref: the
        # apiserver returns 409 AlreadyExists until finalizers clear)
        if key in bucket:
            raise AlreadyExistsError(f"{resource} {key} already exists")
        self._rv += 1
        if not meta.uid:
            self._uid_counter += 1
            meta.uid = f"uid-{self._uid_counter:08x}"
        if meta.creation_timestamp is None:
            from ..utils.clock import now_iso
            meta.creation_timestamp = now_iso()
        if meta.generation == 0 and hasattr(stored, "spec"):
            meta.generation = 1  # ref: registry strategies PrepareForCreate
        meta.resource_version = str(self._rv)
        bucket[key] = (stored, self._rv)
        self._journal("PUT", resource, stored, self._rv)
        return stored

    def create_bulk(self, resource: str, objs: List[Any]) -> List[Any]:
        """N creates under ONE lock acquisition and ONE durability point —
        the write-side analog of bulk_apply. Result slots are the stored
        objects or the Exception that rejected that slot (AlreadyExists);
        accepted items commit even when siblings fail, exactly like N
        independent creates."""
        out: List[Any] = []
        events: List[WatchEvent] = []
        with self._lock:
            for obj in objs:
                try:
                    stored = self._create_locked(resource, obj)
                except Exception as e:
                    out.append(e)
                    continue
                out.append(stored)
                events.append(WatchEvent(
                    ADDED, stored, int(stored.metadata.resource_version)))
            self._wal_commit()
            for ev in events:
                self._publish(resource, ev)
        return out

    def update(self, resource: str, obj: Any, *, enforce_rv: bool = True) -> Any:
        with self._lock:
            meta = obj.metadata
            key = (meta.namespace, meta.name)
            bucket = self._data.setdefault(resource, {})
            existing = bucket.get(key)
            if existing is None:
                raise NotFoundError(f"{resource} {key} not found")
            cur_obj, cur_rv = existing
            if enforce_rv and meta.resource_version and int(meta.resource_version) != cur_rv:
                raise ConflictError(
                    f"{resource} {key}: resourceVersion {meta.resource_version} != {cur_rv}")
            self._rv += 1
            # copy BEFORE stamping (the caller may pass a canonical object)
            stored = serde.deepcopy_obj(obj)
            stored.metadata.resource_version = str(self._rv)
            if not stored.metadata.uid:
                stored.metadata.uid = cur_obj.metadata.uid
            if stored.metadata.creation_timestamp is None:
                stored.metadata.creation_timestamp = \
                    cur_obj.metadata.creation_timestamp
            # spec changes bump metadata.generation (ref: registry strategies
            # PrepareForUpdate; status-only writes keep it). The bind hot path
            # (bulk_apply) intentionally skips this comparison.
            if hasattr(stored, "spec"):
                if stored.spec != cur_obj.spec:
                    stored.metadata.generation = cur_obj.metadata.generation + 1
                else:
                    stored.metadata.generation = cur_obj.metadata.generation
            # removing the last finalizer completes a pending deletion
            # (ref: registry/generic Store.Update deleteCollection path)
            if stored.metadata.deletion_timestamp is not None and \
                    not stored.metadata.finalizers:
                del bucket[key]
                self._journal("DELETE", resource, stored, self._rv)
                self._wal_commit()
                self._publish(resource, WatchEvent(DELETED, stored, self._rv))
                return stored
            bucket[key] = (stored, self._rv)
            self._journal("PUT", resource, stored, self._rv)
            self._wal_commit()
            self._publish(resource, WatchEvent(MODIFIED, stored, self._rv))
            return stored

    def delete(self, resource: str, namespace: str, name: str,
               *, resource_version: Optional[str] = None) -> Any:
        with self._lock:
            key = (namespace, name)
            bucket = self._data.setdefault(resource, {})
            existing = bucket.get(key)
            if existing is None:
                raise NotFoundError(f"{resource} {key} not found")
            cur_obj, cur_rv = existing
            if resource_version is not None and int(resource_version) != cur_rv:
                raise ConflictError(f"{resource} {key}: stale resourceVersion")
            # finalizer semantics: objects with finalizers get a deletion
            # timestamp instead of vanishing (ref: registry/generic
            # Store.Delete). Both paths mutate ONLY metadata fields
            # (deletionTimestamp / resourceVersion), so a shallow shell+
            # metadata clone replaces the former full deepcopy — the frozen
            # source keeps every shared sub-object read-only.
            if cur_obj.metadata.finalizers and cur_obj.metadata.deletion_timestamp is None:
                marked = serde.shallow_meta_clone(cur_obj)
                from ..utils.clock import now_iso
                marked.metadata.deletion_timestamp = now_iso()
                self._rv += 1
                marked.metadata.resource_version = str(self._rv)
                bucket[key] = (marked, self._rv)
                self._journal("PUT", resource, marked, self._rv)
                self._wal_commit()
                self._publish(resource, WatchEvent(MODIFIED, marked, self._rv))
                return marked
            del bucket[key]
            self._rv += 1
            final = serde.shallow_meta_clone(cur_obj)
            final.metadata.resource_version = str(self._rv)
            self._journal("DELETE", resource, final, self._rv)
            self._wal_commit()
            self._publish(resource, WatchEvent(DELETED, final, self._rv))
            return final

    def bulk_apply(self, resource: str,
                   items: List[Tuple[str, str, Callable[[Any], Any]]],
                   copy_fn: Callable[[Any], Any] = serde.deepcopy_obj,
                   slim_fn: Optional[Callable[[Any], Any]] = None,
                   ) -> List[Any]:
        """Apply N read-modify-write mutations under ONE lock acquisition.

        The batched analog of N guaranteed_update calls: the scheduler's bind
        phase turns one-bind-POST-per-pod (ref: scheduler.go:549 -> pod/rest
        BindingREST) into a single store transaction. Each (namespace, name,
        mutate) gets a fresh copy of the live object; a mutate may raise to
        skip its item (the error is recorded in the result slot). A caller
        whose mutate only touches known layers may pass a cheaper copy_fn
        (e.g. serde.shallow_bind_clone for the bind subresource).
        """
        out: List[Any] = []
        events: List[Tuple[str, WatchEvent]] = []
        #: slim records of this transaction, journaled as ONE group-commit
        #: "BINDS" WAL record — one encode + one append per bind batch
        #: instead of one per pod (each entry carries its own rv for replay)
        slim_batch: List[Any] = []
        with self._lock:
            bucket = self._data.setdefault(resource, {})
            for namespace, name, mutate in items:
                key = (namespace, name)
                existing = bucket.get(key)
                if existing is None:
                    out.append(NotFoundError(f"{resource} {key} not found"))
                    continue
                try:
                    updated = mutate(copy_fn(existing[0]))
                except Exception as e:  # mutate rejected the object
                    out.append(e)
                    continue
                self._rv += 1
                updated.metadata.resource_version = str(self._rv)
                if updated.metadata.deletion_timestamp is not None and \
                        not updated.metadata.finalizers:
                    del bucket[key]
                    self._journal("DELETE", resource, updated, self._rv)
                    events.append((resource,
                                   WatchEvent(DELETED, updated, self._rv)))
                else:
                    bucket[key] = (updated, self._rv)
                    slim = slim_fn(updated) if slim_fn is not None else None
                    if slim is not None:
                        # known-shape mutation: journal the compact record
                        # (replayed via apply_bind_fields) and hand the
                        # watch layer the same dict — no full-pod encode
                        # on either path
                        if self._wal is not None:
                            rec = dict(slim)
                            rec["rv"] = self._rv
                            slim_batch.append(rec)
                    else:
                        self._journal("PUT", resource, updated, self._rv)
                    events.append((resource,
                                   WatchEvent(MODIFIED, updated, self._rv,
                                              slim=slim)))
                out.append(updated)
            if slim_batch:
                self._wal.append("BINDS", resource, self._rv,
                                 {"binds": slim_batch},
                                 uid_counter=self._uid_counter)
            self._wal_commit()  # one durability point per transaction
            for res, ev in events:
                self._publish(res, ev)
        return out

    #: False on the base store; a follower's store (replication.py
    #: ReadOnlyStore) overrides to True until promoted — the apiserver
    #: answers 503 on writes against a read-only store
    read_only = False

    def _follow_clock_locked(self, rv: int) -> None:
        """Advance the replica's clock to the primary's. The uid/name
        counter tracks 2*rv: the primary bumps it at most twice per
        create (generated name + uid) while rv advances at least once,
        so counter <= 2*rv there — overshooting keeps every post-promote
        generated suffix/uid above anything the primary ever minted."""
        self._rv = max(self._rv, rv)
        self._uid_counter = max(self._uid_counter, 2 * rv)

    def apply_replicated(self, resource: str, obj: Any, rv: int,
                         deleted: bool = False) -> None:
        """Apply one event from a PRIMARY store at the primary's
        resourceVersion (the replication follower's write path — see
        state/replication.py). The replica's clock follows the primary's
        so a promote continues the same CAS timeline; local watches fire
        so read clients of the replica see live events."""
        with self._lock:
            bucket = self._data.setdefault(resource, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            self._follow_clock_locked(rv)
            if deleted:
                existed = bucket.pop(key, None)
                if existed is not None:
                    self._journal("DELETE", resource, obj, rv)
                    self._wal_commit()
                    self._publish(resource, WatchEvent(DELETED, obj, rv))
                return
            cur = bucket.get(key)
            if cur is not None and cur[1] >= rv:
                return  # stale or duplicate frame (relist overlap)
            bucket[key] = (obj, rv)
            self._journal("PUT", resource, obj, rv)
            self._wal_commit()
            self._publish(resource, WatchEvent(
                ADDED if cur is None else MODIFIED, obj, rv))

    def replace_replicated(self, resource: str, objs: List[Any],
                           rv: int) -> None:
        """Apply a full primary LIST as a replace (the reflector's
        Replace semantics): upsert every listed object and PRUNE local
        keys the primary no longer has — an object deleted during a
        watch outage must not survive as a ghost on the replica.

        A listed object at a rv BELOW the local copy's is accepted, not
        skipped: the primary's consistent LIST is authoritative, and a
        lower rv means the primary REGRESSED under the follower (torn-WAL
        recovery truncated history the follower already applied). Keeping
        the lost future would fork the replica from its primary forever —
        the etcd-learner analog is a snapshot resync after leader log
        truncation. Only an rv-identical copy is skipped (no change).
        The replica's own rv clock never regresses (_follow_clock_locked
        keeps the high-water mark), so a later promote still mints rvs
        above anything EITHER timeline handed out."""
        with self._lock:
            bucket = self._data.setdefault(resource, {})
            listed = set()
            for obj in objs:
                key = (obj.metadata.namespace, obj.metadata.name)
                listed.add(key)
                obj_rv = int(obj.metadata.resource_version or 0)
                cur = bucket.get(key)
                if cur is not None and cur[1] == obj_rv:
                    continue
                bucket[key] = (obj, obj_rv)
                self._journal("PUT", resource, obj, obj_rv)
                self._publish(resource, WatchEvent(
                    ADDED if cur is None else MODIFIED, obj, obj_rv))
            for key in [k for k in bucket if k not in listed]:
                gone, gone_rv = bucket.pop(key)
                self._journal("DELETE", resource, gone, rv)
                self._publish(resource, WatchEvent(DELETED, gone, rv))
            self._follow_clock_locked(rv)
            self._wal_commit()

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          mutate: Callable[[Any], Any], retries: int = 16,
                          copy_fn: Callable[[Any], Any] = serde.deepcopy_obj,
                          ) -> Any:
        """CAS retry loop (ref: etcd3/store.go GuaranteedUpdate :238).
        `copy_fn` is the read-side copy handed to `mutate`: callers whose
        mutator only touches known layers (the bind subresource) pass
        serde.shallow_bind_clone and skip the full deepcopy."""
        for _ in range(retries):
            # get() returns the frozen canonical object; mutate a copy
            updated = mutate(copy_fn(self.get(resource, namespace, name)))
            try:
                return self.update(resource, updated)
            except ConflictError:
                continue
        raise ConflictError(f"{resource} {namespace}/{name}: too many conflicts")

    # ------------------------------------------------------------- reads

    def get(self, resource: str, namespace: str, name: str) -> Any:
        with self._lock:
            existing = self._data.get(resource, {}).get((namespace, name))
            if existing is None:
                raise NotFoundError(f"{resource} {namespace}/{name} not found")
            return existing[0]  # frozen canonical object: read-only

    def list(self, resource: str, namespace: Optional[str] = None,
             label_selector: Optional[Callable[[Any], bool]] = None
             ) -> Tuple[List[Any], int]:
        """Returns (items, listResourceVersion)."""
        with self._lock:
            out = []
            for (ns, _), (obj, _rv) in sorted(self._data.get(resource, {}).items()):
                if namespace is not None and ns != namespace:
                    continue
                if label_selector is not None and not label_selector(obj):
                    continue
                out.append(obj)  # frozen canonical objects: read-only
            return out, self._rv

    def count(self, resource: str) -> int:
        """O(1) object count — cheap emptiness checks for per-request
        admission gates (webhook configs, priority classes)."""
        with self._lock:
            return len(self._data.get(resource, ()))

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    def contents(self) -> Dict[Tuple[str, str, str], int]:
        """{(resource, namespace, name): rv} for every live object — the
        comparison surface for WAL-replay and replication verification
        (chaos/invariants.py checks the journal reconstructs exactly
        this map)."""
        with self._lock:
            return {(resource, ns, name): rv
                    for resource, bucket in self._data.items()
                    for (ns, name), (_obj, rv) in bucket.items()}

    # ------------------------------------------------------------- watch

    def watch(self, resource: str, namespace: Optional[str] = None,
              resource_version: Optional[int] = None) -> Watch:
        """Subscribe to events after `resource_version` (exclusive). None means
        'from now'. Raises ExpiredError if rv is older than the history window
        (clients must relist, ref: 410 Gone -> Reflector relist)."""
        with self._lock:
            self._next_watch_id += 1
            w = Watch(self, self._next_watch_id)
            if resource_version is not None and resource_version > self._rv:
                # a FUTURE rv: no honest client can hold one, so the
                # store's clock must have REGRESSED under this watcher
                # (torn-WAL recovery). Answering "from now" would let the
                # client keep ghost objects the store lost — force the
                # 410 relist instead (ref: apiserver's invalid-rv watch
                # handling; etcd answers ErrFutureRev)
                raise ExpiredError(
                    f"resourceVersion {resource_version} is ahead of the "
                    f"store ({self._rv}): state regressed; relist")
            if resource_version is not None and resource_version < self._rv:
                oldest = self._history[0][0] if self._history else self._rv + 1
                if resource_version + 1 < oldest and resource_version < self._rv:
                    # rv no longer replayable unless it covers everything retained
                    if not (not self._history and resource_version >= self._rv):
                        raise ExpiredError(
                            f"resourceVersion {resource_version} is too old "
                            f"(oldest retained: {oldest})")
                for rv, res, ev in self._history:
                    if rv > resource_version and res == resource:
                        if namespace is None or ev.object.metadata.namespace == namespace:
                            w.events.put(ev)
            self._watches[w._id] = (resource, namespace, w)
            return w

    def _publish(self, resource: str, ev: WatchEvent) -> None:
        # the event shares the canonical frozen object: consumers must not
        # mutate delivered objects (the client-go informer contract)
        self._history.append((ev.resource_version, resource, ev))
        while len(self._history) > self.HISTORY_WINDOW:
            self._history.popleft()
        if self._watches:
            for res, ns, w in list(self._watches.values()):
                if res == resource and (ns is None or
                                        ev.object.metadata.namespace == ns):
                    w.events.put(ev)

    def _remove_watch(self, wid: int) -> None:
        with self._lock:
            self._watches.pop(wid, None)
